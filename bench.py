"""End-to-end reconcile-throughput benchmark under a realistic AWS model.

The reference publishes no benchmark numbers (BASELINE.md: no
``benchmarks/`` dir, no ``Benchmark*`` funcs, no perf claims), so this
measures the framework's own headline capability — full watch →
informer → queue → reconcile → cloud-ensure convergence of the
GlobalAccelerator AND Route53 controllers together — and reports
``vs_baseline`` against the reference's implicit operating point
(1 worker per queue, ``cmd/controller/controller.go:32``; client-go's
fixed 10 qps / 100 burst enqueue bucket; the O(N)+1 ListTags discovery
scan on every reconcile, ``global_accelerator.go:87-110``).

The fake cloud is SHAPED, not uniform:

- **Asymmetric per-operation latency.**  Every operation of all three
  API families (GlobalAccelerator, ELBv2, Route53 — endpoint-group and
  record-change ops included) sleeps a per-op latency taken from
  real-world control-plane behavior (CreateAccelerator is the slowest
  by an order of magnitude; List*/Describe* are fast).  Latencies are
  scaled to 1/10 of their real-world values so the bench completes in
  minutes; quotas are scaled x10 to match, so the RELATIVE pressure
  (which API binds, how much concurrency pays) is preserved under the
  time compression.
- **Per-API throttle quotas.**  Each API family has a token bucket
  (GA mutate, GA read, ELBv2, Route53).  A call that finds the bucket
  empty BLOCKS until admitted — modeling an SDK in standard-retry mode
  pacing itself under ThrottlingException rather than surfacing the
  throttle to the application (our production client does the same:
  ``real_backend.py`` standard retry mode).  The Route53 quota is
  AWS's documented 5 req/s (x10 scale).

The workload drives every family: each Service carries both the
GA-managed annotation and a ``route53-hostname`` annotation resolving
into one of 10 hosted zones, so convergence requires N accelerator
chains (accelerator + listener + endpoint group) AND 2N Route53
records (atomic TXT+A pair per service).

The baseline is measured at N_BASELINE=100 services because the
reference operating point's O(N) tag-scan per reconcile makes serial
convergence at N=1000 intractable (hours).  Comparing per-service
rates FAVORS the baseline: its rate degrades superlinearly with N, so
vs_baseline understates the gap at N=1000.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"detail"} where detail carries per-controller p50/p99 per-item
reconcile latency (via the reconcile loop's sync-duration observer
seam), the steady-state AWS-call rate measured over one full 30 s
resync cycle after convergence, per-op AWS call counts, and the
latency/quota model itself so movement is auditable.
"""

import json
import os
import threading
import time

from agac_tpu import klog
from agac_tpu.cloudprovider.aws.cache import DiscoveryCache, HostedZoneCache
from agac_tpu.apis import (
    AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION,
    AWS_LOAD_BALANCER_TYPE_ANNOTATION,
    ROUTE53_HOSTNAME_ANNOTATION,
)
from agac_tpu.cloudprovider.aws import AWSDriver, FakeAWSBackend
from agac_tpu.cluster import FakeCluster, LoadBalancerIngress, ObjectMeta, Service, ServicePort
from agac_tpu.cluster.objects import ServiceSpec
from agac_tpu.manager import ControllerConfig, Manager
from agac_tpu.reconcile import (
    BucketRateLimiter,
    add_sync_duration_observer,
    remove_sync_duration_observer,
)
from agac_tpu.controllers import (
    EndpointGroupBindingConfig,
    GlobalAcceleratorConfig,
    Route53Config,
)

N_SERVICES = int(os.environ.get("AGAC_BENCH_N", "1000"))
N_BASELINE = int(os.environ.get("AGAC_BENCH_N_BASELINE", "100"))
N_ZONES = 10
TUNED_WORKERS = int(os.environ.get("AGAC_BENCH_WORKERS", "32"))
RESYNC_PERIOD = 30.0  # the reference's informer resync default
STEADY_WINDOW = RESYNC_PERIOD  # one full resync cycle
DEADLINE = 900.0

# Time compression: real-world latencies / LATENCY_SCALE, quotas
# x LATENCY_SCALE — same shape, 1/10 the wall clock.
LATENCY_SCALE = 10.0

# Real-world control-plane latencies (seconds) before scaling.
# Create/Update/Delete on Global Accelerator are slow async control
# operations; reads are fast; Route53 ChangeResourceRecordSets commits
# a transaction.  Shape, not vendor-measured precision, is the point:
# the slowest op is ~15x the fastest and mutates cost multiples of
# reads, so concurrency and caching are rewarded the way they are
# against the real control plane.
REAL_LATENCY = {
    # GlobalAccelerator mutating
    "create_accelerator": 1.5,
    "update_accelerator": 1.0,
    "delete_accelerator": 1.0,
    "create_listener": 0.5,
    "update_listener": 0.5,
    "delete_listener": 0.5,
    "create_endpoint_group": 0.5,
    "update_endpoint_group": 0.5,
    "delete_endpoint_group": 0.5,
    "add_endpoints": 0.3,
    "remove_endpoints": 0.3,
    "tag_resource": 0.2,
    # GlobalAccelerator reads
    "list_accelerators": 0.3,
    "describe_accelerator": 0.2,
    "list_tags_for_resource": 0.1,
    "list_listeners": 0.15,
    "list_endpoint_groups": 0.15,
    "describe_endpoint_group": 0.15,
    # ELBv2
    "describe_load_balancers": 0.2,
    # Route53
    "list_hosted_zones": 0.2,
    "list_hosted_zones_by_name": 0.2,
    "list_resource_record_sets": 0.25,
    "change_resource_record_sets": 0.5,
}

# API family -> (sustained requests/sec, burst) AFTER scaling.
# Real-world: GA mutate ~5/s, GA read ~20/s, ELBv2 describe ~10/s,
# Route53 5/s (the one AWS documents).
QUOTAS = {
    "ga_mutate": (50.0, 100),
    "ga_read": (200.0, 400),
    "elbv2": (100.0, 200),
    "route53": (50.0, 100),
}

OP_FAMILY = {
    "create_accelerator": "ga_mutate",
    "update_accelerator": "ga_mutate",
    "delete_accelerator": "ga_mutate",
    "create_listener": "ga_mutate",
    "update_listener": "ga_mutate",
    "delete_listener": "ga_mutate",
    "create_endpoint_group": "ga_mutate",
    "update_endpoint_group": "ga_mutate",
    "delete_endpoint_group": "ga_mutate",
    "add_endpoints": "ga_mutate",
    "remove_endpoints": "ga_mutate",
    "tag_resource": "ga_mutate",
    "list_accelerators": "ga_read",
    "describe_accelerator": "ga_read",
    "list_tags_for_resource": "ga_read",
    "list_listeners": "ga_read",
    "list_endpoint_groups": "ga_read",
    "describe_endpoint_group": "ga_read",
    "describe_load_balancers": "elbv2",
    "list_hosted_zones": "route53",
    "list_hosted_zones_by_name": "route53",
    "list_resource_record_sets": "route53",
    "change_resource_record_sets": "route53",
}


class TokenBucket:
    """Blocking facade over the framework's own ``BucketRateLimiter``
    (one canonical token-bucket implementation): ``acquire`` reserves
    a token and sleeps until its admission time — FIFO-fair under
    contention, sustained rate exactly ``rate`` once the burst is
    spent."""

    def __init__(self, rate: float, burst: int):
        self._limiter = BucketRateLimiter(qps=rate, burst=burst)
        self._stat_lock = threading.Lock()
        self.throttled_waits = 0  # acquisitions that had to wait

    def acquire(self) -> None:
        wait = self._limiter.when(None)
        if wait > 0:
            with self._stat_lock:
                self.throttled_waits += 1
            time.sleep(wait)


class ShapedAWS(FakeAWSBackend):
    """FakeAWSBackend with asymmetric per-op latency and per-API-family
    blocking throttle quotas on EVERY operation, plus per-op counters
    for call-rate accounting."""

    _SHAPED = frozenset(REAL_LATENCY)

    def __init__(self, *args, **kwargs):
        # a 1000-accelerator fleet runs with raised service quotas in
        # real accounts too; every other documented invariant (name
        # shapes, port ranges, per-listener/group quotas, change-batch
        # limits) stays enforced at AWS defaults
        kwargs.setdefault("quota_accelerators", max(N_SERVICES, N_BASELINE) + 10)
        super().__init__(*args, **kwargs)
        self.op_counts: dict[str, int] = {}
        self._count_lock = threading.Lock()
        self._buckets = {
            family: TokenBucket(rate, burst) for family, (rate, burst) in QUOTAS.items()
        }

    def total_calls(self) -> int:
        with self._count_lock:
            return sum(self.op_counts.values())

    def __getattribute__(self, name):
        attr = super().__getattribute__(name)
        if name.startswith("_") or name not in ShapedAWS._SHAPED:
            return attr
        bucket = super().__getattribute__("_buckets")[OP_FAMILY[name]]
        count_lock = super().__getattribute__("_count_lock")
        op_counts = super().__getattribute__("op_counts")
        latency = REAL_LATENCY[name] / LATENCY_SCALE

        def shaped(*args, **kwargs):
            with count_lock:
                op_counts[name] = op_counts.get(name, 0) + 1
            bucket.acquire()  # throttle admission (SDK-style pacing)
            time.sleep(latency)  # server-side processing time
            return attr(*args, **kwargs)

        return shaped


def make_service(i: int) -> Service:
    lb_host = f"bench{i:04d}-0123456789abcdef.elb.us-west-2.amazonaws.com"
    svc = Service(
        metadata=ObjectMeta(
            name=f"bench{i:04d}",
            namespace=f"ns{i % 10}",
            annotations={
                AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
                AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external",
                ROUTE53_HOSTNAME_ANNOTATION: (
                    f"bench{i:04d}.z{i % N_ZONES}.bench.example.com"
                ),
            },
        ),
        spec=ServiceSpec(
            type="LoadBalancer", ports=[ServicePort(name="http", port=80, protocol="TCP")]
        ),
    )
    svc.status.load_balancer.ingress.append(LoadBalancerIngress(hostname=lb_host))
    return svc


def _percentile(samples: list, q: float) -> float:
    if not samples:
        return 0.0
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, max(0, int(q * len(ordered) + 0.999999) - 1))
    return ordered[idx]


def _controller_of(thread_name: str) -> str:
    for prefix, label in (
        ("global-accelerator", "globalaccelerator"),
        ("route53", "route53"),
        ("endpoint-group", "endpointgroupbinding"),
    ):
        if thread_name.startswith(prefix):
            return label
    return "other"


def run_convergence(
    n: int,
    workers: int,
    cache_ttl: float = 0.0,
    zone_cache_ttl: float = 0.0,
    qps: float = 10.0,
    burst: int = 100,
    measure_steady_state: bool = False,
) -> dict:
    """Create ``n`` annotated services, converge the accelerator chains
    AND Route53 record pairs, and return a result dict with throughput,
    per-controller sync-latency percentiles, AWS call counts, and
    (optionally) the steady-state call rate over one resync cycle."""
    cluster = FakeCluster()
    aws = ShapedAWS()
    cache = DiscoveryCache(ttl=cache_ttl) if cache_ttl > 0 else None
    zone_cache = HostedZoneCache(ttl=zone_cache_ttl) if zone_cache_ttl > 0 else None
    for i in range(n):
        aws.add_load_balancer(
            f"bench{i:04d}",
            "us-west-2",
            f"bench{i:04d}-0123456789abcdef.elb.us-west-2.amazonaws.com",
        )
    zones = [aws.add_hosted_zone(f"z{k}.bench.example.com") for k in range(N_ZONES)]

    latencies: dict[str, list] = {}
    lat_lock = threading.Lock()

    def observer(key: str, seconds: float, err) -> None:
        label = _controller_of(threading.current_thread().name)
        with lat_lock:
            latencies.setdefault(label, []).append(seconds)

    stop = threading.Event()
    config = ControllerConfig(
        global_accelerator=GlobalAcceleratorConfig(
            workers=workers, queue_qps=qps, queue_burst=burst
        ),
        route53=Route53Config(workers=workers, queue_qps=qps, queue_burst=burst),
        endpoint_group_binding=EndpointGroupBindingConfig(
            workers=workers, queue_qps=qps, queue_burst=burst
        ),
    )
    manager = Manager(resync_period=RESYNC_PERIOD)
    add_sync_duration_observer(observer)
    try:
        manager.run(
            cluster,
            config,
            stop,
            cloud_factory=lambda region: AWSDriver(
                aws,
                aws,
                aws,
                discovery_cache=cache,
                zone_cache=zone_cache,
                # the reference requeues every 60 s until the GA
                # controller has converged (route53.go:63-77); scaled
                accelerator_missing_retry=60.0 / LATENCY_SCALE,
            ),
            block=False,
        )
        for i in range(n):
            cluster.create("Service", make_service(i))
        start = time.monotonic()
        deadline = start + DEADLINE

        def converged() -> bool:
            if len(aws.all_accelerator_arns()) < n:
                return False
            records = sum(len(aws.records_in_zone(z.id)) for z in zones)
            return records >= 2 * n

        while time.monotonic() < deadline:
            if converged():
                break
            time.sleep(0.05)
        elapsed = time.monotonic() - start
        if not converged():
            done = len(aws.all_accelerator_arns())
            records = sum(len(aws.records_in_zone(z.id)) for z in zones)
            raise SystemExit(
                f"benchmark did not converge: {done}/{n} accelerators, "
                f"{records}/{2 * n} records"
            )

        steady = None
        if measure_steady_state:
            # Let the convergence tail drain, then count every AWS call
            # over one full resync cycle: the converged level-triggered
            # re-reconcile rate — what the account pays per 30 s for N
            # services of drift verification.
            time.sleep(2.0)
            calls_before = aws.total_calls()
            window_start = time.monotonic()
            time.sleep(STEADY_WINDOW)
            window = time.monotonic() - window_start
            steady = {
                "window_s": round(window, 1),
                "aws_calls": aws.total_calls() - calls_before,
                "aws_calls_per_sec": round((aws.total_calls() - calls_before) / window, 2),
                "resync_period_s": RESYNC_PERIOD,
                # 0 is correct, not a broken probe: resync re-delivers
                # update(old, new) with old == new, and both this
                # framework and the reference skip equal updates
                # (reference controller.go:100-102 reflect.DeepEqual),
                # so a converged fleet is AWS-quiescent between edits
                "note": "converged level-triggered quiescence; equal resync updates are skipped (parity: reference controller.go:100-102)",
            }
    finally:
        remove_sync_duration_observer(observer)
        stop.set()

    with lat_lock:
        sync_latency = {
            label: {
                "p50_s": round(_percentile(vals, 0.50), 4),
                "p99_s": round(_percentile(vals, 0.99), 4),
                "n_syncs": len(vals),
            }
            for label, vals in sorted(latencies.items())
            if label != "other"
        }
    throttled = {
        family: bucket.throttled_waits for family, bucket in aws._buckets.items()
    }
    result = {
        "services_per_sec": round(n / elapsed, 2),
        "zone_cache_ttl_s": zone_cache_ttl,
        "elapsed_s": round(elapsed, 1),
        "n_services": n,
        "workers": workers,
        "queue_qps": qps,
        "queue_burst": burst,
        "discovery_cache_ttl_s": cache_ttl,
        "aws_calls_total": aws.total_calls(),
        "aws_calls_by_op": dict(sorted(aws.op_counts.items())),
        "throttled_acquisitions": throttled,
        "sync_latency": sync_latency,
    }
    if cache is not None:
        result["discovery_cache"] = {"hits": cache.hits, "misses": cache.misses}
    if zone_cache is not None:
        result["zone_cache"] = {"hits": zone_cache.hits, "misses": zone_cache.misses}
    if steady is not None:
        result["steady_state"] = steady
    return result


def main():
    klog.init(verbosity=-1)
    import logging

    logging.getLogger("agac").setLevel(logging.CRITICAL)
    # baseline: the reference's operating point — 1 worker per queue,
    # client-go's fixed 10 qps/100 burst enqueue bucket, full O(N)+1
    # tag-scan discovery on every reconcile (N_BASELINE services; see
    # module docstring for why the subset favors the baseline)
    baseline = run_convergence(N_BASELINE, workers=1, cache_ttl=0.0, qps=10.0, burst=100)
    # measured: this framework's tuned production configuration —
    # concurrent workers, raised enqueue bucket, incremental discovery
    # caches (AGAC_DISCOVERY_CACHE_TTL + AGAC_ZONE_CACHE_TTL) —
    # against the full N.  Under the realistic quota model throughput
    # is GA-mutate-quota-bound and plateaus from 8 workers up (15.49
    # at w=8 → 16.43 at w=32 svc/s, docs/operations.md "Sizing the
    # worker pool"); 32 sits at the plateau top, while the docs
    # recommend 8–16 where p99 matters
    tuned = run_convergence(
        N_SERVICES,
        workers=TUNED_WORKERS,
        # 30 s: with the write journal the cache never masks local
        # writes, so TTL only bounds cross-process staleness — the
        # same 30 s the reference tolerates between informer resyncs
        cache_ttl=30.0,
        zone_cache_ttl=60.0,
        qps=1000.0,
        burst=1000,
        measure_steady_state=True,
    )
    steady = tuned.pop("steady_state")
    print(
        json.dumps(
            {
                "metric": "service_to_accelerator_convergence_throughput",
                "value": tuned["services_per_sec"],
                "unit": "services/sec",
                "vs_baseline": round(
                    tuned["services_per_sec"] / baseline["services_per_sec"], 2
                ),
                "detail": {
                    "workload": (
                        "each Service needs an accelerator+listener+endpoint-group "
                        "chain AND an atomic TXT+A Route53 record pair"
                    ),
                    "baseline": baseline,
                    "tuned": tuned,
                    "steady_state": steady,
                    "latency_model": {
                        "scale": f"real-world seconds / {LATENCY_SCALE:g}; quotas x{LATENCY_SCALE:g}",
                        "real_latency_s": REAL_LATENCY,
                        "quotas_scaled_per_sec": {
                            family: {"rate": rate, "burst": burst_}
                            for family, (rate, burst_) in QUOTAS.items()
                        },
                    },
                },
            }
        )
    )


if __name__ == "__main__":
    main()
