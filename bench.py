"""End-to-end reconcile-throughput benchmark.

The reference publishes no benchmark numbers (BASELINE.md: no
``benchmarks/`` dir, no ``Benchmark*`` funcs, no perf claims), so this
measures the framework's own headline capability — full watch →
informer → queue → reconcile → cloud-ensure convergence — and reports
``vs_baseline`` against the reference's implicit operating point: its
default configuration processes items with 1 worker per queue
(``cmd/controller/controller.go:32``) and is bounded by serial AWS
round trips per reconcile (the N+1 ListTags scan,
``global_accelerator.go:87-110``); with its in-code timings a single
item converges in one reconcile pass, so the baseline proxy here is
this framework run at the reference operating point (workers=1,
client-go default 10 qps/100 burst enqueue bucket, no discovery
cache) — vs_baseline = throughput(tuned) / throughput(reference point)
shows the headroom the rebuild's knobs add on identical fake-cloud
latency: concurrent workers, a tunable enqueue bucket
(--queue-qps/--queue-burst), and the incremental discovery cache.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import threading
import time

from agac_tpu import klog
from agac_tpu.cloudprovider.aws.cache import DiscoveryCache
from agac_tpu.apis import (
    AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION,
    AWS_LOAD_BALANCER_TYPE_ANNOTATION,
)
from agac_tpu.cloudprovider.aws import AWSDriver, FakeAWSBackend
from agac_tpu.cluster import FakeCluster, LoadBalancerIngress, ObjectMeta, Service, ServicePort
from agac_tpu.cluster.objects import ServiceSpec
from agac_tpu.manager import ControllerConfig, Manager
from agac_tpu.controllers import (
    EndpointGroupBindingConfig,
    GlobalAcceleratorConfig,
    Route53Config,
)

N_SERVICES = 150
SIMULATED_AWS_LATENCY = 0.002  # 2 ms per AWS call, applied uniformly


class LatencyAWS(FakeAWSBackend):
    """Fake AWS with a uniform simulated per-call latency so the
    benchmark exercises IO-bound concurrency, not pure Python speed."""

    def __getattribute__(self, name):
        attr = super().__getattribute__(name)
        if name in (
            "list_accelerators",
            "list_tags_for_resource",
            "describe_load_balancers",
            "create_accelerator",
            "create_listener",
            "create_endpoint_group",
            "list_listeners",
            "list_endpoint_groups",
        ):
            def timed(*args, **kwargs):
                time.sleep(SIMULATED_AWS_LATENCY)
                return attr(*args, **kwargs)

            return timed
        return attr


def make_service(i: int) -> Service:
    hostname = f"bench{i:04d}-0123456789abcdef.elb.us-west-2.amazonaws.com"
    svc = Service(
        metadata=ObjectMeta(
            name=f"bench{i:04d}",
            namespace=f"ns{i % 10}",
            annotations={
                AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
                AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external",
            },
        ),
        spec=ServiceSpec(
            type="LoadBalancer", ports=[ServicePort(name="http", port=80, protocol="TCP")]
        ),
    )
    svc.status.load_balancer.ingress.append(LoadBalancerIngress(hostname=hostname))
    return svc


def run_convergence(
    workers: int, cache_ttl: float = 0.0, qps: float = 10.0, burst: int = 100
) -> float:
    """Create N_SERVICES annotated services, return services/sec until
    every accelerator chain exists."""
    cluster = FakeCluster()
    aws = LatencyAWS()
    cache = DiscoveryCache(ttl=cache_ttl) if cache_ttl > 0 else None
    for i in range(N_SERVICES):
        aws.add_load_balancer(
            f"bench{i:04d}",
            "us-west-2",
            f"bench{i:04d}-0123456789abcdef.elb.us-west-2.amazonaws.com",
        )
    stop = threading.Event()
    config = ControllerConfig(
        global_accelerator=GlobalAcceleratorConfig(
            workers=workers, queue_qps=qps, queue_burst=burst
        ),
        route53=Route53Config(workers=workers, queue_qps=qps, queue_burst=burst),
        endpoint_group_binding=EndpointGroupBindingConfig(
            workers=workers, queue_qps=qps, queue_burst=burst
        ),
    )
    manager = Manager(resync_period=300)
    manager.run(
        cluster,
        config,
        stop,
        cloud_factory=lambda region: AWSDriver(aws, aws, aws, discovery_cache=cache),
        block=False,
    )
    for i in range(N_SERVICES):
        cluster.create("Service", make_service(i))
    start = time.monotonic()
    deadline = start + 300
    while time.monotonic() < deadline:
        if len(aws.all_accelerator_arns()) >= N_SERVICES:
            break
        time.sleep(0.01)
    elapsed = time.monotonic() - start
    stop.set()
    done = len(aws.all_accelerator_arns())
    if done < N_SERVICES:
        raise SystemExit(f"benchmark did not converge: {done}/{N_SERVICES}")
    return N_SERVICES / elapsed


def main():
    klog.init(verbosity=-1)
    import logging

    logging.getLogger("agac").setLevel(logging.CRITICAL)
    # baseline: the reference's operating point — 1 worker per queue,
    # client-go's fixed 10 qps/100 burst enqueue bucket, full O(N)+1
    # tag-scan discovery on every reconcile
    baseline = run_convergence(workers=1, cache_ttl=0.0, qps=10.0, burst=100)
    # measured: this framework's tuned production configuration —
    # concurrent workers (32 ≈ the IO-bound sweet spot; 64 regresses on
    # contention), raised enqueue bucket (--queue-qps/--queue-burst),
    # and the incremental discovery cache (AGAC_DISCOVERY_CACHE_TTL)
    value = run_convergence(workers=32, cache_ttl=5.0, qps=1000.0, burst=1000)
    print(
        json.dumps(
            {
                "metric": "service_to_accelerator_convergence_throughput",
                "value": round(value, 2),
                "unit": "services/sec",
                "vs_baseline": round(value / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
