"""End-to-end reconcile-throughput benchmark under a realistic AWS model.

The reference publishes no benchmark numbers (BASELINE.md: no
``benchmarks/`` dir, no ``Benchmark*`` funcs, no perf claims), so this
measures the framework's own headline capability — full watch →
informer → queue → reconcile → cloud-ensure convergence of ALL THREE
controllers together — and reports ``vs_baseline`` against the
reference's implicit operating point (1 worker per queue,
``cmd/controller/controller.go:32``; client-go's fixed 10 qps / 100
burst enqueue bucket; the O(N)+1 ListTags discovery scan on every
reconcile, ``global_accelerator.go:87-110``).

The workload drives every controller and every API family:

- N annotated ``Service``s — each needs an accelerator + listener +
  endpoint-group chain AND an atomic TXT+A Route53 record pair
  (hostnames spread over 10 hosted zones).
- N/10 annotated ALB ``Ingress``es — exercising the listen-ports
  listener derivation (half carry the
  ``alb.ingress.kubernetes.io/listen-ports`` JSON annotation, half
  derive ports from rule backends — reference
  ``global_accelerator.go:517-552``) plus their own Route53 pairs.
- N/10 ``EndpointGroupBinding``s bound into pre-existing endpoint
  groups (reference ``pkg/controller/endpointgroupbinding/
  reconcile.go:112-217``), with a post-convergence CHURN phase:
  every binding's weight is edited and every binding with a same-
  namespace partner Service swaps its serviceRef — endpoint
  add/remove/weight-sync under load.

The fake cloud is SHAPED, not uniform:

- **Asymmetric per-operation latency.**  Every operation of all three
  API families (GlobalAccelerator, ELBv2, Route53) sleeps a per-op
  latency taken from real-world control-plane behavior.  Latencies are
  scaled to 1/10 of their real-world values so the bench completes in
  minutes; quotas are scaled x10 to match, so the RELATIVE pressure
  (which API binds, how much concurrency pays) is preserved.
- **Per-API throttle quotas.**  Each API family has a token bucket
  (GA mutate, GA read, ELBv2, Route53).  A call that finds the bucket
  empty BLOCKS until admitted — modeling an SDK in standard-retry mode
  pacing itself under ThrottlingException (our production client does
  the same: ``real_backend.py`` standard retry mode).

The baseline is measured at N_BASELINE=100 (with its own /10 Ingress +
EndpointGroupBinding populations) because the reference operating
point's O(N) tag-scan per reconcile makes serial convergence at N=1000
intractable (hours).  Comparing per-object rates FAVORS the baseline:
its rate degrades superlinearly with N, so vs_baseline understates the
gap at N=1000.

A separate DRIFT-TICK phase measures the cost of one
``--drift-resync-period`` tick over a converged fleet: the fleet is
converged with shaping disabled (fast), then one full ticker round is
isolated by call-count quiescence and its per-op AWS call counts are
recorded.  Tick wall-clock is derived from the same quota model the
shaped phases use (calls_per_family / family_rate after burst) — see
docs/operations.md "Drift resync at scale".

Output contract (VERDICT r4 #1): the FINAL stdout line is ONE compact
JSON object (< 1 KB) carrying metric/value/unit/vs_baseline plus key
scalars; the full detail blob is written to ``bench_detail.json`` next
to this file (committed, refreshed by each run) and the same path is
named in the compact line.  Progress goes to stderr only.
"""

import json
import os
import sys
import threading
import time
import urllib.request

from agac_tpu import klog
from agac_tpu.observability import fleet as obs_fleet
from agac_tpu.observability import journey as obs_journey
from agac_tpu.observability import metrics as obs_metrics
from agac_tpu.observability import profile as obs_profile
from agac_tpu.observability import stackprof as obs_stackprof
from agac_tpu.cloudprovider.aws.cache import (
    AcceleratorTopologyCache,
    DiscoveryCache,
    HostedZoneCache,
    LoadBalancerCoalescer,
    RecordSetCache,
)
from agac_tpu.apis import (
    ALB_LISTEN_PORTS_ANNOTATION,
    AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION,
    AWS_LOAD_BALANCER_TYPE_ANNOTATION,
    INGRESS_CLASS_ANNOTATION,
    ROUTE53_HOSTNAME_ANNOTATION,
)
from agac_tpu.apis.endpointgroupbinding import (
    EndpointGroupBinding,
    EndpointGroupBindingSpec,
    ServiceReference,
)
from agac_tpu.cloudprovider.aws import AWSDriver, FakeAWSBackend
from agac_tpu.cloudprovider.aws.batcher import ChangeBatcher
from agac_tpu.reconcile import PendingSettleTable
from agac_tpu.cluster import FakeCluster, LoadBalancerIngress, ObjectMeta, Service, ServicePort
from agac_tpu.cluster.objects import (
    HTTPIngressPath,
    HTTPIngressRuleValue,
    Ingress,
    IngressBackend,
    IngressLoadBalancerIngress,
    IngressRule,
    IngressServiceBackend,
    IngressSpec,
    ServiceBackendPort,
    ServiceSpec,
)
from agac_tpu.manager import ControllerConfig, Manager
from agac_tpu.reconcile import (
    BucketRateLimiter,
    add_sync_duration_observer,
    remove_sync_duration_observer,
)
from agac_tpu.controllers import (
    EndpointGroupBindingConfig,
    GarbageCollectorConfig,
    GlobalAcceleratorConfig,
    Route53Config,
)

N_SERVICES = int(os.environ.get("AGAC_BENCH_N", "1000"))
N_BASELINE = int(os.environ.get("AGAC_BENCH_N_BASELINE", "100"))
N_ZONES = 10
# 16: the top of the band docs/operations.md "Sizing the worker pool"
# recommends (8-16) — the headline config IS the documented config
# (VERDICT r4 #7); throughput is GA-mutate-quota-bound from 8 workers
# up, so 32 only bought ~6% at ~8x the GA p99
TUNED_WORKERS = int(os.environ.get("AGAC_BENCH_WORKERS", "16"))
RESYNC_PERIOD = 30.0  # the reference's informer resync default
# one full resync cycle; env-shrinkable so the output-contract smoke
# test (tests/test_bench_output.py) completes in seconds
STEADY_WINDOW = float(os.environ.get("AGAC_BENCH_STEADY_WINDOW", str(RESYNC_PERIOD)))
# drift-tick phase fleet size (shaping disabled there, so N=1000
# converges in seconds; the smoke test shrinks it)
DRIFT_N = int(os.environ.get("AGAC_BENCH_DRIFT_N", str(N_SERVICES)))
DEADLINE = 900.0

# the committed full-scale detail artifact; overridable so the smoke
# test (tests/test_bench_output.py) writes its tiny-fleet blob to a
# tmp dir instead of clobbering the real record
DETAIL_PATH = os.environ.get(
    "AGAC_BENCH_DETAIL_PATH",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_detail.json"),
)

# Time compression: real-world latencies / LATENCY_SCALE, quotas
# x LATENCY_SCALE — same shape, 1/10 the wall clock.
LATENCY_SCALE = 10.0

# async mutation pipeline knobs (ISSUE 6), pre-scaled like latencies:
# the tuned phase batches same-zone record mutations within a 1.2 s
# gather window (12 s real-world) into <= 100-change
# ChangeResourceRecordSets calls, and the settle scheduler re-checks
# parked items every 0.2 s (2 s real-world).  The linger sits OFF the
# convergence critical path: staged chains create every accelerator in
# the first third of the run (the endpoint-group tail binds the
# headline), so records commit long before the last mutate — the
# linger trades only per-record publication latency for a ~10x wire-
# call cut against the 5 req/s Route53 quota.
R53_BATCH_MAX = int(os.environ.get("AGAC_BENCH_R53_BATCH_MAX", "100"))
R53_BATCH_LINGER = float(os.environ.get("AGAC_BENCH_R53_LINGER", "1.2"))

# profiling phase fleet size (ISSUE 14): big enough that throughput is
# genuinely quota-bound (so the control-vs-profiled comparison measures
# the profiler, not scheduler noise), small enough not to double the
# bench's wall time
PROFILE_N = int(os.environ.get("AGAC_BENCH_PROFILE_N", "200"))
# the in-bench regression gate: with the stage accountant AND the
# sampling profiler both armed, the headline may not fall more than
# this many percent below the unprofiled control run
PROFILE_MAX_OVERHEAD_PCT = float(
    os.environ.get("AGAC_BENCH_PROFILE_MAX_OVERHEAD", "5.0")
)
SETTLE_POLL = 0.2

# Real-world control-plane latencies (seconds) before scaling.
# Create/Update/Delete on Global Accelerator are slow async control
# operations; reads are fast; Route53 ChangeResourceRecordSets commits
# a transaction.  Shape, not vendor-measured precision, is the point:
# the slowest op is ~15x the fastest and mutates cost multiples of
# reads, so concurrency and caching are rewarded the way they are
# against the real control plane.
REAL_LATENCY = {
    # GlobalAccelerator mutating
    "create_accelerator": 1.5,
    "update_accelerator": 1.0,
    "delete_accelerator": 1.0,
    "create_listener": 0.5,
    "update_listener": 0.5,
    "delete_listener": 0.5,
    "create_endpoint_group": 0.5,
    "update_endpoint_group": 0.5,
    "delete_endpoint_group": 0.5,
    "add_endpoints": 0.3,
    "remove_endpoints": 0.3,
    "tag_resource": 0.2,
    # GlobalAccelerator reads
    "list_accelerators": 0.3,
    "describe_accelerator": 0.2,
    "list_tags_for_resource": 0.1,
    "list_listeners": 0.15,
    "list_endpoint_groups": 0.15,
    "describe_endpoint_group": 0.15,
    # ELBv2
    "describe_load_balancers": 0.2,
    # Route53
    "list_hosted_zones": 0.2,
    "list_hosted_zones_by_name": 0.2,
    "list_resource_record_sets": 0.25,
    "change_resource_record_sets": 0.5,
}

# API family -> (sustained requests/sec, burst) AFTER scaling.
# Real-world: GA mutate ~5/s, GA read ~20/s, ELBv2 describe ~10/s,
# Route53 5/s (the one AWS documents).
QUOTAS = {
    "ga_mutate": (50.0, 100),
    "ga_read": (200.0, 400),
    "elbv2": (100.0, 200),
    "route53": (50.0, 100),
}

OP_FAMILY = {
    "create_accelerator": "ga_mutate",
    "update_accelerator": "ga_mutate",
    "delete_accelerator": "ga_mutate",
    "create_listener": "ga_mutate",
    "update_listener": "ga_mutate",
    "delete_listener": "ga_mutate",
    "create_endpoint_group": "ga_mutate",
    "update_endpoint_group": "ga_mutate",
    "delete_endpoint_group": "ga_mutate",
    "add_endpoints": "ga_mutate",
    "remove_endpoints": "ga_mutate",
    "tag_resource": "ga_mutate",
    "list_accelerators": "ga_read",
    "describe_accelerator": "ga_read",
    "list_tags_for_resource": "ga_read",
    "list_listeners": "ga_read",
    "list_endpoint_groups": "ga_read",
    "describe_endpoint_group": "ga_read",
    "describe_load_balancers": "elbv2",
    "list_hosted_zones": "route53",
    "list_hosted_zones_by_name": "route53",
    "list_resource_record_sets": "route53",
    "change_resource_record_sets": "route53",
}


def _progress(msg: str) -> None:
    print(f"bench: {msg}", file=sys.stderr, flush=True)


# metric families the per-phase scrape snapshots into bench_detail —
# the observability acceptance set (workqueue depth/latency, AWS call
# outcomes, reconcile results, GC sweeps) without dragging every
# histogram bucket into the committed artifact
_SNAPSHOT_FAMILIES = (
    "agac_workqueue_depth",
    "agac_workqueue_adds_total",
    "agac_workqueue_retries_total",
    "agac_reconcile_results_total",
    "agac_aws_api_calls_total",
    "agac_gc_",
)


def scrape_metrics(port: int) -> dict:
    """GET /metrics off the bench's health server and condense it for
    bench_detail.json: family names + series count prove the exposition
    parses end to end; the key series carry the values the output
    contract asserts.  Counters are process-cumulative across phases
    (that is what Prometheus counters are)."""
    url = f"http://127.0.0.1:{port}/metrics"
    with urllib.request.urlopen(url, timeout=10) as response:
        text = response.read().decode()
    samples = obs_metrics.parse_text(text)
    families = sorted(
        {line.split(" ", 2)[2].split(" ")[0]
         for line in text.splitlines() if line.startswith("# TYPE ")}
    )
    return {
        "series_total": len(samples),
        "families": families,
        "key_series": {
            name: value
            for name, value in sorted(samples.items())
            if name.startswith(_SNAPSHOT_FAMILIES)
        },
    }


class TokenBucket:
    """Blocking facade over the framework's own ``BucketRateLimiter``
    (one canonical token-bucket implementation): ``acquire`` reserves
    a token and sleeps until its admission time — FIFO-fair under
    contention, sustained rate exactly ``rate`` once the burst is
    spent."""

    def __init__(self, rate: float, burst: int):
        self._limiter = BucketRateLimiter(qps=rate, burst=burst)
        self._stat_lock = threading.Lock()
        self.throttled_waits = 0  # acquisitions that had to wait

    def acquire(self) -> None:
        wait = self._limiter.when(None)
        if wait > 0:
            with self._stat_lock:
                self.throttled_waits += 1
            time.sleep(wait)


class _UnshapedView:
    """A second client handle over the same backend that bypasses the
    shaping *and* counting interception entirely — for out-of-band
    verification reads while controller workers are still running.
    Unlike flipping ``shaping_enabled``/``counting_enabled`` globally,
    a concurrent background call (e.g. a resync-driven
    DescribeLoadBalancers) landing mid-verification stays shaped and
    counted (ADVICE r5 #2)."""

    def __init__(self, backend: "ShapedAWS"):
        self._backend = backend

    def __getattr__(self, name):
        # object.__getattribute__ bypasses ShapedAWS.__getattribute__,
        # returning the plain bound method of the underlying fake
        return object.__getattribute__(self._backend, name)


class ShapedAWS(FakeAWSBackend):
    """FakeAWSBackend with asymmetric per-op latency and per-API-family
    blocking throttle quotas on EVERY operation, plus per-op counters
    for call-rate accounting.

    ``shaping_enabled`` gates the latency/quota costs only — counters
    keep running (the drift-tick phase measures call counts with
    shaping off), so phases that pre-build fleet state snapshot
    ``op_counts`` and report deltas.  ``counting_enabled`` pauses the
    counters too, for out-of-band work that is neither fixture nor
    measured; prefer ``unshaped()`` for verification reads that run
    concurrently with live controllers."""

    _SHAPED = frozenset(REAL_LATENCY)

    def __init__(self, *args, **kwargs):
        # a 1000-accelerator fleet runs with raised service quotas in
        # real accounts too; every other documented invariant (name
        # shapes, port ranges, per-listener/group quotas, change-batch
        # limits) stays enforced at AWS defaults.  Callers MUST size
        # quota_accelerators from their own fleet (for_fleet below):
        # an env-derived default once sat BELOW the tuned fleet's need
        # when the smoke knobs shrank N_BASELINE/DRIFT_N, wedging the
        # run in permanent quota retries.
        kwargs.setdefault(
            "quota_accelerators", N_SERVICES + N_BASELINE + DRIFT_N + 400
        )
        self.shaping_enabled = True
        self.counting_enabled = True
        super().__init__(*args, **kwargs)
        self.op_counts: dict[str, int] = {}
        self._count_lock = threading.Lock()
        self._buckets = {
            family: TokenBucket(rate, burst) for family, (rate, burst) in QUOTAS.items()
        }

    def total_calls(self) -> int:
        with self._count_lock:
            return sum(self.op_counts.values())

    def snapshot_counts(self) -> dict[str, int]:
        with self._count_lock:
            return dict(self.op_counts)

    def unshaped(self) -> _UnshapedView:
        """A handle whose calls are never shaped or counted, leaving
        the global toggles alone for concurrent controller traffic."""
        return _UnshapedView(self)

    def __getattribute__(self, name):
        attr = super().__getattribute__(name)
        if name.startswith("_") or name not in ShapedAWS._SHAPED:
            return attr
        bucket = super().__getattribute__("_buckets")[OP_FAMILY[name]]
        count_lock = super().__getattribute__("_count_lock")
        op_counts = super().__getattribute__("op_counts")
        shaped_on = super().__getattribute__("shaping_enabled")
        counting_on = super().__getattribute__("counting_enabled")
        latency = REAL_LATENCY[name] / LATENCY_SCALE

        def shaped(*args, **kwargs):
            if counting_on:
                with count_lock:
                    op_counts[name] = op_counts.get(name, 0) + 1
            if shaped_on:
                bucket.acquire()  # throttle admission (SDK-style pacing)
                time.sleep(latency)  # server-side processing time
            return attr(*args, **kwargs)

        return shaped


# ---------------------------------------------------------------------------
# workload objects
# ---------------------------------------------------------------------------

def scaled_counts(n: int) -> tuple[int, int]:
    """(n_ingresses, n_bindings) for a fleet of ``n`` Services."""
    return max(1, n // 10), max(1, n // 10)


def make_service(i: int) -> Service:
    lb_host = f"bench{i:04d}-0123456789abcdef.elb.us-west-2.amazonaws.com"
    svc = Service(
        metadata=ObjectMeta(
            name=f"bench{i:04d}",
            namespace=f"ns{i % 10}",
            annotations={
                AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
                AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external",
                ROUTE53_HOSTNAME_ANNOTATION: (
                    f"bench{i:04d}.z{i % N_ZONES}.bench.example.com"
                ),
            },
        ),
        spec=ServiceSpec(
            type="LoadBalancer", ports=[ServicePort(name="http", port=80, protocol="TCP")]
        ),
    )
    svc.status.load_balancer.ingress.append(LoadBalancerIngress(hostname=lb_host))
    return svc


def alb_name(j: int) -> str:
    return f"k8s-ns{j % 10}-ing{j:04d}-0a1b2c3d4e"


def alb_hostname(j: int) -> str:
    return f"{alb_name(j)}-111222333.us-west-2.elb.amazonaws.com"


def make_ingress(j: int) -> Ingress:
    """An annotated ALB Ingress.  Even ``j`` carries the listen-ports
    JSON annotation (the reference's primary derivation path,
    ``global_accelerator.go:521-535``); odd ``j`` derives ports from
    its rule backends (``:537-552``)."""
    annotations = {
        INGRESS_CLASS_ANNOTATION: "alb",
        AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
        ROUTE53_HOSTNAME_ANNOTATION: f"ing{j:04d}.z{j % N_ZONES}.bench.example.com",
    }
    if j % 2 == 0:
        annotations[ALB_LISTEN_PORTS_ANNOTATION] = '[{"HTTP": 80}, {"HTTPS": 443}]'
    ing = Ingress(
        metadata=ObjectMeta(
            name=f"ing{j:04d}", namespace=f"ns{j % 10}", annotations=annotations
        ),
        spec=IngressSpec(
            ingress_class_name="alb",
            rules=[
                IngressRule(
                    host=f"ing{j:04d}.bench.example.com",
                    http=HTTPIngressRuleValue(
                        paths=[
                            HTTPIngressPath(
                                path="/",
                                backend=IngressBackend(
                                    service=IngressServiceBackend(
                                        name="backend",
                                        port=ServiceBackendPort(number=80),
                                    )
                                ),
                            )
                        ]
                    ),
                )
            ],
        ),
    )
    ing.status.load_balancer.ingress.append(
        IngressLoadBalancerIngress(hostname=alb_hostname(j))
    )
    return ing


def swap_partner(k: int, n: int) -> int | None:
    """The Service index binding ``k`` swaps its serviceRef to during
    churn: same namespace (index ≡ k mod 10), distinct LB.  None when
    the fleet is too small to have a partner."""
    j = k + 10
    return j if j < n else None


def make_binding(k: int, endpoint_group_arn: str) -> EndpointGroupBinding:
    return EndpointGroupBinding(
        metadata=ObjectMeta(name=f"binding{k:04d}", namespace=f"ns{k % 10}"),
        spec=EndpointGroupBindingSpec(
            endpoint_group_arn=endpoint_group_arn,
            weight=100,
            service_ref=ServiceReference(name=f"bench{k:04d}"),
        ),
    )


def prepare_aws(aws: ShapedAWS, n: int, n_ing: int, n_egb: int) -> tuple[list, list[str]]:
    """Register LBs + hosted zones and pre-build one out-of-band GA
    chain per binding (cluster tag ``external`` so the controllers
    never touch them — reference tag scoping,
    ``global_accelerator.go:87-110``).  Runs with shaping disabled:
    this is fixture state, not measured work."""
    aws.shaping_enabled = False
    try:
        for i in range(n):
            aws.add_load_balancer(
                f"bench{i:04d}",
                "us-west-2",
                f"bench{i:04d}-0123456789abcdef.elb.us-west-2.amazonaws.com",
            )
        for j in range(n_ing):
            aws.add_load_balancer(alb_name(j), "us-west-2", alb_hostname(j))
        zones = [aws.add_hosted_zone(f"z{k}.bench.example.com") for k in range(N_ZONES)]
        driver = AWSDriver(aws, aws, aws)
        group_arns: list[str] = []
        for k in range(n_egb):
            ext_lb = f"ext{k:04d}"
            host = f"{ext_lb}-fedcba9876543210.elb.us-west-2.amazonaws.com"
            aws.add_load_balancer(ext_lb, "us-west-2", host)
            svc = Service(
                metadata=ObjectMeta(name=f"ext{k:04d}", namespace="external"),
                spec=ServiceSpec(
                    type="LoadBalancer",
                    ports=[ServicePort(name="http", port=80, protocol="TCP")],
                ),
            )
            svc.status.load_balancer.ingress.append(LoadBalancerIngress(hostname=host))
            arn, _, _ = driver.ensure_global_accelerator_for_service(
                svc, svc.status.load_balancer.ingress[0], "external", ext_lb, "us-west-2"
            )
            listener = driver.get_listener(arn)
            group = driver.get_endpoint_group(listener.listener_arn)
            group_arns.append(group.endpoint_group_arn)
    finally:
        aws.shaping_enabled = True
    return zones, group_arns


def create_objects(
    cluster: FakeCluster, n: int, n_ing: int, n_egb: int, group_arns: list[str]
) -> list[tuple[str, str]]:
    for i in range(n):
        cluster.create("Service", make_service(i))
    for j in range(n_ing):
        cluster.create("Ingress", make_ingress(j))
    binding_keys = []
    for k in range(n_egb):
        binding = make_binding(k, group_arns[k])
        cluster.create("EndpointGroupBinding", binding)
        binding_keys.append((binding.metadata.namespace, binding.metadata.name))
    return binding_keys


def _percentile(samples: list, q: float) -> float:
    if not samples:
        return 0.0
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, max(0, int(q * len(ordered) + 0.999999) - 1))
    return ordered[idx]


def _controller_of(thread_name: str) -> str:
    for prefix, label in (
        ("global-accelerator", "globalaccelerator"),
        ("route53", "route53"),
        ("endpoint-group", "endpointgroupbinding"),
    ):
        if thread_name.startswith(prefix):
            return label
    return "other"


def _ops_delta(before: dict[str, int], after: dict[str, int]) -> dict[str, int]:
    return {
        op: after[op] - before.get(op, 0)
        for op in sorted(after)
        if after[op] - before.get(op, 0) > 0
    }


class ReadPlane:
    """The per-phase cache bundle: the two discovery-era caches plus
    the three coalesced-read-plane caches, with one place to collect
    their efficacy counters (hits / misses / single-flight waits /
    batch sizes) into the phase's detail record — so cache regressions
    show up in the bench trajectory, not only in call totals."""

    def __init__(
        self,
        discovery_ttl: float = 0.0,
        zone_ttl: float = 0.0,
        topology_verify_ttl: float = 0.0,
        topology_full_ttl: float = 3600.0,
        record_ttl: float = 0.0,
        lb_ttl: float = 0.0,
        lb_batch_window: float = 0.01,
        discovery_tags_ttl: float = 0.0,
        pipeline: bool = False,
    ):
        self.discovery = (
            DiscoveryCache(
                ttl=discovery_ttl,
                tags_ttl=discovery_tags_ttl if discovery_tags_ttl > 0 else None,
            )
            if discovery_ttl > 0
            else None
        )
        self.zones = HostedZoneCache(ttl=zone_ttl) if zone_ttl > 0 else None
        self.topology = (
            AcceleratorTopologyCache(
                verify_ttl=topology_verify_ttl, full_ttl=topology_full_ttl
            )
            if topology_verify_ttl > 0
            else None
        )
        self.record_sets = RecordSetCache(ttl=record_ttl) if record_ttl > 0 else None
        # the bench is single-region, so one coalescer is safe (the
        # production factory keys coalescers per region)
        self.load_balancers = (
            LoadBalancerCoalescer(ttl=lb_ttl, batch_window=lb_batch_window)
            if lb_ttl > 0
            else None
        )
        # the async mutation pipeline (ISSUE 6): pending-settle table
        # (non-blocking settle + the Route53 wait-for-accelerator
        # park), per-zone change batcher, and staged GA chains
        self.settle_table = PendingSettleTable() if pipeline else None
        self.change_batcher = (
            ChangeBatcher(max_changes=R53_BATCH_MAX, linger=R53_BATCH_LINGER)
            if pipeline
            else None
        )
        self.stage_requeue = 0.01 if pipeline else 0.0

    def driver_kwargs(self) -> dict:
        return {
            "discovery_cache": self.discovery,
            "zone_cache": self.zones,
            "topology_cache": self.topology,
            "record_cache": self.record_sets,
            "lb_coalescer": self.load_balancers,
            "settle_table": self.settle_table,
            "change_batcher": self.change_batcher,
            "stage_requeue": self.stage_requeue,
        }

    def stats(self) -> dict:
        return {
            name: cache.stats()
            for name, cache in (
                ("discovery", self.discovery),
                ("zones", self.zones),
                ("topology", self.topology),
                ("record_sets", self.record_sets),
                ("load_balancers", self.load_balancers),
            )
            if cache is not None
        }


def fleet_progress(
    aws: "ShapedAWS",
    cluster: FakeCluster,
    zones: list,
    binding_keys: list[tuple[str, str]],
) -> tuple[tuple[int, int, int], int, int]:
    """((accelerators, listeners, endpoint groups), records, bound
    bindings) — the convergence odometer.  The chain counts come from
    the backend's own tables (no shaped/counted API traffic), and ALL
    THREE levels are tracked: with the interleaved chain stages of
    ISSUE 6, an accelerator exists whole passes before its listener
    and endpoint group — counting accelerators alone would declare
    convergence while chain tails are still mutating."""
    bound = sum(
        1
        for ns, name in binding_keys
        if len(cluster.get("EndpointGroupBinding", ns, name).status.endpoint_ids) == 1
    )
    records = sum(len(aws.records_in_zone(z.id)) for z in zones)
    return aws.chain_counts(), records, bound


def fleet_converged(
    aws: "ShapedAWS",
    cluster: FakeCluster,
    zones: list,
    binding_keys: list[tuple[str, str]],
    base_chain: tuple[int, int, int],
    n: int,
    n_ing: int,
) -> bool:
    """The ONE convergence criterion every phase shares: all
    accelerator chains COMPLETE (accelerator + listener + endpoint
    group each), every TXT+A pair written, every binding bound to
    exactly one endpoint."""
    (accels, listeners, groups), records, bound = fleet_progress(
        aws, cluster, zones, binding_keys
    )
    base_accels, base_listeners, base_groups = base_chain
    return (
        accels >= base_accels + n + n_ing
        and listeners >= base_listeners + n + n_ing
        and groups >= base_groups + n + n_ing
        and records >= 2 * (n + n_ing)
        and bound == len(binding_keys)
    )


def wait_converged(
    converged, progress, deadline: float, stall_after: float = 120.0
) -> bool:
    """Poll until converged.  A frozen progress odometer for
    ``stall_after`` seconds means the fleet is WEDGED (e.g. an item
    stuck in permanent retries) — fail loudly with the odometer
    instead of burning the whole deadline looking alive."""
    last = progress()
    last_change = time.monotonic()
    while time.monotonic() < deadline:
        if converged():
            return True
        cur = progress()
        if cur != last:
            last, last_change = cur, time.monotonic()
        elif time.monotonic() - last_change > stall_after:
            raise SystemExit(
                f"benchmark stalled: progress (accelerators, records, bound)="
                f"{cur!r} frozen for {stall_after:.0f}s"
            )
        time.sleep(0.1)
    return False


# ---------------------------------------------------------------------------
# convergence + churn phase
# ---------------------------------------------------------------------------

def run_convergence(
    n: int,
    workers: int,
    cache_ttl: float = 0.0,
    zone_cache_ttl: float = 0.0,
    qps: float = 10.0,
    burst: int = 100,
    measure_steady_state: bool = False,
    churn: bool = False,
    read_plane_ttl: float = 0.0,
    pipeline: bool = False,
) -> dict:
    """Create the mixed fleet (``n`` Services + n/10 Ingresses + n/10
    EndpointGroupBindings), converge all three controllers, optionally
    churn the bindings and measure the steady state, and return a
    result dict.  ``read_plane_ttl`` > 0 turns on the coalesced
    verification read plane (topology/record-set/LB caches) at that
    tick scope."""
    n_ing, n_egb = scaled_counts(n)
    n_objects = n + n_ing + n_egb
    cluster = FakeCluster()
    # accelerators this run creates: n Services + n_ing Ingresses by
    # the controllers, plus n_egb out-of-band chains in prepare_aws
    aws = ShapedAWS(quota_accelerators=n + n_ing + n_egb + 50)
    plane = ReadPlane(
        discovery_ttl=cache_ttl,
        zone_ttl=zone_cache_ttl,
        topology_verify_ttl=read_plane_ttl,
        record_ttl=read_plane_ttl,
        lb_ttl=read_plane_ttl,
        # incremental snapshot refresh: reloads reuse write-through
        # tags for the whole phase (full tag re-list only at phase
        # scale), killing the per-reload O(N) ListTags stall
        discovery_tags_ttl=600.0 if pipeline else 0.0,
        pipeline=pipeline,
    )
    zones, group_arns = prepare_aws(aws, n, n_ing, n_egb)
    setup_counts = aws.snapshot_counts()
    base_chain = aws.chain_counts()

    latencies: dict[str, list] = {}
    lat_lock = threading.Lock()

    def observer(key: str, seconds: float, err) -> None:
        label = _controller_of(threading.current_thread().name)
        with lat_lock:
            latencies.setdefault(label, []).append(seconds)

    # the convergence SLO plane (ISSUE 9): a PER-PHASE journey tracker
    # (private registry) so the baseline's latencies never bleed into
    # the tuned phase's percentiles; the phase's convergence block is
    # read back through the fleet-merge layer — the same read the
    # sharded fleet view uses
    journey_registry = obs_metrics.MetricsRegistry()
    previous_tracker = obs_journey.install(
        obs_journey.JourneyTracker(registry=journey_registry)
    )

    stop = threading.Event()
    config = ControllerConfig(
        global_accelerator=GlobalAcceleratorConfig(
            workers=workers, queue_qps=qps, queue_burst=burst
        ),
        route53=Route53Config(workers=workers, queue_qps=qps, queue_burst=burst),
        endpoint_group_binding=EndpointGroupBindingConfig(
            workers=workers, queue_qps=qps, queue_burst=burst
        ),
        settle_poll_interval=SETTLE_POLL,
    )
    manager = Manager(
        resync_period=RESYNC_PERIOD, metrics_registry=obs_metrics.registry()
    )
    add_sync_duration_observer(observer)
    try:
        manager.run(
            cluster,
            config,
            stop,
            cloud_factory=lambda region: AWSDriver(
                aws,
                aws,
                aws,
                # the reference requeues every 60 s until the GA
                # controller has converged (route53.go:63-77); scaled
                accelerator_missing_retry=60.0 / LATENCY_SCALE,
                **plane.driver_kwargs(),
            ),
            block=False,
            settle_table=plane.settle_table,
        )
        binding_keys = create_objects(cluster, n, n_ing, n_egb, group_arns)
        start = time.monotonic()
        deadline = start + DEADLINE

        def converged() -> bool:
            return fleet_converged(
                aws, cluster, zones, binding_keys, base_chain, n, n_ing
            )

        done = wait_converged(
            converged, lambda: fleet_progress(aws, cluster, zones, binding_keys), deadline
        )
        elapsed = time.monotonic() - start
        if not done:
            chain, records, bound = fleet_progress(aws, cluster, zones, binding_keys)
            raise SystemExit(
                f"benchmark did not converge: chain={chain} (base {base_chain}, "
                f"target +{n + n_ing} each), {records}/{2 * (n + n_ing)} records, "
                f"{bound}/{len(binding_keys)} bound"
            )

        # convergence-phase ops only: churn and the steady window keep
        # their own deltas, so the quota-floor figure below stays
        # comparable between the churning tuned run and the baseline
        convergence_counts = aws.snapshot_counts()

        churn_result = None
        if churn:
            churn_result = _run_churn(cluster, aws, binding_keys, n, deadline)

        steady = None
        if measure_steady_state:
            # Let the convergence tail drain, then count every AWS call
            # over one full resync cycle: the converged level-triggered
            # re-reconcile rate — what the account pays per 30 s for N
            # services of drift verification.
            time.sleep(2.0)
            calls_before = aws.total_calls()
            window_start = time.monotonic()
            time.sleep(STEADY_WINDOW)
            window = time.monotonic() - window_start
            steady = {
                "window_s": round(window, 1),
                "aws_calls": aws.total_calls() - calls_before,
                "aws_calls_per_sec": round((aws.total_calls() - calls_before) / window, 2),
                "resync_period_s": RESYNC_PERIOD,
                # Services/Ingresses are quiescent when converged: both
                # this framework and the reference skip equal resync
                # updates (reference globalaccelerator/controller.go:
                # 100-102 reflect.DeepEqual).  EndpointGroupBindings are
                # NOT: the EGB handler enqueues resyncs unconditionally
                # (endpointgroupbinding/controller.go:84-94) and the
                # reconcile resolves serviceRef->LB ARNs BEFORE the
                # ObservedGeneration early return (reconcile.go:112-157)
                # — one DescribeLoadBalancers per binding per resync.
                # That read is LOAD-BEARING, not waste: the EGB
                # controller watches only bindings (no Service/Ingress
                # event handlers — listers only), so the resync
                # re-resolution is the ONLY path that propagates a
                # referenced Service's changed LB hostname into the
                # binding.  Exact parity, measured here as n_bindings
                # calls per window.
                "note": (
                    "converged Services/Ingresses are quiescent (equal resync "
                    "updates skipped, parity: globalaccelerator/controller.go:100-102); "
                    "each EndpointGroupBinding still pays 1 DescribeLoadBalancers "
                    "LOOKUP per resync — the load-bearing ref re-resolution that "
                    "propagates referenced-Service LB changes (the EGB controller "
                    "has no Service watch; parity: endpointgroupbinding/"
                    "controller.go:84-94) — but the read-plane coalescer now "
                    "gathers the resync burst into multi-name wire calls, so the "
                    "window's AWS call count is ~n_bindings/batch_size"
                ),
            }
    finally:
        remove_sync_duration_observer(observer)
        obs_journey.install(previous_tracker)
        stop.set()

    with lat_lock:
        sync_latency = {
            label: {
                "p50_s": round(_percentile(vals, 0.50), 4),
                "p99_s": round(_percentile(vals, 0.99), 4),
                "n_syncs": len(vals),
            }
            for label, vals in sorted(latencies.items())
            if label != "other"
        }
    throttled = {
        family: bucket.throttled_waits for family, bucket in aws._buckets.items()
    }
    measured_ops = _ops_delta(setup_counts, convergence_counts)
    mutate_calls = sum(
        count for op, count in measured_ops.items() if OP_FAMILY[op] == "ga_mutate"
    )
    result = {
        "objects_per_sec": round(n_objects / elapsed, 2),
        "elapsed_s": round(elapsed, 1),
        "pipeline": pipeline,
        "ga_mutate_calls": mutate_calls,
        "n_services": n,
        "n_ingresses": n_ing,
        "n_bindings": n_egb,
        "n_objects": n_objects,
        "workers": workers,
        "queue_qps": qps,
        "queue_burst": burst,
        "discovery_cache_ttl_s": cache_ttl,
        "zone_cache_ttl_s": zone_cache_ttl,
        "aws_calls_total": sum(measured_ops.values()),
        "aws_calls_by_op": measured_ops,
        # the quota floor the headline must sit near to be credible:
        # every convergence needs mutate_calls GA mutates through a
        # 50/s bucket, so no configuration can beat this rate
        "ga_mutate_quota_floor_objects_per_sec": round(
            n_objects / max(mutate_calls / QUOTAS["ga_mutate"][0], 0.001), 2
        ),
        "throttled_acquisitions": throttled,
        "sync_latency": sync_latency,
        # end-to-end object-journey convergence latency per kind
        # (ISSUE 9), read through the fleet-merge layer off this
        # phase's journey histograms
        "convergence": obs_fleet.converge_percentiles(
            obs_fleet.merge_expositions({"self": journey_registry.render()})[0]
        ),
    }
    cache_stats = plane.stats()
    if cache_stats:
        result["cache_stats"] = cache_stats
    if plane.settle_table is not None:
        result["pending_settle"] = plane.settle_table.stats()
    if plane.change_batcher is not None:
        result["r53_batching"] = plane.change_batcher.stats()
    if churn_result is not None:
        result["egb_churn"] = churn_result
    if steady is not None:
        result["steady_state"] = steady
    return result


def _run_churn(
    cluster: FakeCluster,
    aws: ShapedAWS,
    binding_keys: list[tuple[str, str]],
    n: int,
    deadline: float,
) -> dict:
    """Post-convergence EndpointGroupBinding churn: every binding's
    weight is edited (weight-sync path, reference
    ``reconcile.go:195-202``); every binding with a same-namespace
    partner Service swaps its serviceRef (endpoint remove + add,
    ``reconcile.go:112-193``)."""
    before = aws.snapshot_counts()
    expected_gen: dict[tuple[str, str], int] = {}
    swaps = 0
    start = time.monotonic()
    for k, (ns, name) in enumerate(binding_keys):
        obj = cluster.get("EndpointGroupBinding", ns, name)
        obj.spec.weight = 50
        partner = swap_partner(k, n)
        if partner is not None:
            obj.spec.service_ref = ServiceReference(name=f"bench{partner:04d}")
            swaps += 1
        updated = cluster.update("EndpointGroupBinding", obj)
        expected_gen[(ns, name)] = updated.metadata.generation

    def churned() -> bool:
        for (ns, name), gen in expected_gen.items():
            obj = cluster.get("EndpointGroupBinding", ns, name)
            if obj.status.observed_generation < gen or len(obj.status.endpoint_ids) != 1:
                return False
        return True

    while time.monotonic() < deadline:
        if churned():
            break
        time.sleep(0.1)
    elapsed = time.monotonic() - start
    if not churned():
        raise SystemExit("EGB churn did not converge within deadline")

    # verify against AWS through a separate unshaped handle: the check
    # costs neither quota nor measured-call accounting, while any
    # background controller call landing in this window (e.g. the
    # per-binding resync DescribeLoadBalancers) stays shaped and
    # counted — no global toggle flip (ADVICE r5 #2)
    raw = aws.unshaped()
    for k, (ns, name) in enumerate(binding_keys):
        obj = cluster.get("EndpointGroupBinding", ns, name)
        group = raw.describe_endpoint_group(obj.spec.endpoint_group_arn)
        weights = {d.endpoint_id: d.weight for d in group.endpoint_descriptions}
        bound = obj.status.endpoint_ids[0]
        if weights.get(bound) != 50:
            raise SystemExit(
                f"churn verification failed: {ns}/{name} bound={bound} weights={weights}"
            )
        # the group also holds its pre-existing out-of-band
        # endpoint, so status ids must be a subset, never equal
        if not set(obj.status.endpoint_ids) <= set(weights):
            raise SystemExit(
                f"churn verification failed: {ns}/{name} status id not bound in AWS"
            )
    return {
        "n_bindings": len(binding_keys),
        "weight_edits": len(binding_keys),
        "ref_swaps": swaps,
        "elapsed_s": round(elapsed, 1),
        "aws_calls_by_op": _ops_delta(before, aws.snapshot_counts()),
        "verified": "every status endpoint id bound in AWS with the edited weight",
    }


# ---------------------------------------------------------------------------
# drift-tick phase
# ---------------------------------------------------------------------------

def _wait_quiescent(aws: ShapedAWS, quiet_need: float, deadline: float) -> int:
    """Block until no AWS call lands for ``quiet_need`` seconds;
    returns the stable total."""
    last = aws.total_calls()
    quiet_since = time.monotonic()
    while time.monotonic() < deadline:
        time.sleep(0.1)
        cur = aws.total_calls()
        if cur != last:
            last, quiet_since = cur, time.monotonic()
        elif time.monotonic() - quiet_since >= quiet_need:
            return last
    raise SystemExit("drift-tick phase: fleet never went AWS-quiescent")


def run_drift_tick(n: int, workers: int) -> dict:
    """Measure ONE full --drift-resync-period tick over a converged
    mixed fleet of ``n`` Services (+ n/10 Ingresses + n/10
    EndpointGroupBindings).

    The tick is driven explicitly: the manager runs with a dormant
    ticker period (large enough never to fire, but > 0 so the EGB
    converged path verifies the actual endpoint group exactly as a
    real tick would), the fleet converges and goes AWS-quiescent
    (equal resync updates are skipped), then every controller's OWN
    ``drift_resync_sources()`` wiring — the same lister/predicate/
    enqueue triples the in-process ticker consumes — is walked once.
    Everything that lands after that IS one tick, measured to the
    call by quiescence bracketing.

    Shaping is disabled for the whole phase (convergence in seconds,
    counters exact); tick WALL time under quota is then derived from
    the same token-bucket model the shaped phases enforce: max over
    families of (calls - burst) / rate.

    Cache TTLs here are the drift-scale operating point
    (docs/operations.md "Drift resync at scale"): the discovery and
    zone snapshots at the drift period (300 s — at tick periods the
    default 30/60 s would just expire between ticks and re-load
    mid-tick), and the verification read plane at a ~1 s tick scope so
    every chain/zone/LB is genuinely RE-READ by the measured tick —
    entries seeded during convergence are stale by tick time, which is
    exactly the freshness contract (writes never count as
    verification)."""
    n_ing, n_egb = scaled_counts(n)
    cluster = FakeCluster()
    aws = ShapedAWS(quota_accelerators=n + n_ing + n_egb + 50)
    plane = ReadPlane(
        discovery_ttl=300.0,
        zone_ttl=300.0,
        topology_verify_ttl=1.0,
        topology_full_ttl=3600.0,
        record_ttl=1.0,
        lb_ttl=1.0,
    )
    zones, group_arns = prepare_aws(aws, n, n_ing, n_egb)
    aws.shaping_enabled = False
    base_chain = aws.chain_counts()

    stop = threading.Event()
    dormant = 10 * DEADLINE  # > 0 activates drift verify; never fires
    config = ControllerConfig(
        global_accelerator=GlobalAcceleratorConfig(
            workers=workers, queue_qps=100000.0, queue_burst=100000,
            drift_resync_period=dormant,
        ),
        route53=Route53Config(
            workers=workers, queue_qps=100000.0, queue_burst=100000,
            drift_resync_period=dormant,
        ),
        endpoint_group_binding=EndpointGroupBindingConfig(
            workers=workers, queue_qps=100000.0, queue_burst=100000,
            drift_resync_period=dormant,
        ),
        # GC sweeper with a dormant interval: sweeps are driven
        # explicitly below (the drift_tick pattern) so the phase
        # measures exactly two sweeps over a fully-live fleet
        garbage_collector=GarbageCollectorConfig(
            interval=dormant, grace_sweeps=2, max_deletes=10
        ),
    )
    # the informer resync is dormant too (not RESYNC_PERIOD): a 30s
    # resync firing during the tick drain would attribute its
    # per-binding DescribeLoadBalancers to the tick counts — the
    # quiescence bracket (quiet_need=1.5s) is far shorter than the
    # resync period, so it cannot wait one out (ADVICE r5 #3).
    # Convergence is watch-driven; the resync safety net is exercised
    # by the soak/chaos tiers, not this measurement.
    manager = Manager(resync_period=dormant, metrics_registry=obs_metrics.registry())
    try:
        manager.run(
            cluster,
            config,
            stop,
            cloud_factory=lambda region: AWSDriver(
                aws, aws, aws,
                accelerator_missing_retry=60.0 / LATENCY_SCALE,
                **plane.driver_kwargs(),
            ),
            block=False,
        )
        binding_keys = create_objects(cluster, n, n_ing, n_egb, group_arns)
        deadline = time.monotonic() + DEADLINE

        def converged() -> bool:
            return fleet_converged(
                aws, cluster, zones, binding_keys, base_chain, n, n_ing
            )

        if not wait_converged(
            converged, lambda: fleet_progress(aws, cluster, zones, binding_keys), deadline
        ):
            raise SystemExit("drift-tick phase: fleet did not converge")

        quiet_need = 1.5
        _wait_quiescent(aws, quiet_need, deadline)
        before = aws.snapshot_counts()
        tick_start = time.monotonic()
        # one tick: exactly what the in-process ticker's loop does,
        # through the controllers' own canonical source wiring
        manager.drift_tick()
        _wait_quiescent(aws, quiet_need, deadline)
        drain = round(time.monotonic() - tick_start - quiet_need, 2)
        tick_ops = _ops_delta(before, aws.snapshot_counts())
        # GC-sweep phase (ISSUE 4): two explicit sweeps over the same
        # converged, fully-live fleet — at scale the sweeper must find
        # zero orphans and delete NOTHING (the zero-false-positive bar
        # the chaos tier's orphan storm drills at N=25), and the sweep
        # counters land in bench_detail.json
        gc_before = aws.snapshot_counts()
        manager.gc_sweep()
        gc_report = manager.gc_sweep()
        gc_ops = _ops_delta(gc_before, aws.snapshot_counts())
        gc_status = manager.gc_status()
        if gc_status.get("deleted_total", 0):
            raise SystemExit(
                f"gc sweep falsely deleted live resources: {gc_status}"
            )
    finally:
        stop.set()

    family_calls: dict[str, int] = {}
    for op, count in tick_ops.items():
        family_calls[OP_FAMILY[op]] = family_calls.get(OP_FAMILY[op], 0) + count
    derived = {
        family: round(max(0.0, (calls - QUOTAS[family][1]) / QUOTAS[family][0]), 1)
        for family, calls in sorted(family_calls.items())
    }
    wall_bound = max(derived.values(), default=0.0)
    return {
        "n_services": n,
        "n_ingresses": n_ing,
        "n_bindings": n_egb,
        "aws_calls_total": sum(tick_ops.values()),
        "aws_calls_by_op": tick_ops,
        "aws_calls_by_family": dict(sorted(family_calls.items())),
        "unthrottled_drain_s": drain,
        # the quota model is the same one the shaped phases enforce;
        # with it, a tick's wall time is bounded below by the binding
        # family's (calls - burst) / rate
        "derived_tick_seconds_by_family_scaled": derived,
        "derived_tick_seconds_scaled": wall_bound,
        "derived_tick_seconds_real_quotas": round(wall_bound * LATENCY_SCALE, 1),
        "cache_stats": plane.stats(),
        # degraded-mode marker (health plane): which controllers this
        # tick enqueued vs skipped over open circuits, and whether the
        # tick is therefore partial/stale — a healthy bench run reads
        # partial=False; a brownout tick says so instead of silently
        # under-reading (ISSUE 3)
        "health": manager.last_drift_report,
        # orphan-GC sweep over the converged fleet (ISSUE 4): the
        # second sweep's counters + cumulative status; a healthy fleet
        # reads candidates 0 / deleted 0 (zero false positives at
        # scale), and aws_calls shows the two sweeps' read cost
        # (discovery snapshot + per-zone record lists via the read
        # plane)
        "gc_sweep": {
            "last_sweep": gc_report,
            "status": gc_status,
            "aws_calls": sum(gc_ops.values()),
        },
        "note": (
            "counts measured over one isolated ticker round on a converged "
            "fleet (coalesced read plane at ~1 s tick scope so the round "
            f"genuinely re-reads AWS); quotas are x{LATENCY_SCALE:g} "
            f"scaled, so real-world tick wall time is x{LATENCY_SCALE:g} the "
            "scaled bound — see docs/operations.md 'Drift resync at scale'"
        ),
    }


# ---------------------------------------------------------------------------
# sharding phase (ISSUE 8): 2-shard REAL subprocesses over one durable
# fake account
# ---------------------------------------------------------------------------

# fleet size of the multi-process phase; the CI smoke test shrinks it
# (speedup is only asserted at >= SHARD_GATE_MIN_N — tiny fleets are
# dominated by process startup, not throughput)
SHARD_N = int(os.environ.get("AGAC_BENCH_SHARD_N", "200"))
SHARD_WORKERS = int(os.environ.get("AGAC_BENCH_SHARD_WORKERS", "8"))
# per-call wire latency shaping the subprocesses (AGAC_FAKE_LATENCY):
# throughput is then bound by each process's worker pool x latency —
# the per-process capacity model sharding divides.  0.3 s sits in the
# real-world GA mutate p50 band (0.15 undershot it and turned the
# 4/8-shard points CPU-bound on shared-core hosts, measuring the
# bench host instead of the architecture).
SHARD_LATENCY = float(os.environ.get("AGAC_BENCH_SHARD_LATENCY", "0.3"))
# the global per-service AWS budget (calls/s): each replica's AIMD
# ceiling is budget x owned/shard_count, so the fleet aggregate can
# never exceed it — asserted from measured call rates below
SHARD_BUDGET_QPS = float(os.environ.get("AGAC_BENCH_SHARD_BUDGET", "400"))
SHARD_MIN_SPEEDUP = 1.7
SHARD_GATE_MIN_N = 100
# the scaling-curve sweep (ISSUE 10): shard widths measured over real
# subprocesses; the CI smoke shrinks this to "1,2".  Width 1 anchors
# the curve; every width's fleet AIMD-ceiling sum and aggregate call
# rate is asserted within the global budget.
SHARD_WIDTHS = tuple(
    int(w)
    for w in os.environ.get("AGAC_BENCH_SHARD_WIDTHS", "1,2,4,8").split(",")
    if w.strip()
)
# the 4-shard efficiency gate: aggregate >= 0.75 x (4 x single-shard)
# — i.e. >= 3.0x the single-shard headline (acceptance, ISSUE 10)
SHARD_MIN_EFFICIENCY_4 = 0.75

SHARD_LB_NAME = "shardlb"
SHARD_LB_HOSTNAME = "shardlb-0123456789abcdef.elb.us-west-2.amazonaws.com"


def _shard_service(i: int) -> Service:
    svc = Service(
        metadata=ObjectMeta(
            name=f"shard{i:04d}",
            namespace="default",
            annotations={
                AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
                AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external",
            },
        ),
        spec=ServiceSpec(
            type="LoadBalancer",
            ports=[ServicePort(name="http", port=80, protocol="TCP")],
        ),
    )
    svc.status.load_balancer.ingress.append(
        LoadBalancerIngress(hostname=SHARD_LB_HOSTNAME)
    )
    return svc


def _scrape_shard_process(port: int) -> dict:
    """One subprocess's telemetry: per-service AWS call totals off
    /metrics, per-service AIMD ceilings off /readyz, and the shard
    assignment off /healthz — the same wires an operator scrapes."""
    calls: dict[str, float] = {}
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ) as response:
        metrics_text = response.read().decode()
    for line in metrics_text.splitlines():
        if line.startswith("agac_aws_api_calls_total{"):
            labels, value = line.rsplit(" ", 1)
            service = labels.split('service="')[1].split('"')[0]
            # elbv2[region] folds into elbv2: the budget is per
            # service family here
            service = service.split("[", 1)[0]
            calls[service] = calls.get(service, 0.0) + float(value)
    ceilings: dict[str, float] = {}
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/readyz", timeout=5
        ) as response:
            ready = json.loads(response.read())
    except urllib.error.HTTPError as err:  # 503 while a circuit is open
        ready = json.loads(err.read())
    for service, snap in ready.get("services", {}).items():
        if "aimd_ceiling" in snap:
            family = service.split("[", 1)[0]
            ceilings[family] = ceilings.get(family, 0.0) + snap["aimd_ceiling"]
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=5
    ) as response:
        sharding = json.loads(response.read())["sharding"]
    return {
        "calls": calls,
        "ceilings": ceilings,
        "sharding": sharding,
        "metrics_text": metrics_text,
    }


def _run_shard_fleet(shard_count: int, replicas: int, n: int) -> dict:
    """Converge ``n`` Services through ``replicas`` REAL controller
    subprocesses sharing one durable fake account (flock-arbitrated
    state file) and one embedded apiserver; returns throughput and
    per-replica telemetry."""
    import socket
    import subprocess
    import tempfile

    import yaml

    from agac_tpu.cloudprovider.aws.fake_backend import FileBackedFakeAWSBackend
    from agac_tpu.cluster.rest import RestClusterClient
    from agac_tpu.cluster.testserver import TestApiServer

    tmp = tempfile.mkdtemp(prefix="agac-shard-bench-")
    state_path = os.path.join(tmp, "aws-state.json")
    repo = os.path.dirname(os.path.abspath(__file__))

    def free_port() -> int:
        with socket.socket() as sock:
            sock.bind(("127.0.0.1", 0))
            return sock.getsockname()[1]

    with TestApiServer() as server:
        kubeconfig_path = os.path.join(tmp, "kubeconfig")
        with open(kubeconfig_path, "w") as f:
            yaml.safe_dump(
                {
                    "current-context": "bench",
                    "contexts": [
                        {"name": "bench", "context": {"cluster": "c", "user": "u"}}
                    ],
                    "clusters": [{"name": "c", "cluster": {"server": server.url}}],
                    "users": [{"name": "u", "user": {}}],
                },
                f,
            )
        client = RestClusterClient(server.url)
        env = dict(
            os.environ,
            AGAC_CLOUD="fake",
            AGAC_FAKE_STATE=state_path,
            AGAC_FAKE_LBS=f"{SHARD_LB_NAME}={SHARD_LB_HOSTNAME}",
            AGAC_FAKE_LATENCY=str(SHARD_LATENCY),
            AGAC_FAKE_QUOTA_ACCELERATORS=str(n + 20),
            POD_NAMESPACE="kube-system",
            AGAC_API_HEALTH_AIMD_QPS=str(SHARD_BUDGET_QPS),
            # throughput-grade lease timing: the sweep measures the
            # scaling curve, not failover (the process drills do), and
            # at 8 busy python processes on shared cores a sub-2s renew
            # deadline reads a GIL pause as a crash — the spurious
            # steal + reshard resync then serializes the whole fleet
            AGAC_LEASE_DURATION="15",
            AGAC_LEASE_RENEW_DEADLINE="8",
            AGAC_LEASE_RETRY_PERIOD="0.5",
            AGAC_ACCELERATOR_MISSING_RETRY="0.1",
            AGAC_LB_NOT_ACTIVE_RETRY="0.1",
            AGAC_POLL_INTERVAL="0.02",
            AGAC_POLL_TIMEOUT="5",
        )
        ports = [free_port() for _ in range(replicas)]
        processes = []
        for port in ports:
            argv = [
                sys.executable, "-m", "agac_tpu", "controller",
                "--kubeconfig", kubeconfig_path,
                "-c", "bench-shard",
                "-w", str(SHARD_WORKERS),
                "--queue-qps", "1000", "--queue-burst", "1000",
                "--health-port", str(port),
                "--shard-count", str(shard_count),
            ]
            if shard_count > 1:
                argv += ["--shards-per-replica", "1"]
            else:
                argv += ["--disable-leader-election"]
            processes.append(
                subprocess.Popen(
                    argv, cwd=repo, env=env,
                    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                )
            )
        try:
            # every shard lease held before the clock starts (startup
            # is measured by the process drills, not this phase)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    views = [_scrape_shard_process(port)["sharding"] for port in ports]
                except Exception:
                    time.sleep(0.2)
                    continue
                if shard_count == 1:
                    break
                held = set().union(
                    *[set(view.get("owned", ())) for view in views if view.get("enabled")]
                )
                if held == set(range(shard_count)):
                    break
                time.sleep(0.2)

            t0 = time.monotonic()
            # parallel creates: the serial REST loop is width-
            # independent fixed cost, but at 4-8 shard aggregate
            # speeds it eats a visible slice of the timed window —
            # fan it out so the sweep measures the FLEET, not the
            # bench's own client
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=8) as pool:
                list(
                    pool.map(
                        lambda i: client.create("Service", _shard_service(i)),
                        range(n),
                    )
                )
            aws = FileBackedFakeAWSBackend(state_path)
            while time.monotonic() - t0 < DEADLINE:
                accelerators, listeners, groups = aws.chain_counts()
                if accelerators == listeners == groups == n:
                    break
                time.sleep(0.3)
            else:
                raise SystemExit(
                    f"sharding phase ({shard_count} shards): fleet never "
                    f"converged ({aws.chain_counts()} of {n})"
                )
            elapsed = time.monotonic() - t0
            per_replica = [_scrape_shard_process(port) for port in ports]
        finally:
            for process in processes:
                process.terminate()
            for process in processes:
                try:
                    process.wait(10)
                except Exception:
                    process.kill()
    calls_by_service: dict[str, float] = {}
    for replica in per_replica:
        for service, count in replica["calls"].items():
            calls_by_service[service] = calls_by_service.get(service, 0.0) + count
    # the fleet-merged convergence view (ISSUE 9): every replica's
    # journey histograms summed through the fleet-merge layer — the
    # ONLY correct way to state a fleet-wide p99 (averaging per-shard
    # percentiles would be statistically meaningless)
    fleet_families, _ = obs_fleet.merge_expositions(
        {
            f"replica-{i}": replica["metrics_text"]
            for i, replica in enumerate(per_replica)
        }
    )
    return {
        "shard_count": shard_count,
        "replicas": replicas,
        "n_objects": n,
        "elapsed_s": round(elapsed, 2),
        "objects_per_sec": round(n / elapsed, 2),
        "convergence": obs_fleet.converge_percentiles(fleet_families),
        "aws_calls_by_service": {k: int(v) for k, v in sorted(calls_by_service.items())},
        "aggregate_calls_per_sec_by_service": {
            service: round(count / elapsed, 2)
            for service, count in sorted(calls_by_service.items())
        },
        "per_replica": [
            {
                "owned_shards": replica["sharding"].get("owned", []),
                "quota_fraction": replica["sharding"].get("quota_fraction"),
                "aimd_ceilings": replica["ceilings"],
                "aws_calls": {k: int(v) for k, v in sorted(replica["calls"].items())},
                # per-replica journey totals, so the fleet-merged
                # count is checkable against its parts
                "journey_converged": int(
                    obs_fleet.converge_percentiles(
                        obs_fleet.merge_expositions(
                            {"self": replica["metrics_text"]}
                        )[0]
                    )["ga"]["count"]
                ),
            }
            for replica in per_replica
        ],
    }


def _assert_run_within_budget(run: dict) -> None:
    """The quota-division contract at ONE width: the fleet AGGREGATE
    never exceeds the global per-service budget — in measured call
    rates AND in the structural sum of the live replicas' AIMD
    ceilings."""
    width = run["shard_count"]
    for service, rate in run["aggregate_calls_per_sec_by_service"].items():
        if rate > SHARD_BUDGET_QPS * 1.001:
            raise SystemExit(
                f"sharding phase ({width} shards): aggregate {service} call "
                f"rate {rate}/s exceeds the global budget {SHARD_BUDGET_QPS}/s"
            )
    ceiling_sums: dict[str, float] = {}
    for replica in run["per_replica"]:
        for service, ceiling in replica["aimd_ceilings"].items():
            ceiling_sums[service] = ceiling_sums.get(service, 0.0) + ceiling
    for service, total in ceiling_sums.items():
        if total > SHARD_BUDGET_QPS * 1.001:
            raise SystemExit(
                f"sharding phase ({width} shards): summed {service} AIMD "
                f"ceilings {total}/s exceed the global budget "
                f"{SHARD_BUDGET_QPS}/s — quota division is broken"
            )
    run["aimd_ceiling_sums"] = {
        service: round(total, 2) for service, total in sorted(ceiling_sums.items())
    }


def _filter_overhead_ns(width: int, keys: list) -> float:
    """Median-ish per-lookup cost of the memoized ShardFilter at one
    width (warm memo — the steady-state enqueue/drift/GC gate cost)."""
    from agac_tpu.sharding import HashRing, ShardFilter

    owned = frozenset(range(max(1, width // 2)))
    shard_filter = ShardFilter(HashRing(width), lambda: owned)
    for key in keys:  # warm the memo: the ring walk happens HERE
        shard_filter.owns_key(key)
    best = float("inf")
    for _ in range(3):
        start = time.perf_counter()
        for key in keys:
            shard_filter.owns_key(key)
        best = min(best, time.perf_counter() - start)
    return best * 1e9 / len(keys)


def run_sharding_phase() -> dict:
    """The scaling-curve sweep (ISSUE 10): converge the same fleet at
    every width in SHARD_WIDTHS (default 1/2/4/8) over real controller
    subprocesses sharing one flock-arbitrated durable account.  Every
    width asserts the quota-division invariant (aggregate call rate
    and summed AIMD ceilings within the global budget); at full scale
    the curve is gated — 2-shard aggregate >= 1.7x single, 4-shard
    efficiency >= 0.75 (i.e. >= 3.0x single).  A memoized-filter
    micro-benchmark asserts the ownership-gate cost stays flat across
    widths."""
    if 1 not in SHARD_WIDTHS:
        raise SystemExit("sharding sweep needs width 1 (the curve's anchor)")
    runs: dict[int, dict] = {}
    for width in SHARD_WIDTHS:
        _progress(
            f"sharding: {width}-shard fleet over {SHARD_N} services "
            f"({width} replicas x {SHARD_WORKERS} workers, "
            f"{SHARD_LATENCY:g}s call latency)"
        )
        runs[width] = _run_shard_fleet(width, width, SHARD_N)
        _progress(
            f"sharding: {width}-shard aggregate "
            f"{runs[width]['objects_per_sec']} objects/s in "
            f"{runs[width]['elapsed_s']}s"
        )
        _assert_run_within_budget(runs[width])
    single = runs[1]
    sweep: dict[str, dict] = {}
    for width, run in sorted(runs.items()):
        efficiency = round(
            run["objects_per_sec"]
            / max(width * single["objects_per_sec"], 1e-9),
            3,
        )
        sweep[str(width)] = {
            "objects_per_sec": run["objects_per_sec"],
            "elapsed_s": run["elapsed_s"],
            "speedup": round(
                run["objects_per_sec"] / max(single["objects_per_sec"], 1e-9), 2
            ),
            "efficiency": efficiency,
            "aimd_ceiling_sums": run["aimd_ceiling_sums"],
            "ga_converge_p99_s": run["convergence"]["ga"]["p99_s"],
        }
    # the memoized ShardFilter micro-assert (ISSUE 10 satellite): the
    # ownership gate's steady-state cost must not grow with width —
    # a dict hit either way, pinned here so a regression to per-call
    # ring walks shows up in the bench, not in production profiles
    micro_keys = [f"ns{i % 10}/bench-{i:05d}" for i in range(2000)]
    filter_overhead = {
        str(width): round(_filter_overhead_ns(width, micro_keys), 1)
        for width in sorted(runs)
    }
    overheads = list(filter_overhead.values())
    if max(overheads) > 6 * max(min(overheads), 0.001) and max(overheads) > 2000:
        raise SystemExit(
            f"sharding phase: memoized filter overhead is not flat across "
            f"widths: {filter_overhead} ns/lookup"
        )
    speedup = sweep.get("2", {}).get("speedup", 0.0)
    phase = {
        "single": single,
        # the 2-shard run keeps its dedicated block (the PR 8 output
        # contract); the full curve lives in "sweep"
        "sharded": runs.get(2, single),
        "speedup": speedup,
        "sweep": sweep,
        "widths": sorted(runs),
        "filter_overhead_ns_by_width": filter_overhead,
        "quota_budget_per_service_qps": SHARD_BUDGET_QPS,
        "workers_per_replica": SHARD_WORKERS,
        "call_latency_s": SHARD_LATENCY,
        "note": (
            "real controller subprocesses over one flock-arbitrated durable "
            "fake account; per-process capacity = workers x call latency, "
            "divided AIMD budget = global x owned/shard_count; efficiency = "
            "aggregate / (width x single)"
        ),
    }
    if SHARD_N >= SHARD_GATE_MIN_N:
        if 2 in runs and speedup < SHARD_MIN_SPEEDUP:
            raise SystemExit(
                f"sharding phase: 2-shard aggregate is only {speedup}x the "
                f"single-shard headline (bar: {SHARD_MIN_SPEEDUP}x) — see "
                "bench_detail.json sharding block"
            )
        if 4 in runs and sweep["4"]["efficiency"] < SHARD_MIN_EFFICIENCY_4:
            raise SystemExit(
                f"sharding phase: 4-shard efficiency "
                f"{sweep['4']['efficiency']} below the "
                f"{SHARD_MIN_EFFICIENCY_4} gate "
                f"({sweep['4']['objects_per_sec']} vs "
                f"{single['objects_per_sec']} objects/s single) — see "
                "bench_detail.json sharding.sweep"
            )
    return phase


# the autoscaler reaction benchmark (ISSUE 13): one load-wave run of
# the closed-loop sim scenario, plus its observe-only twin.  The sim
# harness installs the process-global virtual clock, so both runs are
# subprocess-isolated from this real-clock bench — same reason the
# sharding phase forks.
AUTOSCALE_SEED = int(os.environ.get("AGAC_BENCH_AUTOSCALE_SEED", "1"))
AUTOSCALE_PROFILE = os.environ.get("AGAC_BENCH_AUTOSCALE_PROFILE", "mini")

_AUTOSCALE_CHILD = r"""
import json
import sys

from agac_tpu.autoscaler import ACTION_IN, ACTION_OUT
from agac_tpu.sim import fuzz

observe_only = sys.argv[1] == "observe"
result = fuzz.run_autoscale_scenario(
    int(sys.argv[2]), profile=sys.argv[3], observe_only=observe_only
)
auto = result.stats["autoscale"]
outs = [t for t, action, _ in auto["executed"] if action == ACTION_OUT]
ins = [t for t, action, _ in auto["executed"] if action == ACTION_IN]
print(json.dumps({
    "violations": result.violations,
    "trace_hash": result.trace_hash,
    "wave_at_s": fuzz._WAVE_AT,
    "decisions": auto["decisions"],
    "suppressed_recommendations": auto["suppressed_recommendations"],
    "executed": auto["executed"],
    "first_scale_out_at_s": outs[0] if outs else None,
    "first_scale_in_at_s": ins[0] if ins else None,
    "virtual_s": result.stats["virtual_time"],
    "aws_calls": result.stats["aws_calls"],
}))
"""


def _run_autoscale_child(observe_only: bool) -> dict:
    import subprocess

    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            _AUTOSCALE_CHILD,
            "observe" if observe_only else "act",
            str(AUTOSCALE_SEED),
            AUTOSCALE_PROFILE,
        ],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if proc.returncode != 0:
        raise SystemExit(
            f"autoscaler phase: scenario subprocess failed:\n"
            f"{proc.stderr[-2000:]}"
        )
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    return json.loads(lines[-1])


def run_autoscaler_phase() -> dict:
    """The SLO-driven autoscaler's reaction time (ISSUE 13): the load
    wave blows the convergence objective at a known virtual instant;
    the closed loop (burn/age signals -> policy -> live 2->4 resize)
    must notice and act.  Reported as virtual seconds from the wave
    start: ``spike_to_scale_out_s`` is the executed scale-out,
    ``spike_to_scale_in_s`` is the scale-back — which by construction
    marks sustained sub-threshold burn on every SLO window (the p99
    restored, the headroom streak and cooldown served).  The
    observe-only twin runs the identical wave + fault and must record
    suppressed recommendations while never requesting a resize."""
    acting = _run_autoscale_child(observe_only=False)
    if acting["violations"]:
        raise SystemExit(
            f"autoscaler phase: load-wave scenario violated its oracles: "
            f"{acting['violations']}"
        )
    if acting["first_scale_out_at_s"] is None:
        raise SystemExit("autoscaler phase: wave produced no executed scale-out")
    reaction = round(acting["first_scale_out_at_s"] - acting["wave_at_s"], 1)
    restored = round(acting["first_scale_in_at_s"] - acting["wave_at_s"], 1)
    _progress(
        f"autoscaler: scale-out {reaction}s after the wave, "
        f"scaled back in at +{restored}s (virtual; seed {AUTOSCALE_SEED})"
    )
    observe = _run_autoscale_child(observe_only=True)
    if observe["violations"]:
        raise SystemExit(
            f"autoscaler phase: observe-only scenario violated its oracles: "
            f"{observe['violations']}"
        )
    if observe["executed"]:
        raise SystemExit(
            f"autoscaler phase: observe-only run executed a resize: "
            f"{observe['executed']}"
        )
    _progress(
        f"autoscaler: observe-only twin suppressed "
        f"{observe['suppressed_recommendations']} recommendations, 0 resizes"
    )
    return {
        "seed": AUTOSCALE_SEED,
        "profile": AUTOSCALE_PROFILE,
        "wave_at_s": acting["wave_at_s"],
        "spike_to_scale_out_s": reaction,
        "spike_to_scale_in_s": restored,
        "decisions": acting["decisions"],
        "executed": acting["executed"],
        "virtual_s": acting["virtual_s"],
        "aws_calls": acting["aws_calls"],
        "trace_hash": acting["trace_hash"],
        "observe_only": {
            "decisions": observe["decisions"],
            "suppressed_recommendations": observe["suppressed_recommendations"],
            "executed": observe["executed"],
            "trace_hash": observe["trace_hash"],
        },
        "note": (
            "virtual seconds on the sim scheduler, wave starts at wave_at_s; "
            "scale-in certifies sustained sub-threshold burn on every SLO "
            "window (p99 restored) plus the headroom streak and cooldown; "
            "the scenario's own oracles (reaction budget, SLO verdict, "
            "no-oscillation, flight-record completeness) all passed"
        ),
    }


# ---------------------------------------------------------------------------
# profiling phase (ISSUE 14)
# ---------------------------------------------------------------------------

def run_profiling_phase() -> dict:
    """The continuous-profiling plane measured against itself: the
    same tuned convergence workload runs twice — once with the stage
    accountant disabled (control), once with the accountant armed AND
    the sampling profiler walking every stack at its default hz — and
    the profiled run must hold within ``PROFILE_MAX_OVERHEAD_PCT`` of
    the control's objects/s.  The profiled run's exclusive-time
    attribution table (per-stage CPU/wall + ns/reconcile) and the
    sampler's folded top table go to bench_detail; the table must name
    the production hot-path stages or the accountant has come unwired
    from the reconcile loop."""
    kwargs = dict(
        workers=TUNED_WORKERS,
        cache_ttl=30.0,
        zone_cache_ttl=60.0,
        qps=1000.0,
        burst=1000,
        read_plane_ttl=15.0,
        pipeline=True,
    )
    _progress(f"profiling: control run ({PROFILE_N} services, accountant off)")
    obs_profile.configure(stages=False)
    try:
        control = run_convergence(PROFILE_N, **kwargs)
    finally:
        obs_profile.configure(stages=True)
    _progress(
        f"profiling: profiled run (accountant on + sampler at "
        f"{obs_stackprof.DEFAULT_HZ:g} hz)"
    )
    obs_profile.reset_aggregate()
    sampler_stop = threading.Event()
    sampler = obs_stackprof.StackProfiler()
    sampler_thread = sampler.start(sampler_stop)
    try:
        profiled = run_convergence(PROFILE_N, **kwargs)
    finally:
        sampler_stop.set()
        if sampler_thread is not None:
            sampler_thread.join(timeout=5.0)
    snap = obs_profile.aggregate_snapshot()
    table = obs_profile.attribution_table()
    overhead_pct = round(
        max(
            0.0,
            (control["objects_per_sec"] - profiled["objects_per_sec"])
            / max(control["objects_per_sec"], 1e-9)
            * 100.0,
        ),
        2,
    )
    # the named production stages the attribution table must carry —
    # aws:* per-op stages ride on top of these
    stages_seen = sorted(
        row["stage"] for row in table
        if not row["stage"].startswith(obs_profile.API_STAGE_PREFIX)
    )
    if len(stages_seen) < 5:
        raise SystemExit(
            f"profiling phase: attribution table names only {stages_seen} — "
            "the stage accountant has come unwired from the reconcile hot "
            "path (expected queue-pop/informer-lookup/serialize/"
            "driver-mutate/self-tax at minimum)"
        )
    # the overhead gate is only meaningful once throughput is genuinely
    # quota-bound (same doctrine as the ga_mutate floor assertion):
    # tiny smoke fleets never leave the burst and are all noise
    quota_bound = profiled["ga_mutate_calls"] > 2 * QUOTAS["ga_mutate"][1]
    if quota_bound and overhead_pct > PROFILE_MAX_OVERHEAD_PCT:
        raise SystemExit(
            f"profiling phase: profiler overhead {overhead_pct}% exceeds the "
            f"{PROFILE_MAX_OVERHEAD_PCT}% gate (control "
            f"{control['objects_per_sec']} obj/s vs profiled "
            f"{profiled['objects_per_sec']} obj/s) — a hot-path stage has "
            "grown real cost; see profile.table in bench_detail.json"
        )
    total_cpu = sum(row["cpu_seconds"] for row in table)
    reconciles = snap["reconciles"]
    reconcile_cpu_us = int(total_cpu / max(1, reconciles) * 1e6)
    _progress(
        f"profiling: overhead {overhead_pct}% "
        f"({'gated' if quota_bound else 'reported only — not quota-bound'}), "
        f"{reconcile_cpu_us} us CPU/reconcile across {len(table)} stages"
    )
    sampler_top = sampler.aggregate.top(10)
    return {
        "n_services": PROFILE_N,
        "control_objects_per_sec": control["objects_per_sec"],
        "profiled_objects_per_sec": profiled["objects_per_sec"],
        "overhead_pct": overhead_pct,
        "overhead_gated": quota_bound,
        "max_overhead_pct": PROFILE_MAX_OVERHEAD_PCT,
        "reconciles": reconciles,
        "reconcile_cpu_us": reconcile_cpu_us,
        "stages_seen": stages_seen,
        # exclusive-time ranking: every row's cpu excludes its
        # children, so the column sums to the measured total
        "table": table,
        "sampler": {
            "hz": sampler.hz,
            "samples": sampler.aggregate.samples,
            "top": sampler_top,
        },
    }


def main():
    klog.init(verbosity=-1)
    import logging

    logging.getLogger("agac").setLevel(logging.CRITICAL)
    # the observability plane's scrape endpoint (ISSUE 5): the bench
    # serves the REAL /metrics handler over the process-global
    # registry the instrumented hot paths feed, and snapshots it at
    # the end of every phase — the same wire an operator's Prometheus
    # would scrape
    from agac_tpu.manager import make_health_server

    metrics_server = make_health_server(0, metrics_registry=obs_metrics.registry())
    metrics_port = metrics_server.server_address[1]
    threading.Thread(
        target=metrics_server.serve_forever, daemon=True, name="bench-metrics"
    ).start()
    # baseline: the reference's operating point — 1 worker per queue,
    # client-go's fixed 10 qps/100 burst enqueue bucket, full O(N)+1
    # tag-scan discovery on every reconcile (N_BASELINE objects; see
    # module docstring for why the subset favors the baseline)
    _progress(f"baseline: converging {N_BASELINE}+{sum(scaled_counts(N_BASELINE))} objects at the reference operating point")
    baseline = run_convergence(N_BASELINE, workers=1, cache_ttl=0.0, qps=10.0, burst=100)
    baseline["metrics_snapshot"] = scrape_metrics(metrics_port)
    _progress(f"baseline: {baseline['objects_per_sec']} objects/s in {baseline['elapsed_s']}s")
    # measured: this framework's tuned production configuration —
    # the documented 8-16 worker band's top, raised enqueue bucket,
    # incremental discovery caches (AGAC_DISCOVERY_CACHE_TTL +
    # AGAC_ZONE_CACHE_TTL) — against the full N.  Under the realistic
    # quota model throughput is GA-mutate-quota-bound and plateaus
    # from 8 workers up (docs/operations.md "Sizing the worker pool")
    _progress(f"tuned: converging {N_SERVICES}+{sum(scaled_counts(N_SERVICES))} objects at workers={TUNED_WORKERS}")
    tuned = run_convergence(
        N_SERVICES,
        workers=TUNED_WORKERS,
        # 30 s: with the write journal the cache never masks local
        # writes, so TTL only bounds cross-process staleness — the
        # same 30 s the reference tolerates between informer resyncs
        cache_ttl=30.0,
        zone_cache_ttl=60.0,
        qps=1000.0,
        burst=1000,
        measure_steady_state=True,
        churn=True,
        # the production default read-plane tick scope (ISSUE 2):
        # verification reads coalesce within 15 s windows
        read_plane_ttl=15.0,
        # the async mutation pipeline (ISSUE 6): non-blocking settle,
        # per-zone Route53 change batching, interleaved GA chains
        pipeline=True,
    )
    tuned["metrics_snapshot"] = scrape_metrics(metrics_port)
    _progress(f"tuned: {tuned['objects_per_sec']} objects/s in {tuned['elapsed_s']}s")
    # the pipeline's contract (ISSUE 6): once the mutate volume is
    # genuinely quota-bound (well past the bucket burst), convergence
    # must sit AT or ABOVE the ga_mutate quota floor — workers parked
    # on waits while mutate quota idles is exactly the regression this
    # assertion pins.  Tiny smoke fleets never leave the burst, where
    # the floor is meaningless; they skip.
    floor = tuned["ga_mutate_quota_floor_objects_per_sec"]
    if (
        tuned["ga_mutate_calls"] > 2 * QUOTAS["ga_mutate"][1]
        and tuned["objects_per_sec"] < floor
    ):
        raise SystemExit(
            f"headline {tuned['objects_per_sec']} objects/s fell below the "
            f"ga_mutate quota floor {floor} — the pipeline is leaving mutate "
            "quota idle (see tuned.pending_settle / tuned.r53_batching in "
            "bench_detail.json)"
        )
    _progress(f"drift tick: measuring one ticker round over {DRIFT_N} services")
    drift = run_drift_tick(DRIFT_N, workers=TUNED_WORKERS)
    drift["metrics_snapshot"] = scrape_metrics(metrics_port)
    _progress(f"drift tick: {drift['aws_calls_total']} AWS calls/tick")
    # the continuous-profiling plane measured against itself (ISSUE 14):
    # control vs profiled twin runs, the overhead gate, and the ranked
    # per-stage CPU attribution table
    _progress(
        f"profiling: control-vs-profiled twin runs over {PROFILE_N} services"
    )
    profiling = run_profiling_phase()
    # the horizontal sharding phase (ISSUE 8): real subprocesses, so it
    # runs last — its processes must not share this process's registry
    sharding = run_sharding_phase()
    _progress(
        "sharding: curve "
        + ", ".join(
            f"{width}x={block['objects_per_sec']}/s (eff {block['efficiency']})"
            for width, block in sharding["sweep"].items()
        )
    )
    # the autoscaler reaction benchmark (ISSUE 13): subprocess-isolated
    # sim runs, so the virtual clockseam never touches this process
    _progress(
        f"autoscaler: load-wave reaction scenario (seed {AUTOSCALE_SEED}, "
        f"profile {AUTOSCALE_PROFILE}) + observe-only twin"
    )
    autoscaler = run_autoscaler_phase()

    steady = tuned.pop("steady_state")
    churn = tuned.pop("egb_churn")
    pending_settle = tuned.pop("pending_settle", {})
    r53_batching = tuned.pop("r53_batching", {})
    detail = {
        "workload": (
            "N Services (accelerator chain + atomic TXT/A pair) + N/10 ALB "
            "Ingresses (listen-ports listener derivation + records) + N/10 "
            "EndpointGroupBindings (bind, then weight-edit + serviceRef-swap churn)"
        ),
        "baseline": baseline,
        "tuned": tuned,
        "steady_state": steady,
        "egb_churn": churn,
        # the async mutation pipeline's own counters (ISSUE 6):
        # parked/resolved waits and per-zone batch shapes of the tuned
        # convergence phase
        "pending_settle": pending_settle,
        "r53_batching": r53_batching,
        "drift_tick": drift,
        # the continuous-profiling plane's self-measurement (ISSUE 14):
        # overhead gate result, per-stage exclusive CPU/wall attribution
        # with ns/reconcile rails, and the sampler's folded top table
        "profile": profiling,
        # the 2-shard multi-process phase (ISSUE 8): single-shard
        # headline vs two concurrently-live replicas, with quota
        # division asserted
        "sharding": sharding,
        # the SLO-driven autoscaler's measured reaction (ISSUE 13):
        # spike -> executed scale-out -> scale-back-in (= p99 restored
        # + headroom sustained), plus the observe-only twin's proof
        "autoscaler": autoscaler,
        "latency_model": {
            "scale": f"real-world seconds / {LATENCY_SCALE:g}; quotas x{LATENCY_SCALE:g}",
            "real_latency_s": REAL_LATENCY,
            "quotas_scaled_per_sec": {
                family: {"rate": rate, "burst": burst_}
                for family, (rate, burst_) in QUOTAS.items()
            },
        },
    }
    with open(DETAIL_PATH, "w") as f:
        json.dump(detail, f, indent=1, sort_keys=True)
        f.write("\n")
    _progress(f"detail written to {DETAIL_PATH}")

    # the compact headline record — the ONLY stdout line, kept < 1 KB
    # so a tail-window capture always carries metric/value/vs_baseline
    # (VERDICT r4 #1; tests/test_bench_output.py pins the budget)
    headline = {
        "metric": "mixed_workload_convergence_throughput",
        "value": tuned["objects_per_sec"],
        "unit": "objects/sec",
        "vs_baseline": round(tuned["objects_per_sec"] / baseline["objects_per_sec"], 2),
        "vs_baseline_note": "baseline = this code pinned to the reference's operating point (the reference publishes no numbers)",
        "n_objects": tuned["n_objects"],
        "workers": tuned["workers"],
        "aws_calls_total": tuned["aws_calls_total"],
        "ga_mutate_quota_floor_objects_per_sec": tuned[
            "ga_mutate_quota_floor_objects_per_sec"
        ],
        "sync_p99_s": {
            label: stats["p99_s"] for label, stats in tuned["sync_latency"].items()
        },
        "steady_aws_calls_per_sec": steady["aws_calls_per_sec"],
        "egb_churn_s": churn["elapsed_s"],
        # Route53 write batching at a glance: wire calls per record
        # mutation (1,100 calls for 1,100 records before ISSUE 6)
        "r53_cr_calls": tuned["aws_calls_by_op"].get(
            "change_resource_record_sets", 0
        ),
        "drift_tick": {
            "aws_calls": drift["aws_calls_total"],
            "derived_s_scaled": drift["derived_tick_seconds_scaled"],
            "derived_s_real": drift["derived_tick_seconds_real_quotas"],
        },
        # scale-out at a glance: the 1/2/4/8 curve (ISSUE 10) — per-
        # width aggregate objs/s, plus the 2-shard speedup and 4-shard
        # efficiency the gates pin
        "sharding": {
            "speedup": sharding["speedup"],
            "agg_objs_per_sec": sharding["sharded"]["objects_per_sec"],
            "sweep_objs_per_sec": {
                width: block["objects_per_sec"]
                for width, block in sharding["sweep"].items()
            },
            "efficiency_4": sharding["sweep"].get("4", {}).get("efficiency"),
        },
        # the autoscaler's reaction at a glance (ISSUE 13): virtual
        # seconds from the load-wave spike to the executed scale-out
        # and to the scale-back (p99 restored), and the observe-only
        # twin's resize count (must be 0)
        "autoscaler": {
            "react_s": autoscaler["spike_to_scale_out_s"],
            "restore_s": autoscaler["spike_to_scale_in_s"],
            "observe_resizes": len(autoscaler["observe_only"]["executed"]),
        },
        # the continuous-profiling plane at a glance (ISSUE 14): the
        # hottest attributed stage, exclusive CPU per reconcile, and
        # the measured profiler overhead vs the unprofiled control
        "profile": {
            "top_stage": profiling["table"][0]["stage"] if profiling["table"] else "",
            "reconcile_cpu_us": profiling["reconcile_cpu_us"],
            "overhead_pct": profiling["overhead_pct"],
        },
        # fleet-merged convergence SLO signals (ISSUE 9): per-kind
        # journey p99 of the tuned phase (through the fleet-merge
        # read) + the 2-replica fleet-merged GA p99 of the sharded run
        "convergence": {
            "ga_p99_s": tuned["convergence"]["ga"]["p99_s"],
            "record_p99_s": tuned["convergence"]["record"]["p99_s"],
            "fleet_sharded_ga_p99_s": sharding["sharded"]["convergence"]["ga"][
                "p99_s"
            ],
        },
        "detail_file": os.path.basename(DETAIL_PATH),
    }
    print(json.dumps(headline, separators=(",", ":")))


if __name__ == "__main__":
    main()
