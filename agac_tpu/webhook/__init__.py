"""Validating admission webhook — a separate process from the
controller, like the reference's ``webhook`` subcommand.

Capability parity with ``pkg/webhoook/`` [sic] (161 LoC): a plain
stdlib HTTP(S) server with two routes — ``/healthz`` and
``/validate-endpointgroupbinding`` — and a validator enforcing
``spec.endpointGroupArn`` immutability on UPDATE.
"""

from .server import Server, make_server
from .validator import validate

__all__ = ["Server", "make_server", "validate"]
