"""AdmissionReview validation for EndpointGroupBinding.

Capability parity with the reference's
``pkg/webhoook/endpointgroupbinding/validator.go:15-58``:

- request kind != EndpointGroupBinding → denied, code 400;
- operation != UPDATE → allowed (creates pass through);
- no oldObject → allowed;
- ``spec.endpointGroupArn`` changed → denied, code 403,
  message "Spec.EndpointGroupArn is immutable";
- otherwise allowed, code 200, message "valid".

Works on wire-format dicts (the AdmissionReview JSON), decoding the
embedded objects through the generic serde.
"""

from __future__ import annotations

from typing import Any

from .. import klog
from ..apis.endpointgroupbinding import EndpointGroupBinding
from ..cluster.serde import from_wire


def _review_response(uid: str, allowed: bool, code: int, reason: str) -> dict[str, Any]:
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": {
            "uid": uid,
            "allowed": allowed,
            # AdmissionResponse.Result serializes under the "status"
            # key (metav1.Status), as in the reference's responses
            "status": {"code": code, "message": reason},
        },
    }


def validate(review: dict[str, Any]) -> dict[str, Any]:
    request = review.get("request") or {}
    uid = request.get("uid", "")

    kind = (request.get("kind") or {}).get("kind")
    if kind != "EndpointGroupBinding":
        klog.errorf("%s is not supported", kind)
        return _review_response(uid, False, 400, f"{kind} is not supported")

    if request.get("operation") != "UPDATE":
        klog.v(4).infof("Operation is not Update")
        return _review_response(uid, True, 200, "")

    old_raw = request.get("oldObject")
    if not old_raw:
        klog.v(4).infof("OldObject is nil")
        return _review_response(uid, True, 200, "")

    try:
        previous = from_wire(EndpointGroupBinding, old_raw)
        new = from_wire(EndpointGroupBinding, request.get("object") or {})
    except Exception as err:
        klog.error(err)
        return _review_response(uid, False, 500, str(err))

    if previous.spec.endpoint_group_arn != new.spec.endpoint_group_arn:
        klog.errorf("Spec.EndpointGroupArn is immutable")
        return _review_response(uid, False, 403, "Spec.EndpointGroupArn is immutable")

    return _review_response(uid, True, 200, "valid")
