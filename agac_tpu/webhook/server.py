"""The webhook HTTP(S) server.

Capability parity with the reference's ``pkg/webhoook/webhook.go:14-91``:
stdlib HTTP server (no framework), optional TLS from cert/key files,
``/healthz`` returning 200, and ``/validate-endpointgroupbinding``
doing strict request parsing — Content-Type must be application/json
(400 otherwise), empty body is 400, a review without a request is 400 —
then dispatching to the validator.
"""

from __future__ import annotations

import json
import os
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import klog
from .validator import validate


class _Handler(BaseHTTPRequestHandler):
    # keep-alive: the apiserver calls this webhook on every CRD write
    # (failurePolicy=Fail) and must not pay a TCP+TLS handshake each time
    protocol_version = "HTTP/1.1"

    # quiet the default per-request stderr lines; klog covers it
    def log_message(self, fmt, *args):
        klog.v(4).infof("webhook http: " + fmt, *args)

    def do_GET(self):
        if self.path == "/healthz":
            # kubelet probes hit this every few seconds — verbose
            # level, or the probe traffic floods the logs
            klog.v(4).infof("healthz")
            # Content-Length is mandatory under keep-alive: without it
            # the client waits forever for a body that never comes
            self._respond(200, b"ok", content_type="text/plain")
            return
        self.send_error(404)

    def do_POST(self):
        if self.path != "/validate-endpointgroupbinding":
            self.send_error(404)
            return
        klog.infof("validate-endpointgroupbinding")
        review, err = self._parse_request()
        if err is not None:
            klog.error(err)
            self._respond(400, err.encode(), content_type="text/plain")
            return
        try:
            response = validate(review)
            body = json.dumps(response).encode()
        except Exception as exc:
            klog.error(exc)
            self._respond(500, str(exc).encode(), content_type="text/plain")
            return
        self._respond(200, body)

    def _parse_request(self):
        """(review, error) — mirrors ``parseRequest`` (webhook.go:61-85)."""
        content_type = self.headers.get("Content-Type", "")
        if content_type.split(";")[0].strip() != "application/json":
            return None, "invalid Content-Type"
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        if not body:
            return None, "empty body"
        try:
            review = json.loads(body)
        except ValueError as err:
            return None, f"failed to unmarshal body: {err}"
        if not isinstance(review, dict) or not review.get("request"):
            return None, "empty request"
        return review, None

    def _respond(self, code: int, body: bytes, content_type: str = "application/json"):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _reloading_tls_context(cert_file: str, key_file: str) -> ssl.SSLContext:
    """TLS context that re-reads the cert/key when the files change.

    cert-manager rotates webhook certificates in place; the reference's
    Go server loads the pair once at startup and serves the stale cert
    until the pod restarts.  Here every handshake's SNI callback checks
    the files' mtimes and swaps in a freshly loaded context when they
    moved — a half-written rotation (cert/key momentarily mismatched)
    keeps serving the previous pair instead of breaking handshakes.
    The kube-apiserver always sends SNI (it dials the service DNS
    name); a client that omits SNI keeps the startup certificate.
    """
    lock = threading.Lock()
    state: dict = {"mtime": None, "context": None}

    def mtimes():
        return (os.stat(cert_file).st_mtime_ns, os.stat(key_file).st_mtime_ns)

    def load() -> ssl.SSLContext:
        context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        context.load_cert_chain(cert_file, key_file)
        context.sni_callback = sni
        return context

    def current() -> ssl.SSLContext:
        try:
            mtime = mtimes()
        except OSError:
            return state["context"]  # mid-rotation: files briefly absent
        with lock:
            if mtime != state["mtime"]:
                try:
                    state["context"] = load()
                    state["mtime"] = mtime
                    klog.infof("Loaded TLS certificate from %s", cert_file)
                except (ssl.SSLError, OSError) as err:
                    klog.errorf("Failed to reload TLS certificate: %s", err)
            return state["context"]

    def sni(sslobj, server_name, base_context):
        fresh = current()
        if fresh is not None and fresh is not sslobj.context:
            sslobj.context = fresh
        return None

    # first load is outside current(): a bad pair at startup must
    # fail fast with the real SSLError, not an opaque None downstream
    state["mtime"] = mtimes()
    state["context"] = load()
    return state["context"]


def make_server(port: int, tls_cert_file: str = "", tls_key_file: str = "", host: str = "") -> ThreadingHTTPServer:
    """Build the server (separately from serving, so tests can bind
    port 0 and shut down cleanly)."""
    server = ThreadingHTTPServer((host, port), _Handler)
    ssl_on = bool(tls_cert_file and tls_key_file)
    if ssl_on:
        context = _reloading_tls_context(tls_cert_file, tls_key_file)
        server.socket = context.wrap_socket(server.socket, server_side=True)
    klog.infof("Listening on :%d, SSL is %s", port, str(ssl_on).lower())
    return server


def Server(port: int, tls_cert_file: str = "", tls_key_file: str = "") -> None:
    """Blocking entry point, the analog of ``webhook.Server``."""
    make_server(port, tls_cert_file, tls_key_file).serve_forever()
