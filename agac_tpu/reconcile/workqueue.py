"""Rate-limited, deduplicating work queues.

Re-implements the semantics of client-go's ``util/workqueue`` that the
reference relies on everywhere (queues constructed with
``workqueue.NewNamedRateLimitingQueue(workqueue.DefaultControllerRateLimiter(), ...)``,
e.g. reference ``pkg/controller/globalaccelerator/controller.go:64-65``):

- **Dedup FIFO**: an item added while queued is coalesced; an item
  added while being processed is re-queued when ``done`` is called, so
  a given key is never processed concurrently by two workers.
- **Delaying**: ``add_after`` schedules an add in the future
  (used by the kernel for ``Result.requeue_after``,
  reference ``pkg/reconcile/reconcile.go:79-82``).
- **Rate limiting**: ``add_rate_limited`` consults a per-item
  exponential-backoff limiter combined with an overall token bucket —
  the same pair as client-go's ``DefaultControllerRateLimiter``
  (5 ms base doubling to a 1000 s cap, plus a 10 qps / 100 burst
  bucket).  ``forget`` resets the per-item backoff.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Any, Callable, Hashable, Optional

from .. import clockseam
from ..analysis import racecheck
from ..observability import instruments


class ItemExponentialFailureRateLimiter:
    """Per-item exponential backoff: base * 2^failures, capped."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self._base = base_delay
        self._max = max_delay
        self._failures: dict[Hashable, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Hashable) -> float:
        with self._lock:
            failures = self._failures.get(item, 0)
            self._failures[item] = failures + 1
        # exponent capped so a persistently failing item can never push
        # 2**failures past float range (OverflowError would swallow the
        # requeue entirely)
        delay = self._base * (2 ** min(failures, 64))
        return min(delay, self._max)

    def forget(self, item: Hashable) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Hashable) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class BucketRateLimiter:
    """A token bucket shared by all items (qps with burst).

    ``when`` reserves a token and returns how long the caller must wait
    for it, like golang.org/x/time/rate's ``Reserve().Delay()``.

    ``clock`` is injectable (default ``time.monotonic``) so limiter and
    queue tests drive refill with a fake clock instead of sleeping real
    wall time.
    """

    def __init__(
        self,
        qps: float = 10.0,
        burst: int = 100,
        clock: Optional[Callable[[], float]] = None,
    ):
        self._qps = qps
        self._burst = burst
        self._tokens = float(burst)
        # default: the process clock seam (wall time in production,
        # virtual time under the sim runtime — ISSUE 7)
        self._clock = clock = clock or clockseam.monotonic
        self._last = clock()
        self._lock = threading.Lock()

    def when(self, item: Hashable) -> float:
        with self._lock:
            now = self._clock()
            self._tokens = min(self._burst, self._tokens + (now - self._last) * self._qps)
            self._last = now
            self._tokens -= 1.0
            if self._tokens >= 0:
                return 0.0
            return -self._tokens / self._qps

    def qps(self) -> float:
        with self._lock:
            return self._qps

    def set_qps(self, qps: float) -> None:
        """Retune the refill rate in place — the seam the API health
        plane's AIMD limiter adjusts (cloudprovider/aws/health.py).
        Tokens accrued so far are settled at the OLD rate first, so a
        rate cut takes effect from now rather than retroactively."""
        with self._lock:
            now = self._clock()
            self._tokens = min(self._burst, self._tokens + (now - self._last) * self._qps)
            self._last = now
            self._qps = max(qps, 1e-9)

    def forget(self, item: Hashable) -> None:  # bucket has no per-item state
        pass

    def num_requeues(self, item: Hashable) -> int:
        return 0


class MaxOfRateLimiter:
    """Takes the worst (longest) delay of its children."""

    def __init__(self, *limiters):
        self._limiters = limiters

    def when(self, item: Hashable) -> float:
        return max(l.when(item) for l in self._limiters)

    def forget(self, item: Hashable) -> None:
        for l in self._limiters:
            l.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return max(l.num_requeues(item) for l in self._limiters)


def default_controller_rate_limiter() -> MaxOfRateLimiter:
    """The client-go default: per-item exponential + overall bucket."""
    return controller_rate_limiter(10.0, 100)


def controller_rate_limiter(
    qps: float = 10.0,
    burst: int = 100,
    max_backoff: float = 1000.0,
    clock: Optional[Callable[[], float]] = None,
) -> MaxOfRateLimiter:
    """The client-go default shape (per-item exponential + overall
    bucket) with a tunable bucket — the analog of passing a custom
    limiter where client-go users outgrow
    ``DefaultControllerRateLimiter()``'s 10 qps / 100 burst.

    qps <= 0 means "no overall bucket" (per-item backoff only).
    ``max_backoff`` caps the per-item exponential delay (client-go's
    1000 s default is far past useful for external-API retries; many
    controllers cap at seconds).  ``clock`` is threaded through to the
    bucket so tests drive refill with a fake clock."""
    if qps <= 0:
        return MaxOfRateLimiter(ItemExponentialFailureRateLimiter(0.005, max_backoff))
    return MaxOfRateLimiter(
        ItemExponentialFailureRateLimiter(0.005, max_backoff),
        BucketRateLimiter(qps, burst, clock=clock),
    )


class RateLimitingQueue:
    """Dedup FIFO + delayed adds + rate-limited adds, in one object.

    The three client-go queue layers (Type, DelayingInterface,
    RateLimitingInterface) collapsed into one class; the controllers
    only ever consume the combined interface.

    Two condition variables share one mutex: workers blocked in
    ``get`` wait on ``_ready`` while the single delay-waker thread
    waits on ``_delay``, so a ``notify`` for one never gets consumed
    by the other.

    ``clock`` is injectable for delay tests: with a fake clock, a test
    advances time and calls ``kick_delays()`` so the waker re-examines
    the heap instead of the test sleeping real wall seconds.
    """

    def __init__(
        self,
        rate_limiter=None,
        name: str = "",
        clock: Optional[Callable[[], float]] = None,
        metrics_registry=None,
    ):
        self.name = name
        self._clock = clock or clockseam.monotonic
        self._limiter = rate_limiter or default_controller_rate_limiter()
        # the controller-runtime standard workqueue metric set, bound
        # to this queue's name label (observability plane, ISSUE 5)
        queue_metrics = instruments.workqueue_instruments(metrics_registry)
        label = name or "unnamed"
        self._m_depth = queue_metrics.depth.labels(name=label)
        self._m_adds = queue_metrics.adds.labels(name=label)
        self._m_retries = queue_metrics.retries.labels(name=label)
        self._m_queue_duration = queue_metrics.queue_duration.labels(name=label)
        self._m_work_duration = queue_metrics.work_duration.labels(name=label)
        self._added_at: dict[Hashable, float] = {}  # item -> enqueue time
        self._got_at: dict[Hashable, float] = {}  # item -> handed-out time
        self._pop_wait = threading.local()  # per-worker last queue wait
        # racecheck seam: a plain Lock unless the lock-order watchdog
        # is enabled (tests), in which case acquisition order across
        # the worker/waker/handler threads is recorded and verified
        self._mutex = racecheck.make_lock(f"workqueue.{name or 'unnamed'}")
        self._ready = threading.Condition(self._mutex)
        self._delay = threading.Condition(self._mutex)
        self._queue: deque[Any] = deque()  # FIFO of items ready to be handed out
        self._dirty: set = set()  # items needing (re-)processing
        self._processing: set = set()  # items currently being processed
        self._shutting_down = False
        # delayed adds: heap of (ready_monotonic_time, seq, item)
        self._waiting: list = []
        self._seq = 0
        # explain-plane side tables (ISSUE 15), both O(1) per key:
        # item -> eta of its LATEST delayed add (matched on pop so a
        # superseded entry's maturation does not clear a newer one),
        # and item -> last structured reason code attached at the
        # requeue site (cleared on forget — a converged item carries
        # no stale cause)
        self._waiting_eta: dict[Hashable, float] = {}
        self._reasons: dict[Hashable, str] = {}
        # the delay waker is a real thread ONLY when the runtime allows
        # threads; under the sim runtime (ISSUE 7) delayed adds are
        # popped synchronously by the cooperative scheduler via
        # pop_due_delays()/kick_delays(), so every requeue interleaving
        # is deterministic
        self._waker: Optional[threading.Thread] = None
        if clockseam.threads_enabled():
            self._waker = threading.Thread(
                target=self._waiting_loop, daemon=True, name=f"workqueue-delay-{name}"
            )
            self._waker.start()

    # ---- Type (dedup FIFO) ----
    def _add_locked(self, item: Hashable) -> None:
        if self._shutting_down or item in self._dirty:
            return
        self._dirty.add(item)
        self._m_adds.inc()
        self._added_at[item] = self._clock()
        if item in self._processing:
            return
        self._queue.append(item)
        self._m_depth.set(len(self._queue))
        self._ready.notify()

    def add(self, item: Hashable) -> None:
        with self._mutex:
            self._add_locked(item)

    def get(self, timeout: Optional[float] = None) -> tuple[Any, bool]:
        """Block until an item is available. Returns (item, shutdown).

        When shutdown is True the worker loop must exit
        (reference ``pkg/reconcile/reconcile.go:27-31``).  A ``timeout``
        expiry returns ``(None, False)`` — callers that poll must
        distinguish it from shutdown.
        """
        # real wall clock on purpose, independent of the injected
        # delay clock: get() blocks a live worker thread, and a fake
        # delay clock must not turn a poll timeout into a hang
        deadline = None if timeout is None else time.monotonic() + timeout  # agac-lint: ignore[unseamed-clock] -- bounds a real blocked thread; a virtual clock here would turn the poll timeout into a hang
        with self._mutex:
            while not self._queue and not self._shutting_down:
                remaining = None if deadline is None else deadline - time.monotonic()  # agac-lint: ignore[unseamed-clock] -- same real-thread timeout as above
                if remaining is not None and remaining <= 0:
                    return None, False
                self._ready.wait(remaining)
            if not self._queue:
                return None, True
            item = self._queue.popleft()
            self._processing.add(item)
            self._dirty.discard(item)
            now = self._clock()
            wait = max(0.0, now - self._added_at.pop(item, now))
            self._m_queue_duration.observe(wait)
            self._pop_wait.wait = wait
            self._got_at[item] = now
            self._m_depth.set(len(self._queue))
            return item, False

    def last_pop_wait(self) -> Optional[float]:
        """The queued-time of the item THIS worker thread most
        recently got — the queue-wait span the reconcile trace
        attaches (the add timestamp is known only to the queue)."""
        return getattr(self._pop_wait, "wait", None)

    def done(self, item: Hashable) -> None:
        with self._mutex:
            self._processing.discard(item)
            now = self._clock()
            started = self._got_at.pop(item, None)
            if started is not None:
                self._m_work_duration.observe(max(0.0, now - started))
            if item in self._dirty:
                self._queue.append(item)
                self._m_depth.set(len(self._queue))
                self._ready.notify()

    def __len__(self) -> int:
        with self._mutex:
            return len(self._queue)

    def peek(self) -> Optional[Any]:
        """The item ``get`` would hand out next, without claiming it
        (the sim harness records it into the event trace before
        stepping a worker)."""
        with self._mutex:
            return self._queue[0] if self._queue else None

    def shutdown(self) -> None:
        with self._mutex:
            self._shutting_down = True
            self._ready.notify_all()
            self._delay.notify_all()

    def shutting_down(self) -> bool:
        with self._mutex:
            return self._shutting_down

    # ---- DelayingInterface ----
    def add_after(self, item: Hashable, delay: float, reason: str = "") -> None:
        if delay <= 0:
            if reason:
                with self._mutex:
                    self._reasons[item] = reason
            self.add(item)
            return
        with self._mutex:
            if self._shutting_down:
                return
            self._seq += 1
            eta = self._clock() + delay
            heapq.heappush(self._waiting, (eta, self._seq, item))
            self._waiting_eta[item] = eta
            if reason:
                self._reasons[item] = reason
            self._delay.notify()

    def kick_delays(self) -> None:
        """Wake the delay waker to re-examine the heap now — the seam
        fake-clock tests use after advancing their clock (a fake clock
        cannot make ``Condition.wait`` return early).  In threadless
        mode (sim runtime) there is no waker: the due items are popped
        synchronously on the caller's thread instead."""
        with self._mutex:
            if self._waker is None:
                self._pop_due_locked()
            else:
                self._delay.notify()

    def pop_due_delays(self) -> None:
        """Synchronously move every matured delayed add onto the ready
        FIFO — the sim scheduler's explicit pump (equivalent to the
        waker thread waking at the right moment, but on the
        cooperative scheduler's own thread, in deterministic order)."""
        with self._mutex:
            self._pop_due_locked()

    def next_delay_deadline(self) -> Optional[float]:
        """The clock time at which the earliest delayed add matures
        (None when nothing is parked) — how the sim scheduler knows
        when this queue next becomes interesting."""
        with self._mutex:
            return self._waiting[0][0] if self._waiting else None

    def _pop_due_locked(self) -> None:
        now = self._clock()
        while self._waiting and self._waiting[0][0] <= now:
            ready_time, _, item = heapq.heappop(self._waiting)
            # only the LATEST delayed add owns the eta entry; a
            # superseded (earlier) entry maturing must not clear it
            if self._waiting_eta.get(item) == ready_time:
                del self._waiting_eta[item]
            self._add_locked(item)

    def debug_status(self) -> dict:
        """A point-in-time dump of the queue's internals for
        ``/debug/queues`` (ISSUE 10): ready/processing/dirty depths,
        parked delay count and how far away the nearest delay is —
        enough to tell a wedged worker pool from a backoff park from a
        genuinely drained queue."""
        with self._mutex:
            now = self._clock()
            return {
                "ready": len(self._queue),
                "processing": sorted(map(str, self._processing)),
                "dirty": len(self._dirty),
                "delayed": len(self._waiting),
                "next_delay_in_s": (
                    round(self._waiting[0][0] - now, 3) if self._waiting else None
                ),
                "shutting_down": self._shutting_down,
            }

    def _waiting_loop(self) -> None:
        with self._mutex:
            while not self._shutting_down:
                self._pop_due_locked()
                now = self._clock()
                wait_for = (self._waiting[0][0] - now) if self._waiting else None
                self._delay.wait(wait_for)

    # ---- RateLimitingInterface ----
    def add_rate_limited(self, item: Hashable, reason: str = "") -> None:
        self._m_retries.inc()
        self.add_after(item, self._limiter.when(item), reason=reason)

    def forget(self, item: Hashable) -> None:
        self._limiter.forget(item)
        with self._mutex:
            self._reasons.pop(item, None)

    def num_requeues(self, item: Hashable) -> int:
        return self._limiter.num_requeues(item)

    # ---- explain plane (ISSUE 15) ----
    def delayed_peek(self, item: Hashable) -> Optional[dict]:
        """If ``item`` currently sits in a delayed add, its next-eta,
        last reason code and backoff count — a dict get, O(1) in queue
        and fleet size (the explain plane's per-key probe).  None when
        the item is not delayed (ready/processing/absent)."""
        with self._mutex:
            eta = self._waiting_eta.get(item)
            if eta is None:
                return None
            return {
                "eta_s": round(max(0.0, eta - self._clock()), 3),
                "reason": self._reasons.get(item, ""),
                "requeues": self._limiter.num_requeues(item),
            }

    def contains(self, item: Hashable) -> bool:
        """True when the item is ready, dirty, or being processed
        (NOT delayed — ``delayed_peek`` answers that) — O(1) set
        membership for the explain plane."""
        with self._mutex:
            return item in self._dirty or item in self._processing

    def last_reason(self, item: Hashable) -> str:
        with self._mutex:
            return self._reasons.get(item, "")
