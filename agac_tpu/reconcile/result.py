"""Reconcile result contract.

Mirrors the reference's ``pkg/reconcile/reconcile.go:17-20``: a process
function reports whether the item should be requeued (rate-limited) or
re-scheduled after a fixed delay.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class Result:
    requeue: bool = False
    requeue_after: float = 0.0  # seconds; > 0 wins over ``requeue``
    # the item was popped but belongs to another replica's shards
    # (ISSUE 10: a key re-homed between enqueue and pop — queue
    # residue across a drain/handoff or a lease steal): forget it
    # WITHOUT closing its journey — the new owner's resync carries it
    skip: bool = False
    # structured explain-catalog reason code (explain.REASON_CODES) for
    # WHY the requeue/skip happened — the explain plane's blocked-on
    # verdict reads it back from the queue/journey, never inferring.
    # The unexplained-requeue lint rule requires every requeue/skip
    # Result in controllers/ and reconcile/ to carry a literal code.
    reason: str = ""
