"""The level-triggered reconcile loop.

Capability parity with the reference's ``pkg/reconcile/reconcile.go``:
``process_next_work_item`` pops one key from a rate-limited workqueue,
resolves it to an object through ``key_to_obj`` (a lister/cache read),
dispatches to the delete path when the object is gone
(``reconcile.go:62-63``) or to the create-or-update path with a deep
copy of the cached object (``reconcile.go:67``), then applies the retry
policy (``reconcile.go:70-89``):

- processing raised → rate-limited requeue, unless the exception chain
  contains a ``NoRetryError`` (``pkg/errors/errors.go:33-39``);
- ``Result.requeue_after > 0`` → forget (reset backoff) then re-add
  after the fixed delay;
- ``Result.requeue`` → rate-limited requeue;
- success → forget.

Instead of Go's ``(Result, error)`` pairs, process functions here
return a ``Result`` and signal errors by raising; ``NotFoundError``
from ``key_to_obj`` selects the delete path, mirroring apimachinery's
``IsNotFound`` dispatch.
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Any, Callable

from .. import clockseam, klog
from ..cloudprovider.aws import health as api_health
from ..errors import NoRetryError, NotFoundError, is_no_retry
from ..observability import explain, instruments, journey, profile, recorder, trace
from .pending import SettleWait
from .result import Result
from .workqueue import RateLimitingQueue


def _controller_name() -> str:
    """The controller a worker thread belongs to, from the
    ``{controller}-worker-{i}`` naming ``run_workers`` applies — the
    per-controller label the reconcile metrics and traces share.
    Non-pool threads (tests, direct drivers) label as themselves."""
    name = threading.current_thread().name
    return name.rsplit("-worker-", 1)[0]

KeyToObjFunc = Callable[[str], Any]
ProcessDeleteFunc = Callable[[str], Result]
ProcessCreateOrUpdateFunc = Callable[[Any], Result]

# ---------------------------------------------------------------------------
# sync-duration observers — a process-global metrics seam (the analog of
# controller-runtime's global metrics registry; the reference only LOGS
# the per-item duration via its v4 defer, ``reconcile.go:44-47``).
# Observers receive (key, seconds, error_or_None) after every completed
# sync pass, on the worker thread; ``threading.current_thread().name``
# carries the controller name (``run_workers`` names its threads
# ``{controller}-worker-{i}``) for per-controller breakdowns.  Observer
# exceptions are contained like hook exceptions.
# ---------------------------------------------------------------------------
SyncDurationObserver = Callable[[str, float, "Exception | None"], None]
_sync_duration_observers: list[SyncDurationObserver] = []


def add_sync_duration_observer(fn: SyncDurationObserver) -> None:
    _sync_duration_observers.append(fn)


def remove_sync_duration_observer(fn: SyncDurationObserver) -> None:
    try:
        _sync_duration_observers.remove(fn)
    except ValueError:
        pass


def _observe_sync_duration(key: str, seconds: float, err: "Exception | None") -> None:
    for fn in list(_sync_duration_observers):
        try:
            fn(key, seconds, err)
        except Exception as obs_err:
            klog.errorf("sync duration observer failed for %r: %s", key, obs_err)
# (key, error_or_None, num_requeues, permanent) — observability hook
# fired after the retry policy has been applied.  ``error`` is None on
# a successful sync (so streak-tracking hooks can reset); ``permanent``
# is True for NoRetry errors (the item will NOT be retried).
SyncResultFunc = Callable[[str, "Exception | None", int, bool], None]


def process_next_work_item(
    queue: RateLimitingQueue,
    key_to_obj: KeyToObjFunc,
    process_delete: ProcessDeleteFunc,
    process_create_or_update: ProcessCreateOrUpdateFunc,
    on_sync_result: SyncResultFunc | None = None,
    reconcile_deadline: float | None = None,
) -> bool:
    """Process one queue item; False only when the queue shut down.

    The analog of ``ProcessNextWorkItem`` (reference
    ``pkg/reconcile/reconcile.go:26-42``): errors from the handler are
    logged and swallowed so the worker loop keeps running (crash
    containment, the analog of ``utilruntime.HandleError``).

    ``on_sync_result`` (absent in the reference, which only logs —
    VERDICT r1 #6) lets controllers surface failing items to users,
    e.g. as Warning Events; it observes, never alters, the retry
    policy, and its own exceptions are contained.

    Each item is bracketed by the API health plane's seams: the
    worker-heartbeat table records (thread, key, since) for the
    watchdog/``/healthz``, and ``reconcile_deadline`` (seconds, None/0
    disables) arms the per-worker deadline the driver's poll loops and
    the backend's retry backoffs consult — expiry surfaces as the
    retryable DeadlineExceeded instead of a wedged worker.
    """
    controller = _controller_name()
    # stage accountant (ISSUE 14): the pop is charged outside the
    # reconcile scope — its wall time is dominated by idle queue wait,
    # which would drown the per-item cpu/wall ratio; its CPU side is
    # the pop bookkeeping itself
    with profile.stage("queue-pop", controller=controller):
        item, shutdown = queue.get()
    if shutdown:
        return False
    heartbeats = api_health.worker_heartbeats()
    heartbeats.begin(item if isinstance(item, str) else repr(item))
    if reconcile_deadline:
        api_health.set_reconcile_deadline(reconcile_deadline)
    # observability plane (ISSUE 5): a sampled item gets a trace whose
    # spans (queue wait here; AWS calls and settle polls via the
    # driver hooks) ride a thread-local — unsampled items carry None
    # and every tracing call site degrades to a no-op
    tracer = trace.tracer()
    with profile.reconcile_scope(controller):
        item_trace = tracer.start(
            controller,
            item if isinstance(item, str) else repr(item),
            queue_wait=getattr(queue, "last_pop_wait", lambda: None)(),
        )
        try:
            with trace.activate(item_trace):
                _reconcile_handler(
                    item, queue, key_to_obj, process_delete,
                    process_create_or_update, on_sync_result,
                )
        except Exception as err:  # containment: a bad item must not kill the worker
            klog.errorf("unhandled error reconciling %r: %s", item, err)
        finally:
            with profile.stage("self-tax"):
                tracer.finish(item_trace)
                if item_trace is not None:
                    instruments.reconcile_instruments().traces_sampled.labels(
                        controller=item_trace.controller
                    ).inc()
            api_health.clear_reconcile_deadline()
            heartbeats.done()
            queue.done(item)
    return True


def _reconcile_handler(
    key: Any,
    queue: RateLimitingQueue,
    key_to_obj: KeyToObjFunc,
    process_delete: ProcessDeleteFunc,
    process_create_or_update: ProcessCreateOrUpdateFunc,
    on_sync_result: SyncResultFunc | None = None,
) -> None:
    if not isinstance(key, str):
        queue.forget(key)
        klog.errorf("expected string in workqueue but got %r", key)
        return
    controller = _controller_name()
    # the journey plane (ISSUE 9): stamp the attempt, and capture the
    # journey's id BEFORE the result branches below can close it — the
    # flight-recorder entry must carry the id either way, so a slow
    # convergence in /slo is one grep away from its recorded attempts
    with profile.stage("self-tax"):
        journeys = journey.tracker()
        journeys.attempt(controller, key)
        journey_id = journeys.journey_id(controller, key)
    start = clockseam.monotonic()
    try:
        with trace.span("sync"):
            res, err, was_delete = _dispatch(
                key, key_to_obj, process_delete, process_create_or_update
            )
    finally:
        elapsed = clockseam.monotonic() - start
        klog.v(4).infof("Finished syncing %r (%.3fs)", key, elapsed)
    if _sync_duration_observers:
        _observe_sync_duration(key, elapsed, err)

    with profile.stage("self-tax"):
        reconcile_metrics = instruments.reconcile_instruments()
        reconcile_metrics.duration.labels(controller=controller).observe(elapsed)

    if isinstance(err, SettleWait) and err.table is not None:
        # the async mutation pipeline (ISSUE 6): the handler reached an
        # AWS wait state — park the item in the pending-settle table
        # and free the worker; the poll-tick scheduler requeues it when
        # the wait resolves (or its deadline expires).  Parking is not
        # a failure: backoff state is untouched, and the sync-result
        # hook sees a clean pass so failure streaks reset.
        result = instruments.RESULT_PARKED
        reason = "parked-settle"
        with profile.stage("settle-park"):
            err.table.park(key, queue, err, controller=controller,
                           reason="parked-settle")
        journeys.stage(controller, key, journey.STAGE_PARKED,
                       reason="parked-settle")
        klog.v(2).infof("Parked %r: %s", key, err)
        _notify(on_sync_result, key, None, 0, False)
        err = None
    elif err is not None:
        permanent = is_no_retry(err)
        if permanent:
            result = instruments.RESULT_PERMANENT_ERROR
            reason = ""
            # the item will NOT be retried: its journey can never
            # converge, so drop it (the stage counter still shows it)
            journeys.drop(controller, key)
            klog.errorf("error syncing %r: %s", key, err)
        else:
            result = instruments.RESULT_ERROR
            # the explain code for WHY the retry waits (ISSUE 15):
            # circuit rejections and pacing-vs-deadline losses are
            # backpressure, not failures — each gets its own verdict
            if isinstance(err, api_health.CircuitOpenError):
                reason = "circuit-open"
                queue.add_rate_limited(key, reason="circuit-open")
            elif (isinstance(err, api_health.DeadlineExceeded)
                    and getattr(err, "paced", False)):
                reason = "quota-paced"
                queue.add_rate_limited(key, reason="quota-paced")
            else:
                reason = "backoff"
                queue.add_rate_limited(key, reason="backoff")
            journeys.stage(controller, key, journey.STAGE_REQUEUED,
                           reason=reason)
            klog.errorf("error syncing %r, and requeued: %s", key, err)
        if isinstance(err, api_health.DeadlineExceeded):
            reconcile_metrics.deadline_exceeded.labels(controller=controller).inc()
        _notify(on_sync_result, key, err, queue.num_requeues(key), permanent)
    elif res.skip:
        # shard-guard skip (ISSUE 10): the key re-homed to another
        # replica after it was enqueued here — drop the residue item
        # without touching its journey (the new owner's resync opened
        # or will close it) and without any AWS work having run
        result = instruments.RESULT_SKIPPED
        reason = res.reason
        queue.forget(key)
        klog.v(4).infof("Skipped %r: owned by another replica's shards", key)
        _notify(on_sync_result, key, None, 0, False)
    elif res.requeue_after > 0:
        result = instruments.RESULT_REQUEUE_AFTER
        reason = res.reason
        queue.forget(key)
        queue.add_after(key, res.requeue_after, reason=res.reason)
        journeys.stage(controller, key, journey.STAGE_REQUEUED,
                       reason=res.reason)
        klog.infof("Successfully synced %r, but requeued after %.1fs", key, res.requeue_after)
        _notify(on_sync_result, key, None, 0, False)
    elif res.requeue:
        result = instruments.RESULT_REQUEUE
        reason = res.reason
        queue.add_rate_limited(key, reason=res.reason)
        journeys.stage(controller, key, journey.STAGE_REQUEUED,
                       reason=res.reason)
        klog.infof("Successfully synced %r, but requeued", key)
        _notify(on_sync_result, key, None, 0, False)
    else:
        result = instruments.RESULT_SUCCESS
        reason = ""
        queue.forget(key)
        # a clean terminal pass closes the journey: the object's spec
        # is verified converged (or its teardown finished) — this is
        # the observation the convergence-latency histogram measures
        if was_delete:
            journeys.deleted(controller, key)
        else:
            journeys.converged(controller, key)
        klog.infof("Successfully synced %r", key)
        _notify(on_sync_result, key, None, 0, False)

    with profile.stage("self-tax"):
        reconcile_metrics.results.labels(controller=controller, result=result).inc()
        active_trace = trace.current()
        if active_trace is not None:
            # a sampled trace answers "where did this reconcile's time
            # go" on its own: journey id for the /slo drill-down plus
            # the stage-CPU breakdown closed so far (ISSUE 14)
            active_trace.annotate(
                result=result,
                error=str(err) if err is not None else None,
                journey=journey_id or "",
                stage_cpu_us=profile.current_scope().breakdown_us(),
            )
        recorder.flight_recorder().record(
            "reconcile",
            controller=controller,
            key=key,
            result=result,
            reason=reason,
            ring_epoch=explain.ring_epoch(),
            duration=round(elapsed, 4),
            error=str(err) if err is not None else "",
            journey=journey_id or "",
        )


def _notify(hook, key, err, requeues, permanent) -> None:
    if hook is None:
        return
    try:
        hook(key, err, requeues, permanent)
    except Exception as hook_err:
        klog.errorf("on_sync_result hook failed for %r: %s", key, hook_err)


def _dispatch(
    key: str,
    key_to_obj: KeyToObjFunc,
    process_delete: ProcessDeleteFunc,
    process_create_or_update: ProcessCreateOrUpdateFunc,
) -> tuple[Result, Exception | None, bool]:
    """(result, error, was_delete) — the delete-path flag lets the
    journey plane close a finished teardown as ``deleted`` rather than
    ``converged``."""
    try:
        with profile.stage("informer-lookup"):
            obj = key_to_obj(key)
    except NotFoundError:
        try:
            with profile.stage("driver-mutate"):
                return process_delete(key), None, True
        except Exception as err:
            return Result(), err, True
    except Exception as err:
        # A store read failing for any reason other than NotFound is
        # logged without a requeue in the reference
        # (``reconcile.go:64-65`` returns before the retry policy);
        # NoRetryError reproduces exactly that.
        return (
            Result(),
            NoRetryError(f"Unable to retrieve {key!r} from store: {err}"),
            False,
        )
    try:
        # DeepCopy before mutation: the cache/lister owns ``obj``
        # (reference ``pkg/reconcile/reconcile.go:67``).
        with profile.stage("serialize"):
            obj_copy = copy.deepcopy(obj)
        with profile.stage("driver-mutate"):
            return process_create_or_update(obj_copy), None, False
    except Exception as err:
        return Result(), err, False
