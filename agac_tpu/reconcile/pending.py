"""The pending-settle table: non-blocking waits for slow AWS state.

The mutation hot path used to HOLD a worker whenever AWS made it wait
— the accelerator disable→DEPLOYED settle poll slept up to 180 s
inside ``process_next_work_item``, and the Route53 ensure requeued
blind 60 s timers while waiting for the GlobalAccelerator controller
to converge.  Workers are a fixed pool; a parked worker is throughput
burned while mutate quota sits idle (ISSUE 6 / ROADMAP "async mutation
pipeline").

This module turns those waits inside out:

- a process function that reaches an AWS wait state raises
  ``SettleWait`` instead of sleeping.  The reconcile loop catches it,
  **parks** the item here — (queue, key, wait token, deadline) — and
  returns the worker to the queue immediately;
- a poll-tick scheduler (``SettleScheduler``, or an explicit
  ``poll_once()`` in tests/bench — FakeClock-compatible) re-checks all
  parked items of a group through ONE registered **group poller** per
  tick: coalesced describes instead of per-item poll loops.  A wait
  that resolved re-adds its item (backoff forgotten — parking is not a
  failure); a wait that resolved *failed* re-adds rate-limited so a
  persistently failing wait backs off instead of livelocking at tick
  frequency;
- **deadlines** are per item: an entry parked longer than its wait's
  timeout is expired and re-added rate-limited — the item re-runs,
  re-derives its state, and re-parks with a fresh deadline (bounded
  progress, never a wedged table entry);
- **health-plane circuits** integrate at the poller: a poller that
  raises ``CircuitOpenError`` (its coalesced describe was shed) skips
  its group for the tick — parked items age but are not dropped, and
  their deadlines still run, so an outage degrades to the legacy
  requeue cadence instead of hammering the dead service.

The table is deliberately in-memory only.  Crash consistency comes
from level-triggered reconciliation, not persistence: after a process
death the informer relist / drift tick re-enqueues every managed
object, each re-runs idempotently, and whatever still waits re-parks
— the table is REBUILT from requeue (proven by the kill-mid-settle
drill in ``tests/test_process_e2e.py``).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .. import clockseam, klog
from ..analysis import racecheck
from ..observability import instruments, journey

# what a group poller reports per token
SETTLE_PENDING = "pending"
SETTLE_READY = "ready"
SETTLE_FAILED = "failed"

# fallback deadline for waits parked without an explicit timeout
DEFAULT_SETTLE_TIMEOUT = 180.0

# Pollers receive the distinct tokens of their parked group and return
# {token: SETTLE_READY | SETTLE_FAILED}; omitted tokens stay pending.
GroupPoller = Callable[[list], dict]


class SettleWait(Exception):
    """Raised by driver code when a mutate chain reaches an AWS wait
    state (accelerator IN_PROGRESS, a change batch still committing,
    a cross-controller dependency not yet converged).  The reconcile
    loop parks the item instead of treating this as an error.

    ``group`` names the registered poller that can answer the wait;
    ``token`` is what that poller is asked about (an ARN, a hostname,
    a batch ticket); ``timeout`` bounds how long the item may stay
    parked before it is expired back into the queue; ``table`` is the
    pending-settle table the raising driver is wired to (riding on the
    exception keeps the reconcile loop free of global lookups — a
    driver without a table never raises this)."""

    def __init__(
        self,
        group: str,
        token,
        message: str = "",
        table: Optional["PendingSettleTable"] = None,
        timeout: float = DEFAULT_SETTLE_TIMEOUT,
    ):
        self.group = group
        self.token = token
        self.table = table
        self.timeout = timeout
        super().__init__(message or f"waiting on {group}:{token!r}")


@dataclass
class _Parked:
    key: str
    queue: object  # RateLimitingQueue (duck-typed: add/forget/add_rate_limited)
    group: str
    token: object
    parked_at: float
    deadline: float
    # the journey plane's controller label (the parking reconcile
    # loop's worker label; falls back to the queue name when unset)
    controller: str = ""


@dataclass
class _GroupState:
    poller: Optional[GroupPoller] = None
    entries: dict = field(default_factory=dict)  # key -> _Parked


class PendingSettleTable:
    """Parked reconcile items keyed by (group, item key), with one
    coalescing poller per group.  Thread-safe; pollers run OUTSIDE the
    lock (they may touch the wire)."""

    def __init__(
        self,
        clock: Optional[Callable[[], float]] = None,
        registry=None,
    ):
        self._clock = clock or clockseam.monotonic
        # racecheck seam: instrumented when the lock-order watchdog is
        # armed (chaos/soak tiers), a plain Lock otherwise
        self._lock = racecheck.make_lock("pending-settle")
        self._groups: dict[str, _GroupState] = {}
        # cumulative counters (stats() / bench export)
        self.parked_total = 0
        self.resolved_total = 0
        self.failed_total = 0
        self.expired_total = 0
        self.circuit_skips = 0
        self.max_depth = 0
        metrics = instruments.pipeline_instruments(registry)
        metrics.pending_depth.labels(table="settle").set_function(self.depth)
        metrics.pending_oldest_age.labels(table="settle").set_function(
            self.oldest_age
        )
        self._m_parked = metrics.pending_parked
        self._m_resolved = metrics.pending_resolved

    # ------------------------------------------------------------------
    # registration + parking
    # ------------------------------------------------------------------
    def register_poller(self, group: str, poller: GroupPoller) -> None:
        """Install (or replace) the coalescing poller for ``group``.
        Re-registration is idempotent by design: every per-region
        driver construction re-registers the same global pollers."""
        with self._lock:
            self._groups.setdefault(group, _GroupState()).poller = poller

    def park(self, key: str, queue, wait: SettleWait, controller: str = "",
             reason: str = "parked-settle") -> None:
        """Park ``key`` until ``wait`` resolves (or its deadline
        expires).  A key re-parked in the same group replaces its
        entry (fresh token + deadline); parking the same key under a
        different group moves it — one wait per item at a time, the
        one its latest reconcile pass hit.  ``reason`` is the explain
        code the parking site asserts (always ``parked-settle`` today;
        the kwarg exists so the unexplained-requeue lint sees a literal
        at the call site rather than special-casing park)."""
        del reason  # the parked entry itself IS the explain evidence
        now = self._clock()
        entry = _Parked(
            key=key,
            queue=queue,
            group=wait.group,
            token=wait.token,
            parked_at=now,
            deadline=now + max(wait.timeout, 0.001),
            controller=controller,
        )
        with self._lock:
            for state in self._groups.values():
                state.entries.pop(key, None)
            self._groups.setdefault(wait.group, _GroupState()).entries[key] = entry
            self.parked_total += 1
            self.max_depth = max(self.max_depth, self._depth_locked())
        self._m_parked.labels(group=wait.group).inc()

    def parked_info(self, key: str) -> Optional[dict]:
        """If ``key`` is parked, its wait's shape (group, token,
        parked_at, deadline, controller) — the explain plane's per-key
        probe.  The scan is over the handful of registered GROUPS (an
        entry lookup per group is a dict get), never over entries."""
        with self._lock:
            for group, state in self._groups.items():
                entry = state.entries.get(key)
                if entry is not None:
                    return {
                        "group": group,
                        "token": entry.token,
                        "parked_at": entry.parked_at,
                        "deadline": entry.deadline,
                        "controller": entry.controller,
                    }
        return None

    def parked_keys(self) -> list[str]:
        """Every parked key across groups — the sim explain oracle's
        ground truth for the ``parked-settle`` verdict."""
        with self._lock:
            return [
                key
                for state in self._groups.values()
                for key in state.entries
            ]

    def discard(self, key: str) -> None:
        """Drop a parked entry without requeueing (the item was
        re-enqueued by an external event and already re-ran)."""
        with self._lock:
            for state in self._groups.values():
                state.entries.pop(key, None)

    def reset(self) -> None:
        """Drop EVERY parked entry without requeueing — process death
        (the sim harness's leader kill, the kill drills): the table is
        in-memory only and is rebuilt from requeue by the next
        generation's relist, so entries referencing a dead generation's
        queues must not be polled on its behalf."""
        with self._lock:
            for state in self._groups.values():
                state.entries.clear()

    # ------------------------------------------------------------------
    # the poll tick
    # ------------------------------------------------------------------
    def poll_once(self) -> dict:
        """One scheduler tick: for every group, expire overdue entries,
        then ask the group's poller about the remainder in ONE call and
        requeue whatever resolved.  Returns a report for logging/tests:
        ``{"resolved": n, "failed": n, "expired": n, "pending": n,
        "circuit_skipped": [groups]}``."""
        report = {"resolved": 0, "failed": 0, "expired": 0, "pending": 0,
                  "circuit_skipped": []}
        with self._lock:
            groups = {
                name: (state.poller, list(state.entries.values()))
                for name, state in self._groups.items()
                if state.entries
            }
        now = self._clock()
        for name, (poller, entries) in groups.items():
            live: list[_Parked] = []
            for entry in entries:
                if now >= entry.deadline:
                    self._remove(entry)
                    self.expired_total += 1
                    report["expired"] += 1
                    # expiry is failure-shaped: the wait never resolved,
                    # so the retry backs off like any failing item
                    self._requeue(entry, failed=True,
                                  stage=journey.STAGE_SETTLE_EXPIRED)
                else:
                    live.append(entry)
            if not live:
                continue
            if poller is None:
                report["pending"] += len(live)
                continue
            tokens = []
            seen = set()
            for entry in live:  # tokens are hashable (str / ticket objects)
                if entry.token not in seen:
                    seen.add(entry.token)
                    tokens.append(entry.token)
            try:
                outcomes = poller(tokens)
            except Exception as err:
                # CircuitOpenError lands here too: the coalesced check
                # was shed — skip this group for the tick, entries age
                # toward their own deadlines
                self.circuit_skips += 1
                report["circuit_skipped"].append(name)
                klog.v(2).infof(
                    "settle poll for group %s skipped: %s", name, err
                )
                report["pending"] += len(live)
                continue
            for entry in live:
                outcome = outcomes.get(entry.token, SETTLE_PENDING)
                if outcome == SETTLE_READY:
                    self._remove(entry)
                    self.resolved_total += 1
                    report["resolved"] += 1
                    self._m_resolved.labels(group=name, outcome="ready").inc()
                    self._requeue(entry, failed=False,
                                  stage=journey.STAGE_SETTLE_RESOLVED)
                elif outcome == SETTLE_FAILED:
                    self._remove(entry)
                    self.failed_total += 1
                    report["failed"] += 1
                    self._m_resolved.labels(group=name, outcome="failed").inc()
                    self._requeue(entry, failed=True,
                                  stage=journey.STAGE_SETTLE_FAILED)
                else:
                    report["pending"] += 1
        return report

    def _remove(self, entry: _Parked) -> None:
        with self._lock:
            state = self._groups.get(entry.group)
            if state is not None and state.entries.get(entry.key) is entry:
                del state.entries[entry.key]

    @staticmethod
    def _requeue(entry: _Parked, failed: bool, stage: str) -> None:
        # the journey stamp (ISSUE 9): the settle wait's outcome is a
        # lifecycle stage; queue names are the controller labels the
        # journey plane keys on
        journey.tracker().stage(
            entry.controller
            or getattr(entry.queue, "name", "")
            or entry.group,
            entry.key,
            stage,
            reason="backoff" if failed else "in-flight",
        )
        try:
            if failed:
                # a failed/expired wait retries like any failing item
                entry.queue.add_rate_limited(entry.key, reason="backoff")
            else:
                entry.queue.forget(entry.key)
                entry.queue.add(entry.key)
        except Exception as err:  # a dead queue must not kill the tick
            klog.errorf("settle requeue of %r failed: %s", entry.key, err)

    # ------------------------------------------------------------------
    # views
    # ------------------------------------------------------------------
    def _depth_locked(self) -> int:
        return sum(len(state.entries) for state in self._groups.values())

    def depth(self) -> int:
        with self._lock:
            return self._depth_locked()

    def depth_by_group(self) -> dict[str, int]:
        with self._lock:
            return {
                name: len(state.entries)
                for name, state in self._groups.items()
                if state.entries
            }

    def oldest_age(self) -> float:
        """Seconds the oldest parked entry has waited (0 when empty) —
        the staleness signal the depth gauge alone cannot carry."""
        with self._lock:
            oldest = min(
                (
                    entry.parked_at
                    for state in self._groups.values()
                    for entry in state.entries.values()
                ),
                default=None,
            )
        if oldest is None:
            return 0.0
        return max(0.0, self._clock() - oldest)

    def stats(self) -> dict:
        with self._lock:
            depth = self._depth_locked()
        return {
            "depth": depth,
            "depth_by_group": self.depth_by_group(),
            "parked_total": self.parked_total,
            "resolved_total": self.resolved_total,
            "failed_total": self.failed_total,
            "expired_total": self.expired_total,
            "circuit_skips": self.circuit_skips,
            "max_depth": self.max_depth,
        }


class SettleScheduler:
    """The poll-tick driver: calls ``table.poll_once()`` every
    ``interval`` seconds on a daemon thread until ``stop`` fires.
    Tests and the bench drive ``poll_once()`` directly instead (the
    drift_tick pattern), so the thread is wall-clock-only plumbing."""

    def __init__(
        self,
        table: PendingSettleTable,
        interval: float = 1.0,
    ):
        self.table = table
        self.interval = max(interval, 0.01)
        self._thread: Optional[threading.Thread] = None

    def start(self, stop: threading.Event) -> threading.Thread:
        def loop():
            while not stop.wait(self.interval):
                try:
                    self.table.poll_once()
                except Exception as err:  # a bad tick must not kill the loop
                    klog.errorf("settle scheduler tick failed: %s", err)

        self._thread = threading.Thread(
            target=loop, daemon=True, name="settle-scheduler"
        )
        self._thread.start()
        return self._thread
