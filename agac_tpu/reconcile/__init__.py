"""The generic reconcile kernel: rate-limited workqueues plus the
level-triggered process-next-work-item loop.

Capability parity with the reference's ``pkg/reconcile/reconcile.go``
and the client-go ``util/workqueue`` machinery it builds on.
"""

from .pending import (
    SETTLE_FAILED,
    SETTLE_PENDING,
    SETTLE_READY,
    PendingSettleTable,
    SettleScheduler,
    SettleWait,
)
from .result import Result
from .workqueue import (
    BucketRateLimiter,
    ItemExponentialFailureRateLimiter,
    MaxOfRateLimiter,
    RateLimitingQueue,
    controller_rate_limiter,
    default_controller_rate_limiter,
)
from .reconcile import (
    add_sync_duration_observer,
    process_next_work_item,
    remove_sync_duration_observer,
)

__all__ = [
    "add_sync_duration_observer",
    "remove_sync_duration_observer",
    "Result",
    "RateLimitingQueue",
    "ItemExponentialFailureRateLimiter",
    "BucketRateLimiter",
    "MaxOfRateLimiter",
    "controller_rate_limiter",
    "default_controller_rate_limiter",
    "process_next_work_item",
    "PendingSettleTable",
    "SettleScheduler",
    "SettleWait",
    "SETTLE_PENDING",
    "SETTLE_READY",
    "SETTLE_FAILED",
]
