"""agac_tpu — a from-scratch framework with the capabilities of
omi-lab/aws-global-accelerator-controller.

The reference (mounted read-only at /root/reference) is a ~8k-LoC Go
Kubernetes controller; this package re-implements its full capability
surface as an idiomatic Python framework (see SURVEY.md for the layer
map and component inventory the design follows):

- a generic level-triggered reconcile kernel (``agac_tpu.reconcile``)
  with rate-limited workqueues,
- a cluster I/O layer (``agac_tpu.cluster``) with typed objects,
  shared informers, listers, an event recorder, and both a fake
  in-memory apiserver and a real-apiserver REST client,
- a cloud-provider layer (``agac_tpu.cloudprovider``) with the AWS
  Global Accelerator / ELBv2 / Route53 drivers behind injectable
  interfaces plus an in-memory fake AWS backend,
- three controllers (``agac_tpu.controllers``): globalaccelerator,
  route53, endpointgroupbinding,
- a validating admission webhook (``agac_tpu.webhook``),
- leader election, signals, a controller manager, a CLI, and manifest
  generation.
"""

VERSION = "0.1.0"
