"""The incident time machine's replay half (ISSUE 19): feed a
recorded capture back through the REAL manager stack on virtual time
and bisect the first divergent input.

``capture.py`` taped every external input a run consumed; this module
reconstructs the run from that tape:

- the capture header's snapshot rebuilds the WORLD — the harness
  config (``decode_config``) and the cluster store (``FakeCluster.
  restore``, same objects, same resourceVersion counter);
- a ``ReplayAWSBackend`` substitutes recorded outcomes for the cloud:
  a recorded ERROR is re-raised as its typed exception without
  touching backend state (a brownout replays with no fault plan at
  all), a recorded SUCCESS executes against the deterministic inner
  fake so controller reads re-derive (or, with
  ``substitute_results=True``, returns the recorded payload verbatim
  — the mode for captures of non-fake backends);
- external control verbs, scenario cluster writes and delivered
  signals are re-injected at their recorded virtual instants
  (priority −1, so a same-instant harness tick never overtakes them);
  internal-origin control events (crash recovery, autoscaler resizes)
  are NOT re-injected — the replayed stack re-derives them;
- everything the replayed run observes lands in an in-memory SHADOW
  capture via the same taps, so the two input streams are directly
  comparable.

Divergence bisection is a chain walk: recompute the rolling hash over
the shadow stream (starting from the recorded header's chain) and
compare each step to the hash EMBEDDED in the recorded event at the
same position.  The first position where they split names the first
divergent input — the exact event where the replayed world stopped
being the recorded one.

Known limitation: a recorded ``fail_after_commit`` error replays as a
pre-commit failure (the recorded exception is raised without running
the inner op), so state written by the original half-commit is absent
from the replayed backend; the resulting read divergence IS the
bisection's report, deliberately.  Crash faults carry their boundary
(``when="after"`` executes the inner op before dying), so kill drills
replay exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, Optional

from .. import klog
from ..cloudprovider.aws.fake_backend import FakeAWSBackend, SimulatedCrash
from ..cloudprovider.aws.health import ALL_OPS
from ..cluster import FakeCluster
from ..observability import explain as obs_explain
from . import capture as capture_mod
from .capture import Capture, IncidentCapture, load_capture
from .harness import SimHarness, decode_config


@dataclasses.dataclass
class Divergence:
    """The first event where the replayed input stream split from the
    recorded one."""

    serial: int
    index: int  # position in the recorded event list
    reason: str  # hash-split | replay-ended-early | replay-extra-events
    recorded: Optional[dict] = None
    replayed: Optional[dict] = None

    def describe(self) -> str:
        lines = [f"first divergent event: serial={self.serial} ({self.reason})"]
        if self.recorded is not None:
            lines.append(
                f"  recorded: kind={self.recorded.get('kind')} "
                f"t={self.recorded.get('t')} "
                f"data={capture_mod.canonical_form(self.recorded, 'real')[:240]}"
            )
        if self.replayed is not None:
            lines.append(
                f"  replayed: kind={self.replayed.get('kind')} "
                f"t={self.replayed.get('t')} "
                f"data={capture_mod.canonical_form(self.replayed, 'real')[:240]}"
            )
        return "\n".join(lines)


@dataclasses.dataclass
class ReplayResult:
    """One replay's verdict: the recorded vs replayed chains, the
    bisected divergence (None = byte-identical input streams), the
    oracle battery's violations, and the substitution ledger."""

    recorded_hash: str
    replay_hash: str
    replayed_events: int
    recorded_events: int
    divergence: Optional[Divergence]
    violations: list[str] = dataclasses.field(default_factory=list)
    notes: list[str] = dataclasses.field(default_factory=list)

    @property
    def identical(self) -> bool:
        return self.divergence is None and self.recorded_hash == self.replay_hash


def bisect_divergence(
    capture: Capture, shadow_events: list[dict]
) -> Optional[Divergence]:
    """Walk both streams in lockstep, advancing the recorded chain
    over the SHADOW events' canonical forms: the first position where
    the recomputed hash stops matching the recorded event's embedded
    hash is the first divergent input."""
    chain = capture.header.get("chain", capture_mod.GENESIS)
    mode = capture.clock_mode
    for index, recorded in enumerate(capture.events):
        if index >= len(shadow_events):
            return Divergence(
                serial=recorded.get("serial", index + 1),
                index=index,
                reason="replay-ended-early",
                recorded=recorded,
            )
        replayed = shadow_events[index]
        chain = capture_mod.advance_hash(
            chain, capture_mod.canonical_form(replayed, mode)
        )
        if chain != recorded.get("hash"):
            return Divergence(
                serial=recorded.get("serial", index + 1),
                index=index,
                reason="hash-split",
                recorded=recorded,
                replayed=replayed,
            )
    if len(shadow_events) > len(capture.events):
        extra = shadow_events[len(capture.events)]
        return Divergence(
            serial=capture.events[-1].get("serial", 0) + 1 if capture.events else 1,
            index=len(capture.events),
            reason="replay-extra-events",
            replayed=extra,
        )
    return None


def _rechain(capture: Capture, shadow_events: list[dict]) -> str:
    """The shadow stream's chain computed from the RECORDED genesis —
    comparable to ``capture.final_hash()`` regardless of the shadow's
    own base serial."""
    chain = capture.header.get("chain", capture_mod.GENESIS)
    for event in shadow_events:
        chain = capture_mod.advance_hash(
            chain, capture_mod.canonical_form(event, capture.clock_mode)
        )
    return chain


class ReplayAWSBackend:
    """The recorded AWS outcome stream standing in for the cloud.

    Service ops (``ALL_OPS``) consume the recorded ``aws`` events in
    strict global order: a recorded error re-raises as its typed
    exception (no inner-state mutation — the fault plan that produced
    it is not needed); a recorded success executes the deterministic
    inner fake (rederive mode, the default) or returns the recorded
    payload (``substitute_results=True``).  Everything else — the
    ``calls`` ledger, the oracle helper methods, ``install_fault_plan``
    — delegates to the inner fake so the whole assertion surface works
    on a replayed world."""

    def __init__(
        self,
        inner: FakeAWSBackend,
        recorded: list[dict],
        substitute_results: bool = False,
    ):
        self._inner = inner
        self._recorded = list(recorded)
        self._next = 0
        self._substitute = substitute_results
        self.notes: list[str] = []

    def __getattr__(self, name: str):
        if name in ALL_OPS:
            def op(*args, **kwargs):
                return self._call(name, args, kwargs)

            op.__name__ = name
            return op
        return getattr(self._inner, name)

    def _pop(self, op: str) -> Optional[dict]:
        if self._next >= len(self._recorded):
            self.notes.append(f"aws stream exhausted before {op}")
            return None
        event = self._recorded[self._next]
        data = event.get("data", {})
        if data.get("op") != op:
            # the replayed world asked a different question than the
            # recording answered — leave the stream in place; the
            # bisection names the split, the note names the call
            self.notes.append(
                f"aws stream skew: replay called {op}, recorded "
                f"serial={event.get('serial')} is {data.get('op')}"
            )
            return None
        self._next += 1
        return data

    def _call(self, op: str, args: tuple, kwargs: dict) -> Any:
        data = self._pop(op)
        if data is None:
            return getattr(self._inner, op)(*args, **kwargs)
        error = data.get("error")
        if error is not None:
            err = capture_mod.decode_error(error)
            if isinstance(err, SimulatedCrash) and err.when == "after":
                # the original died AFTER the commit: reproduce the
                # state change, then die at the same boundary
                getattr(self._inner, op)(*args, **kwargs)
            raise err
        if self._substitute:
            return capture_mod.decode_value(data.get("result"))
        return getattr(self._inner, op)(*args, **kwargs)

    def remaining(self) -> int:
        return len(self._recorded) - self._next


class ReplayInformerFeed:
    """Recorded watch batches standing in for the live pump (the
    ``substitute_results`` analog for informers): ``SimHarness.
    informer_feed`` duck-type.  Default (rederive) replays leave this
    unset — the restored cluster re-derives the same batches."""

    def __init__(self, recorded: list[dict]):
        self._by_stream: dict[tuple[str, str], list[dict]] = {}
        for event in recorded:
            data = event.get("data", {})
            key = (data.get("identity", ""), data.get("informerKind", ""))
            batch = dict(data)
            batch["t"] = event.get("t", 0.0)
            self._by_stream.setdefault(key, []).append(batch)

    def due(self, identity: str, kind: str, now: float) -> Iterator[dict]:
        stream = self._by_stream.get((identity, kind))
        while stream and stream[0]["t"] <= now + 1e-9:
            yield stream.pop(0)

    def decode_events(self, batch: dict) -> list:
        from ..cluster.client import WatchEvent

        events = []
        for entry in batch.get("events", ()):
            obj = capture_mod.decode_value(entry.get("obj"))
            events.append(WatchEvent(entry.get("type", "?"), obj))
        return events


# control actions a replay re-injects, by recorded name
_CONTROL_VERBS = (
    "kill_leader",
    "demote_leader",
    "kill_shard_replica",
    "stop_shard_replica",
    "add_shard_replica",
    "request_resize",
)


class ReplayHarness:
    """A recorded incident, re-run.  Use::

        with ReplayHarness(load_capture(path)) as rh:
            rh.run()                       # to the recorded stop instant
            result = rh.result()
            assert result.identical, result.divergence.describe()

    or stop mid-flight for as-of forensics::

        with ReplayHarness(cap) as rh:
            rh.run_to(t)                   # any past virtual instant
            print(rh.explain("default/web"))
    """

    def __init__(
        self,
        capture: Capture,
        substitute_results: bool = False,
        substitute_informers: bool = False,
        oracles: Optional[Callable[[SimHarness], list[str]]] = None,
    ):
        if capture.clock_mode != "virtual" and not substitute_results:
            # a real-clock capture's successes came from real AWS — the
            # inner fake cannot re-derive them
            substitute_results = True
        self.capture = capture
        self._oracles = oracles
        snapshot = capture.snapshot
        self.config = decode_config(snapshot.get("config") or {})
        opaque = (snapshot.get("config") or {}).get("__opaque__")
        self.notes: list[str] = []
        if opaque:
            self.notes.append(
                f"config fields {opaque} were not captured (callable-"
                "bearing); replaying with defaults"
            )
        cluster = FakeCluster()
        cluster_snap = snapshot.get("cluster") or {}
        restored = [
            (entry["kind"], capture_mod.decode_value(entry["obj"]))
            for entry in cluster_snap.get("objects", ())
        ]
        if restored or cluster_snap.get("resourceVersion"):
            cluster.restore(restored, cluster_snap.get("resourceVersion", 0))
        inner = FakeAWSBackend(
            quota_accelerators=self.config.quota_accelerators,
            settle_describes=self.config.settle_describes,
        )
        aws_snap = snapshot.get("aws")
        if aws_snap:
            inner.restore_state(aws_snap)
        self.aws = ReplayAWSBackend(
            inner,
            [
                event
                for event in capture.events_of("aws")
                # guard-level rejections (an open circuit failing fast,
                # a reconcile deadline expiring before the call) were
                # recorded at the instrument seam but never reached the
                # backend — the replay's own health guard re-derives
                # them, so they must not consume the backend stream
                if (event.get("data", {}).get("error") or {}).get("__err__")
                not in ("CircuitOpenError", "DeadlineExceeded")
            ],
            substitute_results=substitute_results,
        )
        self.shadow = IncidentCapture(
            clock_mode=capture.clock_mode, source="replay"
        )
        self.harness = SimHarness(
            cluster=cluster, aws=self.aws, config=self.config,
            capture=self.shadow,
        )
        self._substitute_informers = substitute_informers
        self._entered = False
        self._closed = False
        self._stop_t = self._recorded_stop()

    def _recorded_stop(self) -> float:
        stop = 0.0
        for event in self.capture.events:
            data = event.get("data", {})
            if event.get("kind") == "clock" and data.get("label") == "stop":
                stop = max(stop, float(event.get("t", 0.0)))
        if stop:
            return stop
        if self.capture.events:
            return float(self.capture.events[-1].get("t", 0.0))
        return 0.0

    # ---- lifecycle ----------------------------------------------------
    def __enter__(self) -> "ReplayHarness":
        self.harness.__enter__()
        self._entered = True
        if self._substitute_informers:
            self.harness.informer_feed = ReplayInformerFeed(
                list(self.capture.events_of("informer"))
            )
        self._schedule_injections()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        if self._entered and not self._closed:
            self._closed = True
            self.harness.__exit__(None, None, None)

    # ---- re-injection -------------------------------------------------

    # how many same-instant retries a gated injection tolerates before
    # force-firing (a diverged replay may never reproduce the events
    # the gate waits for; forcing keeps the run moving so the
    # bisection can name the split)
    _GATE_RETRY_LIMIT = 64

    def _schedule_injections(self) -> None:
        for index, event in enumerate(self.capture.events):
            kind = event.get("kind")
            data = event.get("data", {})
            t = float(event.get("t", 0.0))
            if kind == "cluster":
                fn = self._cluster_injector(data)
            elif kind == "control" and data.get("origin") == "external":
                fn = self._control_injector(data)
            elif kind == "signal":
                fn = self._signal_injector(event)
            else:
                continue
            self._schedule_gated(t, kind, fn, index)

    def _schedule_gated(
        self, t: float, kind: str, fn: Callable[[], None], index: int,
        attempts: int = 0,
    ) -> None:
        """Re-inject an external input at its recorded instant AND its
        recorded position in the event stream.  The instant alone is
        not enough: at a shared virtual instant the original run may
        have interleaved harness ticks (a lease renewal, an informer
        pump) BEFORE the scenario's action — the recorded serial
        captures that order exactly, so the injection waits until the
        shadow stream has re-recorded every preceding event.  First
        attempt fires at priority −1 (before co-timed ticks — the
        common case of scenario actions taken before the clock ran);
        when the gate finds preceding events missing it requeues
        itself at priority 2, AFTER the co-timed ticks that must
        produce them."""

        def gated() -> None:
            done = self.shadow.cursor()["serial"]
            if done < index and attempts < self._GATE_RETRY_LIMIT:
                self._schedule_gated(t, kind, fn, index, attempts + 1)
                return
            if done < index:
                self.notes.append(
                    f"injection gate gave up waiting for event {index} "
                    f"(shadow at {done}); forcing"
                )
            fn()

        self.harness.scheduler.call_at(
            t, gated, f"replay-inject:{kind}",
            priority=-1 if attempts == 0 else 2,
        )

    def _cluster_injector(self, data: dict) -> Callable[[], None]:
        method = data.get("method", "")
        kind = data.get("kind", "")

        def inject() -> None:
            cluster = self.harness.cluster
            try:
                if method == "delete":
                    cluster.delete(kind, data.get("namespace", ""), data.get("name", ""))
                else:
                    obj = capture_mod.decode_value(data.get("obj"))
                    getattr(cluster, method)(kind, obj)
            except Exception as err:
                # a failed re-injection is itself divergence evidence;
                # keep replaying so the bisection can report it
                self.notes.append(f"cluster {method} {kind} failed: {err}")
                klog.warningf("replay: cluster inject %s %s: %s", method, kind, err)

        return inject

    def _control_injector(self, data: dict) -> Callable[[], None]:
        action = data.get("action", "")

        def inject() -> None:
            harness = self.harness
            try:
                if action == "kill_leader":
                    harness.kill_leader()
                elif action == "demote_leader":
                    harness.demote_leader()
                elif action == "kill_shard_replica":
                    harness.kill_shard_replica(
                        identity=data.get("identity"),
                        replace=bool(data.get("replace")),
                    )
                elif action == "stop_shard_replica":
                    harness.stop_shard_replica(identity=data.get("identity"))
                elif action == "add_shard_replica":
                    harness.add_shard_replica()
                elif action == "request_resize":
                    harness.request_resize(int(data.get("target", 0)))
                elif action == "aws_seed":
                    args = capture_mod.decode_value(data.get("args")) or []
                    kwargs = capture_mod.decode_value(data.get("kwargs")) or {}
                    getattr(harness.aws, data.get("method", ""))(*args, **kwargs)
                else:
                    self.notes.append(f"unknown control action {action!r}")
            except Exception as err:
                self.notes.append(f"control {action} failed: {err}")
                klog.warningf("replay: control inject %s: %s", action, err)

        return inject

    def _signal_injector(self, event: dict) -> Callable[[], None]:
        def inject() -> None:
            # signals are not reproducible inputs — echo the recorded
            # event onto the shadow chain at its recorded slot
            self.shadow.echo(event)

        return inject

    # ---- running ------------------------------------------------------
    def run_to(self, t: float) -> None:
        """Advance the replayed world to virtual instant ``t`` (capped
        at the recorded stop)."""
        self.harness.run_until(min(t, self._stop_t))

    def run(self) -> None:
        """Replay end to end: to the recorded stop instant, then close
        the harness so the shadow records its stop at the same t."""
        self.harness.run_until(self._stop_t)
        self.close()

    # ---- verdicts -----------------------------------------------------
    def result(self) -> ReplayResult:
        shadow_events = self.shadow.events()
        divergence = bisect_divergence(self.capture, shadow_events)
        return ReplayResult(
            recorded_hash=self.capture.final_hash(),
            replay_hash=_rechain(self.capture, shadow_events),
            replayed_events=len(shadow_events),
            recorded_events=len(self.capture.events),
            divergence=divergence,
            notes=self.notes + self.aws.notes,
        )

    def run_oracles(self) -> list[str]:
        """The standard final-state battery over the replayed world
        (or the constructor's override)."""
        from . import oracles as oracle_mod

        if self._oracles is not None:
            return self._oracles(self.harness)
        return oracle_mod.standard_oracles(
            self.harness, self.config.cluster_name
        )

    def explain(self, key: str, controller: Optional[str] = None) -> dict:
        """The fleet-merged ``/debug/explain`` answer AS OF the
        replayed world's current virtual instant — the time-machine
        query: ``run_to(t)`` first, then ask."""
        answers = {}
        for stack in self.harness.live_stacks():
            engine = getattr(stack.manager, "explain_engine", None)
            if engine is not None:
                answers[stack.identity] = engine.explain(key, controller)
        if not answers:
            return {"key": key, "verdict": "no-live-stack", "controllers": {}}
        return obs_explain.merge_fleet_explains(answers)


def replay_capture(
    source,
    oracles: Optional[Callable[[SimHarness], list[str]]] = None,
    substitute_results: bool = False,
    run_oracles: bool = True,
) -> ReplayResult:
    """One-shot convenience: load (if given a path), replay end to
    end, bisect, and run the oracle battery."""
    capture = source if isinstance(source, Capture) else load_capture(source)
    with ReplayHarness(
        capture, substitute_results=substitute_results, oracles=oracles
    ) as rh:
        rh.run()
        result = rh.result()
        if run_oracles:
            try:
                result.violations = rh.run_oracles()
            except Exception as err:
                result.violations = [f"oracle battery failed: {err!r}"]
    return result


def explain_at(source, t: float, key: str, controller: Optional[str] = None) -> dict:
    """``explain --at``: the verdict for ``key`` at past virtual
    instant ``t`` of a replayed capture."""
    capture = source if isinstance(source, Capture) else load_capture(source)
    with ReplayHarness(capture) as rh:
        rh.run_to(t)
        return rh.explain(key, controller)
