"""Deterministic discrete-event simulation runtime (ISSUE 7).

Runs the ENTIRE controller manager — workqueue rate-limiter delays,
settle poll ticks, drift ticks, GC sweeps, health-plane AIMD/circuit
windows, leader-election lease renewals, informer resyncs, and the
Route53 batcher linger — on virtual time against the fake (or
file-backed fake) AWS backend, single-threaded and byte-replayable
from a seed.  A 10k-Service fleet converges and a 7-virtual-day soak
completes in minutes of wall clock.

- ``runtime``: ``SimClock``/``SimScheduler`` — the virtual clock, the
  event heap with a deterministic ready-queue order, cooperative
  generator actors, and the rolling event-trace hash;
- ``harness``: ``SimHarness`` — assembles a real ``Manager`` (via
  ``Manager.build``) on the sim clock and pumps informers, workers,
  settle polls, drift ticks, GC sweeps and leader electors
  cooperatively;
- ``oracles``: the invariant checks every scenario runs against;
- ``fuzz``: the hypothesis-compatible scenario fuzzer composing
  ``FaultPlan`` primitives (crash × throttle × brownout × racing spec
  edits × leader churn) with seed replay;
- ``capture``/``replay``: the incident time machine (ISSUE 19) — the
  bounded external-input recording of a live or chaos run, and the
  harness that feeds it back through the real manager stack on
  virtual time with first-divergent-event bisection.
"""

from .capture import Capture, IncidentCapture, load_capture
from .runtime import SimClock, SimScheduler, installed
from .harness import SimHarness, SimHarnessConfig
from .replay import ReplayHarness, ReplayResult, replay_capture

__all__ = [
    "Capture",
    "IncidentCapture",
    "ReplayHarness",
    "ReplayResult",
    "SimClock",
    "SimScheduler",
    "SimHarness",
    "SimHarnessConfig",
    "installed",
    "load_capture",
    "replay_capture",
]
