"""SimHarness: the whole Manager on virtual time (ISSUE 7 tentpole).

The harness assembles the SAME objects production runs — controllers
via ``Manager.build`` (so a sim manager can never drift from a real
one), the pending-settle table, the Route53 change batcher, the API
health plane, the read-plane caches, the GC sweeper, and lease-based
leader electors — but wires every clock to a ``SimScheduler`` and
replaces every thread with a cooperative pump:

- **informers** are driven by a non-blocking watch cursor
  (``FakeCluster.events_since``) plus periodic relists
  (``SharedInformer.sync_once``) on the resync timer; a trimmed-
  history gap degrades to a relist exactly like a real 410 Gone;
- **workers** are stepped one item at a time, round-robin over every
  queue in construction order, after every scheduler event — the
  deterministic ready-queue order of the cooperative executor;
- **delayed requeues** (rate-limiter backoff, ``requeue_after``,
  stage yields) sit in each queue's waiting heap; the harness asks
  ``next_delay_deadline()`` and parks a wake event so virtual time
  jumps straight to the next interesting instant;
- **settle polls / drift ticks / GC sweeps / lease renewals /
  resyncs** are recurring scheduler events driving the same
  ``poll_once``/``drift_tick``/``gc_sweep``/``try_acquire_or_renew``
  entry points tests and the bench already use;
- **leader churn** is first-class: N contending electors over the
  shared Lease object; ``kill_leader()`` drops the leading replica's
  whole stack without releasing the lease (crash semantics — the
  standby takes over a full lease_duration later), ``demote_leader()``
  releases cleanly.  A new stack is built by whichever replica
  acquires the lease, resynced from cluster + AWS state — the same
  level-triggered recovery story the process drills prove.

Every worker step, informer delta batch and timer firing folds into
the scheduler's event-trace hash, so one seed ⇒ one interleaving ⇒
one hash — the replay contract ``sim/fuzz.py`` builds on.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Generator, Optional

from .. import klog
from ..autoscaler import (
    AutoscalerLoop,
    ScalePolicy,
    ScalePolicyConfig,
    ScaleSignals,
)
from ..cloudprovider.aws import AWSDriver
from ..cloudprovider.aws.batcher import ChangeBatcher
from ..cloudprovider.aws.cache import (
    AcceleratorTopologyCache,
    DiscoveryCache,
    HostedZoneCache,
    LoadBalancerCoalescer,
    RecordSetCache,
)
from ..cloudprovider.aws.fake_backend import (
    FakeAWSBackend,
    FaultPlan,
    SimulatedCrash,
)
from ..cloudprovider.aws.health import (
    ELBV2_OPS,
    GA_OPS,
    ROUTE53_OPS,
    HealthConfig,
    HealthTracker,
)
from ..cluster import FakeCluster, SharedInformerFactory
from ..controllers import (
    EndpointGroupBindingConfig,
    GlobalAcceleratorConfig,
    Route53Config,
)
from ..controllers.common import with_circuit_backoff
from ..controllers.garbagecollector import GarbageCollectorConfig
from ..leaderelection import LeaderElection, LeaderElectionConfig
from ..manager import ControllerConfig, Manager
from ..observability import fleet as obs_fleet
from ..observability import journey as obs_journey
from ..observability import metrics as obs_metrics
from ..observability import recorder as obs_recorder
from ..observability import slo as obs_slo
from ..cluster import serde
from ..reconcile.pending import PendingSettleTable
from ..reconcile.reconcile import process_next_work_item
from ..sharding import ShardingConfig
from . import capture as capture_mod
from . import runtime
from .capture import IncidentCapture

# a pump round that never quiesces within this many worker steps is a
# livelock (an item requeueing itself with zero delay) — fail loudly
# with the offending queues instead of spinning forever
PUMP_STEP_LIMIT = 1_000_000


@dataclass
class SimHarnessConfig:
    """Knobs for one simulated world.  Defaults favor scenario
    realism at production-shaped timings — virtual time makes the
    long constants free."""

    cluster_name: str = "default"
    replicas: int = 1
    # horizontal sharding (ISSUE 8): shard_count > 1 switches the
    # harness into multi-replica mode — ``replicas`` concurrently-LIVE
    # stacks, each with its own process-world (settle table, batcher,
    # caches, health tracker) and its own shard membership over the
    # shared Lease objects, instead of one active leader
    shard_count: int = 1
    shards_per_replica: int = 0
    resync_period: float = 3600.0
    settle_poll_interval: float = 1.0
    drift_tick_period: float = 0.0  # 0 = off
    gc_sweep_period: float = 0.0  # 0 = off
    gc_grace_sweeps: int = 2
    gc_max_deletes: int = 50
    queue_qps: float = 0.0  # 0 = per-item backoff only
    queue_burst: int = 100
    queue_max_backoff: float = 8.0
    reconcile_deadline: float = 0.0
    # driver pacing (production constants; virtual seconds are free)
    poll_interval: float = 10.0
    poll_timeout: float = 180.0
    lb_not_active_retry: float = 5.0
    accelerator_missing_retry: float = 5.0
    stage_requeue: float = 0.01
    # async mutation pipeline
    r53_batch_linger: float = 0.2
    r53_batch_max: int = 100
    # API health plane; None disables
    health: Optional[HealthConfig] = None
    # read plane TTLs
    discovery_ttl: float = 30.0
    discovery_tags_ttl: float = 300.0
    zone_ttl: float = 60.0
    read_plane_ttl: float = 15.0
    topology_full_ttl: float = 900.0
    # leader election (client-go's 60/15/5 shape by default)
    lease: LeaderElectionConfig = field(
        default_factory=lambda: LeaderElectionConfig(
            lease_duration=60.0, renew_deadline=15.0, retry_period=5.0
        )
    )
    # fake-backend shape when the harness builds it
    quota_accelerators: int = 200
    settle_describes: int = 2
    # the convergence SLO plane (ISSUE 9): evaluation cadence of the
    # per-scenario engine (0 disables journey/SLO tracking entirely);
    # shed gates default OFF in sim — the burn state machine and its
    # metrics run either way, but only a scenario that opts in has
    # sustained burn actually defer GC sweeps / drift ticks
    slo_eval_interval: float = 15.0
    slo_shed_gates: bool = False
    # objective override for the per-scenario SLO engine (None = the
    # shipped default_objectives()).  Autoscale scenarios declare
    # fast-tripping low-threshold objectives so a load wave burns the
    # budget within sim-scale minutes AND the cumulative good fraction
    # can recover above target after the reaction — keeping check_slo
    # meaningful post-scale instead of permanently poisoned by the wave
    slo_objectives: Optional[tuple] = None
    # burn-window override (None = DEFAULT_WINDOWS, 5 m / 1 h).  The
    # autoscale scenarios shrink these so a load wave's burn both
    # trips AND decays inside one sim-scale run — with the production
    # 1 h window the wave would poison scale-in headroom for an hour
    # of virtual time after it ended
    slo_windows: Optional[tuple] = None
    # SLO-driven shard autoscaler (ISSUE 13): arms a harness-level
    # AutoscalerLoop over the scenario's SLO engine, journey tracker,
    # membership state and health planes, executing through the traced
    # request_resize verb.  Sharded mode only.  autoscale_policy is a
    # ScalePolicyConfig (None = defaults — production-shaped cooldowns,
    # usually too slow for sim scenarios)
    autoscale: bool = False
    autoscale_interval: float = 15.0
    autoscale_policy: Optional[ScalePolicyConfig] = None
    # elastic resharding (ISSUE 10): the longest a moving key may sit
    # unowned between its donor's drain and its gainer's adoption
    # before the handoff oracle flags it; 0 = 4 lease retry periods
    # (drain starts only once the adopter is standing by, so the gap
    # is bounded by tick interleaving, not lease expiry)
    handoff_window_budget: float = 0.0
    # incident capture (ISSUE 19): a non-empty path arms an
    # ``IncidentCapture`` tap for the harness's lifetime — every
    # external input (informer batches, AWS outcomes, lease
    # observations, scenario verbs and cluster writes) lands in the
    # bounded JSONL ring so a failed drill replays through
    # ``sim.replay.ReplayHarness``.  The ``AGAC_SIM_CAPTURE`` env var
    # arms the same tap without touching the scenario (the chaos
    # suites' capture-on-failure teardown path).
    capture_path: Optional[str] = None
    capture_max_bytes: int = capture_mod.DEFAULT_MAX_BYTES


# config fields the capture header cannot round-trip (callable-bearing
# or element-type-erased tuples); a capture made with one set records
# its presence so the replay can warn instead of silently differing
_CONFIG_OPAQUE_FIELDS = ("slo_objectives", "slo_windows", "autoscale_policy")


def encode_config(config: SimHarnessConfig) -> dict:
    """Capture-header encoding of the harness config: scalars verbatim,
    nested dataclasses via the serde wire format, opaque fields listed
    by name (the replay restores defaults and warns)."""
    out: dict[str, Any] = {}
    for f in dataclasses.fields(config):
        value = getattr(config, f.name)
        if f.name in _CONFIG_OPAQUE_FIELDS:
            if value is not None:
                out.setdefault("__opaque__", []).append(f.name)
            continue
        if value is None:
            continue
        if dataclasses.is_dataclass(value) and not isinstance(value, type):
            out[f.name] = {"__dc__": type(value).__name__, "fields": serde.to_wire(value)}
        else:
            out[f.name] = value
    return out


def decode_config(data: dict) -> SimHarnessConfig:
    """Inverse of ``encode_config``; the replayed harness never
    re-captures (``capture_path`` is stripped — the shadow stream is
    in-memory by construction)."""
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(SimHarnessConfig):
        if f.name not in data or f.name in ("capture_path",):
            continue
        value = data[f.name]
        if isinstance(value, (dict, list)):
            value = capture_mod.decode_value(value)
        kwargs[f.name] = value
    return SimHarnessConfig(**kwargs)


# one process may build many harnesses (a pytest chaos module, a fuzz
# batch); each gets a distinct capture file under the armed base path
_capture_serials: dict[str, int] = {}
_capture_serial_lock = threading.Lock()


def _next_capture_path(base: str) -> str:
    """The nth harness writing the SAME ``base`` in this process gets
    ``-n`` spliced before the extension (the first keeps the bare
    path) — distinct bases stay untouched, so sequential tests with
    their own paths name their artifacts predictably while a
    multi-harness drill sharing one knob never clobbers itself."""
    with _capture_serial_lock:
        serial = _capture_serials.get(base, 0) + 1
        _capture_serials[base] = serial
    if serial == 1:
        return base
    root, ext = os.path.splitext(base)
    return f"{root}-{serial}{ext or '.jsonl'}"


class _World:
    """One process's shared-memory state: the API health plane, the
    pending-settle table, the Route53 change batcher, the read-plane
    caches and the per-region LB coalescers.  Single-leader mode keeps
    ONE world across leader generations (that state is process-level
    there); sharded mode builds one world PER replica, so concurrently
    live replicas can only communicate through the cluster and AWS —
    never through shared caches, which would be cross-process
    telepathy."""

    def __init__(self, harness: "SimHarness"):
        config = harness.config
        scheduler = harness.scheduler
        self._harness = harness
        # one PRIVATE metrics registry per process-world (ISSUE 9):
        # concurrently-live sim replicas must never fold their
        # counters/gauges into one process-global registry — two
        # replicas' agac_shard_keys_owned summed into one series is
        # exactly the cross-process telepathy the fleet-merge layer
        # exists to do explicitly (and label by shard)
        self.registry = obs_metrics.MetricsRegistry()
        self.health = (
            HealthTracker(
                config=config.health,
                clock=scheduler.monotonic,
                sleep=scheduler.clock.sleep,
                registry=self.registry,
            )
            if config.health is not None
            else None
        )
        self.settle_table = PendingSettleTable(
            clock=scheduler.monotonic, registry=self.registry
        )
        self.batcher = (
            ChangeBatcher(
                max_changes=config.r53_batch_max,
                linger=config.r53_batch_linger,
                clock=scheduler.monotonic,
            )
            if config.r53_batch_linger > 0
            else None
        )
        self.discovery = DiscoveryCache(
            ttl=config.discovery_ttl,
            tags_ttl=config.discovery_tags_ttl or None,
            degraded=(
                (lambda: self.health.is_open("globalaccelerator"))
                if self.health is not None
                else None
            ),
        )
        self.zones = HostedZoneCache(ttl=config.zone_ttl)
        self.topology = AcceleratorTopologyCache(
            verify_ttl=config.read_plane_ttl, full_ttl=config.topology_full_ttl
        )
        self.records = RecordSetCache(
            ttl=config.read_plane_ttl,
            degraded=(
                (lambda: self.health.is_open("route53"))
                if self.health is not None
                else None
            ),
        )
        self.lb_coalescers: dict[str, LoadBalancerCoalescer] = {}

    def cloud_factory(self, region: str) -> AWSDriver:
        harness = self._harness
        if self.health is not None:
            ga = self.health.guard(harness.aws, "globalaccelerator", GA_OPS)
            elbv2 = self.health.guard(harness.aws, f"elbv2[{region}]", ELBV2_OPS)
            route53 = self.health.guard(harness.aws, "route53", ROUTE53_OPS)
        else:
            ga = elbv2 = route53 = harness.aws
        coalescer = self.lb_coalescers.get(region)
        if coalescer is None:
            coalescer = self.lb_coalescers[region] = LoadBalancerCoalescer(
                ttl=harness.config.read_plane_ttl, batch_window=0.0
            )
        return AWSDriver(
            ga,
            elbv2,
            route53,
            poll_interval=harness.config.poll_interval,
            poll_timeout=harness.config.poll_timeout,
            sleep=harness.scheduler.clock.sleep,
            lb_not_active_retry=harness.config.lb_not_active_retry,
            accelerator_missing_retry=harness.config.accelerator_missing_retry,
            discovery_cache=self.discovery,
            zone_cache=self.zones,
            topology_cache=self.topology,
            record_cache=self.records,
            lb_coalescer=coalescer,
            settle_table=self.settle_table,
            change_batcher=self.batcher,
            stage_requeue=harness.config.stage_requeue,
        )


class _WorkerEntry:
    """One queue's cooperative worker: the controller's own
    ``worker_specs()`` entry, circuit-wrapped exactly like
    ``run_workers`` would."""

    __slots__ = (
        "name", "queue", "key_to_obj", "process_delete",
        "process_create_or_update", "on_sync_result", "reconcile_deadline",
    )

    def __init__(self, spec: dict):
        self.name = spec["name"]
        self.queue = spec["queue"]
        self.key_to_obj = spec["key_to_obj"]
        self.process_delete = with_circuit_backoff(spec["process_delete"])
        self.process_create_or_update = with_circuit_backoff(
            spec["process_create_or_update"]
        )
        self.on_sync_result = spec.get("on_sync_result")
        self.reconcile_deadline = spec.get("reconcile_deadline") or None


class _Stack:
    """One controller-process generation: a Manager + informers +
    worker entries, alive while its replica leads."""

    def __init__(
        self,
        harness: "SimHarness",
        identity: str,
        world: Optional[_World] = None,
        controller_config: Optional[ControllerConfig] = None,
    ):
        self.identity = identity
        self.world = world if world is not None else harness.world
        config = (
            controller_config
            if controller_config is not None
            else harness.controller_config
        )
        self.manager = Manager(
            resync_period=harness.config.resync_period,
            health=self.world.health,
            metrics_registry=self.world.registry,
        )
        # stacks hold the RAW cluster: controller writes (status
        # updates, finalizers, leases) are consequences a replay
        # re-derives, never recorded external inputs
        self.informer_factory = SharedInformerFactory(
            harness._raw_cluster,
            harness.config.resync_period,
            clock=harness.scheduler.monotonic,
        )
        self.manager.build(
            harness._raw_cluster, config, self.world.cloud_factory,
            self.informer_factory,
        )
        self.manager.settle_table = self.world.settle_table
        # initial list+sync, then per-informer watch cursors
        self.cursors: dict = {}
        for informer in self.informer_factory.informers():
            self.cursors[informer] = informer.sync_once()
        self.workers: list[_WorkerEntry] = [
            _WorkerEntry(spec)
            for controller in self.manager.controllers.values()
            for spec in controller.worker_specs()
        ]

    def pump_informers(self, harness: "SimHarness") -> bool:
        """Apply new cluster events to every informer and dispatch
        handler deltas inline; True when anything moved.  With a
        capture armed every delivered batch (and every 410-degraded
        relist) lands in the tap — the informer half of the incident
        time machine; with a replay's ``informer_feed`` substituted,
        recorded batches are applied instead of live cluster deltas."""
        if harness.informer_feed is not None:
            return self._pump_recorded(harness)
        moved = False
        tap = harness.capture
        for informer in self.informer_factory.informers():
            events, cursor = harness._raw_cluster.events_since(
                informer.kind, self.cursors[informer]
            )
            if events is None:
                # watch window trimmed (the 410 Gone analog): relist
                self.cursors[informer] = informer.sync_once()
                if tap is not None:
                    tap.record_informer_batch(
                        self.identity, informer.kind, [],
                        cursor=self.cursors[informer], relist=True, delivered=0,
                    )
                harness.scheduler.record("informer", f"{informer.kind}:relist")
                moved = True
                continue
            for event in events:
                informer.apply_event(event)
            self.cursors[informer] = cursor
            delivered = informer.drain_pending_deltas()
            if events or delivered:
                if tap is not None:
                    tap.record_informer_batch(
                        self.identity, informer.kind, events,
                        cursor=cursor, relist=False, delivered=delivered,
                    )
                harness.scheduler.record(
                    "informer", f"{informer.kind}:{len(events)}"
                )
                moved = True
        return moved

    def _pump_recorded(self, harness: "SimHarness") -> bool:
        """Replay-substitution pump: apply the recorded watch batches
        that are due at (or before) the current virtual instant for
        this stack's identity, in recorded order, instead of reading
        the live cluster — the live-capture replay path where the
        recorded stream IS the truth."""
        moved = False
        feed = harness.informer_feed
        tap = harness.capture
        now = harness.scheduler.monotonic()
        for informer in self.informer_factory.informers():
            for batch in feed.due(self.identity, informer.kind, now):
                if batch.get("relist"):
                    self.cursors[informer] = informer.sync_once()
                    if tap is not None:
                        tap.record_informer_batch(
                            self.identity, informer.kind, [],
                            cursor=self.cursors[informer],
                            relist=True, delivered=0,
                        )
                    harness.scheduler.record(
                        "informer", f"{informer.kind}:relist"
                    )
                    moved = True
                    continue
                events = feed.decode_events(batch)
                for event in events:
                    informer.apply_event(event)
                self.cursors[informer] = batch.get("cursor", "")
                delivered = informer.drain_pending_deltas()
                if events or delivered:
                    if tap is not None:
                        tap.record_informer_batch(
                            self.identity, informer.kind, events,
                            cursor=self.cursors[informer],
                            relist=False, delivered=delivered,
                        )
                    harness.scheduler.record(
                        "informer", f"{informer.kind}:{len(events)}"
                    )
                    moved = True
        return moved

    def resync(self, harness: "SimHarness") -> None:
        for informer in self.informer_factory.informers():
            self.cursors[informer] = informer.sync_once()
        harness.scheduler.record("informer", "resync")


class _SimElector:
    """Cooperative lease state machine over the real ``LeaderElection``
    CAS logic — ticked every retry_period by the scheduler instead of
    running acquire/renew threads."""

    def __init__(self, harness: "SimHarness", identity: str):
        self.harness = harness
        self.identity = identity
        self.elector = LeaderElection(
            "agac-sim-controller",
            "kube-system",
            config=harness.config.lease,
            identity=identity,
            clock=harness.scheduler.monotonic,
        )
        self.leading = False
        self.renew_deadline = 0.0
        self.dead = False
        self.event = harness.scheduler.every(
            harness.config.lease.retry_period,
            self.tick,
            f"elector:{identity}",
            first_after=0.0,
        )

    def tick(self) -> None:
        if self.dead:
            return
        acquired, _holder = self.elector.try_acquire_or_renew(
            self.harness._raw_cluster
        )
        now = self.harness.scheduler.monotonic()
        if not self.leading:
            if acquired:
                self.leading = True
                self.renew_deadline = now + self.harness.config.lease.renew_deadline
                self.elector.set_leading(True)
                self.harness._on_leader_acquired(self)
        elif acquired:
            self.renew_deadline = now + self.harness.config.lease.renew_deadline
            if self.harness._stack is None:
                # we lead but no stack exists (a prior guard deferred
                # the build while an old generation drained) — build now
                self.harness._on_leader_acquired(self)
        elif now >= self.renew_deadline:
            self.leading = False
            self.elector.set_leading(False)
            self.harness._on_leader_lost(self)

    def kill(self) -> None:
        """Crash: stop participating WITHOUT releasing the lease."""
        self.dead = True
        self.leading = False
        self.elector.set_leading(False)
        self.event.cancel()

    def release(self) -> None:
        """Graceful shutdown: release the lease so a standby can
        acquire on its next tick instead of waiting out the lease."""
        self.dead = True
        self.leading = False
        self.elector.set_leading(False)
        self.event.cancel()
        self.elector._release(self.harness._raw_cluster)


class _ShardReplica:
    """One concurrently-live sharded controller replica (ISSUE 8): its
    own process-world (settle table, batcher, caches, health tracker),
    its own Manager — whose ``build()`` creates the shard membership
    and filter — and a cooperative membership tick every retry_period.
    The in-sim analog of a separate controller process: replicas talk
    only through the shared cluster and AWS state."""

    def __init__(self, harness: "SimHarness", identity: str):
        self.harness = harness
        self.identity = identity
        self.dead = False
        self.world = _World(harness)
        config = harness.config
        sharding = ShardingConfig(
            shard_count=config.shard_count,
            shards_per_replica=config.shards_per_replica,
            lease=config.lease,
            identity=identity,
        )
        self.controller_config = harness._make_controller_config(sharding)
        self.stack = _Stack(
            harness, identity, world=self.world,
            controller_config=self.controller_config,
        )
        self.stack._sim_replica = self
        # reshard adoptions drop this replica's world snapshots — the
        # adopted chains were written by another replica's driver
        self.stack.manager.on_reshard = self._invalidate_world
        self.tick_event = harness.scheduler.every(
            config.lease.retry_period,
            self.shard_tick,
            f"shard-tick:{identity}",
            first_after=0.0,
        )

    def shard_tick(self) -> None:
        if self.dead:
            return
        manager = self.stack.manager
        try:
            changed = manager.shard_tick(self.harness._raw_cluster)
        except SimulatedCrash as crash:
            self.harness._handle_crash_replica(self, crash)
            return
        if changed:
            self.harness.scheduler.record(
                "shard", f"{self.identity}:{manager.shard_filter.token()}"
            )
        self.harness.check_exclusive_ownership()

    def _invalidate_world(self) -> None:
        world = self.world
        world.discovery.invalidate()
        world.zones.invalidate()
        world.topology.invalidate_all()
        world.records.invalidate_all()

    def kill(self) -> None:
        """Crash semantics: the stack vanishes, the shard leases stay
        HELD until they expire under a survivor's observation."""
        self.dead = True
        self.tick_event.cancel()

    def stop(self) -> None:
        """Graceful shutdown: drop shards locally, then release the
        leases for immediate takeover."""
        self.dead = True
        self.tick_event.cancel()
        self.stack.manager.shard_membership.release_all(
            self.harness._raw_cluster
        )


class _RecordingCluster:
    """The scenario-facing cluster handle while a capture is armed:
    reads pass through untouched; the four mutators record a
    ``cluster`` event AFTER the apiserver accepts them — these writes
    are EXTERNAL inputs (the drill script's own actions), so a replay
    re-injects them at their recorded instants.  Controller-internal
    writes never flow here: stacks, electors and membership hold the
    raw cluster, because their writes are consequences the replay
    re-derives, not inputs."""

    def __init__(self, inner, harness: "SimHarness"):
        self._inner = inner
        self._harness = harness

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _record(self, method: str, kind: str, namespace="", name="", obj=None) -> None:
        tap = self._harness.capture
        if tap is not None:
            tap.record_cluster_mutation(
                method, kind, namespace=namespace, name=name, obj=obj
            )

    def create(self, kind, obj):
        result = self._inner.create(kind, obj)
        self._record("create", kind, obj=obj)
        return result

    def update(self, kind, obj):
        result = self._inner.update(kind, obj)
        self._record("update", kind, obj=obj)
        return result

    def update_status(self, kind, obj):
        result = self._inner.update_status(kind, obj)
        self._record("update_status", kind, obj=obj)
        return result

    def delete(self, kind, namespace, name):
        self._inner.delete(kind, namespace, name)
        self._record("delete", kind, namespace=namespace or "", name=name)


_AWS_SEED_HELPERS = frozenset(
    {"add_load_balancer", "add_hosted_zone", "set_load_balancer_state"}
)


class _RecordingAWS:
    """The scenario-facing AWS handle while a capture is armed: the
    seed helpers (LB registration, hosted-zone creation, LB state
    flips) are EXTERNAL inputs — a drill script conjuring the world —
    so they land on the capture chain as ``aws_seed`` control events
    and a replay re-injects them at their recorded instants.  API ops
    and oracle reads pass straight through; their outcomes are
    captured separately at the instrumented driver seam."""

    def __init__(self, inner, harness: "SimHarness"):
        self._inner = inner
        self._harness = harness

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name not in _AWS_SEED_HELPERS:
            return attr

        def seeded(*args, **kwargs):
            result = attr(*args, **kwargs)
            self._harness._record_control(
                "aws_seed", method=name, args=list(args), kwargs=dict(kwargs)
            )
            return result

        return seeded


class SimHarness:
    """Context manager owning one simulated world.  Use::

        with SimHarness(config=SimHarnessConfig(...)) as h:
            h.cluster.create("Service", make_lb_service())
            h.run_for(300.0)          # five virtual minutes
            assert h.converged(...)
    """

    def __init__(
        self,
        cluster: Optional[FakeCluster] = None,
        aws: Optional[FakeAWSBackend] = None,
        config: Optional[SimHarnessConfig] = None,
        capture: Optional[IncidentCapture] = None,
    ):
        self.config = config or SimHarnessConfig()
        self.scheduler = runtime.SimScheduler()
        self._given_cluster = cluster
        self._given_aws = aws
        self._given_capture = capture
        # the incident tap (ISSUE 19), armed in __enter__; the replay
        # harness sets informer_feed to substitute recorded watch
        # batches for live cluster deltas
        self.capture: Optional[IncidentCapture] = None
        self.informer_feed = None
        self._prev_capture: Optional[IncidentCapture] = None
        self._internal_verbs = 0
        self._installed = False
        self._stack: Optional[_Stack] = None
        self._electors: list[_SimElector] = []
        self._replica_serial = 0
        # sharded multi-replica mode (ISSUE 8)
        self._sharded = self.config.shard_count > 1
        self._replicas: list["_ShardReplica"] = []
        self._queue_wake = None
        self._pumping = False
        self.generations = 0  # stacks built (leadership acquisitions)
        self.violations: list[str] = []
        # elastic resharding (ISSUE 10): unowned-window tracking of
        # moving keys across a drain/handoff, and the violations the
        # check_resize_handoffs oracle surfaces
        self.handoff_violations: list[str] = []
        self._unowned_since: dict[str, float] = {}
        self._resize_requests: list[int] = []
        # SLO-driven autoscaler (ISSUE 13): built in __enter__ when
        # config.autoscale arms it (sharded mode only)
        self.autoscaler: Optional[AutoscalerLoop] = None
        self.autoscaler_recorder: Optional[obs_recorder.FlightRecorder] = None
        # hooks the fuzzer uses: called around every GC sweep so
        # continuous oracles can snapshot ownership immediately before
        # the sweep and attribute each deletion to it precisely
        # (anything deleted BETWEEN sweeps belongs to the ordinary
        # reconcile paths, not the sweeper)
        self.on_gc_sweep_begin: Optional[Callable] = None
        self.on_gc_sweep: Optional[Callable] = None
        # called with (harness, stack) after every generation build —
        # scenario/canary customization point (each leadership
        # acquisition builds a fresh stack, so per-instance patches
        # must be re-applied)
        self.on_stack_built: Optional[Callable] = None

    # ------------------------------------------------------------------
    # world construction (inside the installed seam)
    # ------------------------------------------------------------------
    def __enter__(self) -> "SimHarness":
        from .. import clockseam

        clock = self.scheduler.clock
        clockseam.install(
            monotonic=clock.monotonic,
            wall=clock.time,
            sleep=clock.sleep,
            threads=False,
        )
        self._installed = True
        config = self.config
        self._raw_cluster = self._given_cluster or FakeCluster()
        if not hasattr(self._raw_cluster, "events_since"):
            raise TypeError(
                "SimHarness needs a cluster with events_since (FakeCluster)"
            )
        self.aws = self._given_aws or FakeAWSBackend(
            quota_accelerators=config.quota_accelerators,
            settle_describes=config.settle_describes,
        )
        # fault plan with NO creator exemption: the harness thread IS
        # every controller thread, so an exemption would exempt the
        # whole world.  Oracle reads use the unfaulted helper methods.
        if self.aws.fault_plan is None:
            self.aws.install_fault_plan(FaultPlan(exempt_creator=False))
        self.fault_plan = self.aws.fault_plan

        # the incident capture tap (ISSUE 19): armed by an explicit
        # IncidentCapture (the replay's shadow stream), the config knob,
        # or the AGAC_SIM_CAPTURE env var (chaos-suite teardowns).  The
        # header snapshots cluster + config so a replay reconstructs
        # the world; scenario-facing cluster writes flow through the
        # recording proxy, while stacks/electors keep the raw handle.
        self.capture = self._given_capture
        if self.capture is None:
            path = config.capture_path or os.environ.get("AGAC_SIM_CAPTURE")
            if path:
                self.capture = IncidentCapture(
                    _next_capture_path(path),
                    max_bytes=config.capture_max_bytes,
                    clock_mode="virtual",
                    source="sim",
                    snapshot_fn=self._capture_snapshot,
                )
        if self.capture is not None:
            self._prev_capture = capture_mod.install(self.capture)
            self.capture.record_clock("start")
            self.cluster = _RecordingCluster(self._raw_cluster, self)
            self.aws = _RecordingAWS(self.aws, self)
        else:
            self.cluster = self._raw_cluster

        # the convergence SLO plane (ISSUE 9): one fleet-scoped journey
        # tracker + SLO engine per scenario, on virtual time, installed
        # over the process globals for the harness's lifetime (the
        # reconcile loop and the controllers' enqueue stamps read the
        # global seam) and restored on exit.  Journeys are fleet-wide
        # by design: a key's journey survives the replica that opened
        # it, so a failover's true end-to-end latency is measured.
        self.journey_registry = obs_metrics.MetricsRegistry()
        self.journey = obs_journey.JourneyTracker(
            registry=self.journey_registry, clock=self.scheduler.monotonic
        )
        self._prev_journey = obs_journey.install(self.journey)
        self.slo_engine = obs_slo.SLOEngine(
            registry=self.journey_registry,
            objectives=config.slo_objectives,
            windows=(
                config.slo_windows
                if config.slo_windows is not None
                else obs_slo.DEFAULT_WINDOWS
            ),
            clock=self.scheduler.monotonic,
            journey_tracker=self.journey,
            shed_gates=config.slo_shed_gates,
        )
        self._prev_slo = obs_slo.install_engine(self.slo_engine)
        if config.slo_eval_interval > 0:
            self.scheduler.every(
                config.slo_eval_interval, self.slo_engine.tick, "slo-eval",
                priority=1,
            )
        if config.autoscale:
            # the shard autoscaler (ISSUE 13), tick-driven on the
            # virtual clock; registered AFTER slo-eval so a co-timed
            # evaluation sees this instant's burn, not last round's
            assert self._sharded, "autoscale needs shard_count > 1"
            self._wire_autoscaler()

        if self._sharded:
            # every replica gets its OWN process-world when it is
            # built (add_shard_replica below); the harness-level
            # aliases stay None so nothing accidentally shares state
            self.world = None
            self.health = None
            self.settle_table = None
            self.batcher = None
            self.controller_config = self._make_controller_config()
        else:
            self.world = _World(self)
            self.health = self.world.health
            self.settle_table = self.world.settle_table
            self.batcher = self.world.batcher
            self.controller_config = self._make_controller_config()

        # recurring plumbing ticks (priority 1: after same-instant
        # scenario actors, before nothing in particular — stable order)
        self.scheduler.every(
            config.settle_poll_interval, self._settle_tick, "settle-poll", priority=1
        )
        if config.drift_tick_period > 0:
            self.scheduler.every(
                config.drift_tick_period, self._drift_tick, "drift-tick", priority=1
            )
        if config.gc_sweep_period > 0:
            self.scheduler.every(
                config.gc_sweep_period, self._gc_tick, "gc-sweep", priority=1
            )
        self.scheduler.every(
            config.resync_period, self._resync_tick, "informer-resync", priority=1
        )
        with self._internal():
            if self._sharded:
                for _ in range(config.replicas):
                    self.add_shard_replica()
            else:
                for _ in range(config.replicas):
                    self._add_replica()
        return self

    # ------------------------------------------------------------------
    # incident capture (ISSUE 19)
    # ------------------------------------------------------------------
    def _capture_snapshot(self) -> dict:
        """The capture header's world snapshot: enough to rebuild this
        harness — config plus the cluster store (rv-ordered, with the
        rv counter, so the replay mints the same resourceVersion
        stream).  Re-taken at every ring rotation."""
        objects: list = []
        rv = 0
        if hasattr(self._raw_cluster, "snapshot"):
            pairs, rv = self._raw_cluster.snapshot()
            objects = [
                {"kind": kind, "obj": capture_mod.encode_value(obj)}
                for kind, obj in pairs
            ]
        snapshot = {
            "config": encode_config(self.config),
            "cluster": {"resourceVersion": rv, "objects": objects},
        }
        if hasattr(self.aws, "snapshot_state"):
            snapshot["aws"] = self.aws.snapshot_state()
        return snapshot

    @contextlib.contextmanager
    def _internal(self):
        """Scope marking harness-initiated verbs: control events
        recorded inside carry origin=internal, so a replay knows they
        re-derive (crash handling, replacement replicas, autoscaler
        resizes) instead of needing re-injection."""
        self._internal_verbs += 1
        try:
            yield
        finally:
            self._internal_verbs -= 1

    def _record_control(self, action: str, **fields) -> None:
        if self.capture is not None:
            origin = "internal" if self._internal_verbs else "external"
            self.capture.record_control(action, origin=origin, **fields)

    def _wire_autoscaler(self) -> None:
        """Build the harness-level AutoscalerLoop: signals from the
        scenario's SLO engine / journey tracker, the first live
        replica's membership + key census (any replica works — the
        ring lease is shared truth), the union of live replicas' open
        circuits, and execution through the traced ``request_resize``
        verb.  Decisions land in a dedicated flight recorder so
        scenarios can assert EVERY decision was recorded."""
        config = self.config
        self.autoscaler_recorder = obs_recorder.FlightRecorder(
            capacity=4096, clock=self.scheduler.monotonic
        )

        def resize_status() -> dict:
            live = self.live_replicas()
            if not live:
                return {}
            return live[0].stack.manager.shard_membership.resize_status()

        def keys_by_shard() -> dict:
            live = self.live_replicas()
            if not live:
                return {}
            return live[0].stack.manager.keys_by_shard()

        def open_circuits() -> set:
            services: set = set()
            for replica in self.live_replicas():
                health = replica.world.health
                if health is not None:
                    services.update(health.open_services())
            return services

        signals = ScaleSignals(
            slo_engine=self.slo_engine,
            journey_tracker=self.journey,
            resize_status=resize_status,
            keys_by_shard=keys_by_shard,
            replica_count=lambda: len(self.live_replicas()),
            open_circuits=open_circuits,
            clock=self.scheduler.monotonic,
        )
        policy = ScalePolicy(config.autoscale_policy or ScalePolicyConfig())

        def execute_resize(target_count: int) -> int:
            # autoscaler resizes re-derive on replay (the loop runs
            # again over the same signals) — internal origin
            with self._internal():
                return self.request_resize(target_count)

        self.autoscaler = AutoscalerLoop(
            signals,
            policy,
            execute=execute_resize,
            registry=self.journey_registry,
            flight_recorder=self.autoscaler_recorder,
        )

        def autoscale_tick() -> None:
            if self.live_replicas():
                self.autoscaler.tick()

        self.scheduler.every(
            config.autoscale_interval, autoscale_tick, "autoscale", priority=1
        )

    def _make_controller_config(
        self, sharding: Optional[ShardingConfig] = None
    ) -> ControllerConfig:
        config = self.config
        return ControllerConfig(
            global_accelerator=GlobalAcceleratorConfig(
                cluster_name=config.cluster_name,
                queue_qps=config.queue_qps,
                queue_burst=config.queue_burst,
                queue_max_backoff=config.queue_max_backoff,
                reconcile_deadline=config.reconcile_deadline,
            ),
            route53=Route53Config(
                cluster_name=config.cluster_name,
                queue_qps=config.queue_qps,
                queue_burst=config.queue_burst,
                queue_max_backoff=config.queue_max_backoff,
                reconcile_deadline=config.reconcile_deadline,
            ),
            endpoint_group_binding=EndpointGroupBindingConfig(
                queue_qps=config.queue_qps,
                queue_burst=config.queue_burst,
                queue_max_backoff=config.queue_max_backoff,
                reconcile_deadline=config.reconcile_deadline,
            ),
            garbage_collector=GarbageCollectorConfig(
                interval=config.gc_sweep_period,
                grace_sweeps=config.gc_grace_sweeps,
                max_deletes=config.gc_max_deletes,
                cluster_name=config.cluster_name,
            ),
            settle_poll_interval=config.settle_poll_interval,
            sharding=sharding if sharding is not None else ShardingConfig(),
        )

    def __exit__(self, *exc) -> None:
        from .. import clockseam

        self._installed = False
        if self.capture is not None:
            self.capture.record_clock("stop")
            capture_mod.install(self._prev_capture)
            if self.capture is not self._given_capture:
                self.capture.close()
        obs_journey.install(self._prev_journey)
        obs_slo.install_engine(self._prev_slo)
        clockseam.reset()

    # ------------------------------------------------------------------
    # cloud factory (the per-region driver production would build)
    # ------------------------------------------------------------------
    def cloud_factory(self, region: str) -> AWSDriver:
        """Single-leader mode's driver factory (the process world's);
        sharded replicas each build drivers from their OWN world."""
        return self.world.cloud_factory(region)

    # ------------------------------------------------------------------
    # sharded multi-replica mode (ISSUE 8)
    # ------------------------------------------------------------------
    def add_shard_replica(self) -> "_ShardReplica":
        """Add one concurrently-live sharded replica (its own world,
        manager, membership and informer cursors)."""
        assert self._sharded, "add_shard_replica needs shard_count > 1"
        self._replica_serial += 1
        replica = _ShardReplica(self, f"shard-replica-{self._replica_serial}")
        self._replicas.append(replica)
        self.generations += 1
        self._record_control("add_shard_replica", identity=replica.identity)
        if self.on_stack_built is not None:
            self.on_stack_built(self, replica.stack)
        return replica

    def live_replicas(self) -> list["_ShardReplica"]:
        return [replica for replica in self._replicas if not replica.dead]

    def kill_shard_replica(
        self, identity: Optional[str] = None, replace: bool = False
    ) -> str:
        """Hard-kill a sharded replica (default: the first live one):
        its stack and world vanish, its shard leases stay HELD — a
        survivor with spare capacity steals them one lease_duration
        after the last renewal it observed, then adopts the orphaned
        keyspace via the reshard resync."""
        for replica in self._replicas:
            if replica.dead:
                continue
            if identity is None or replica.identity == identity:
                self._record_control(
                    "kill_shard_replica",
                    identity=replica.identity, replace=replace,
                )
                self.scheduler.record("shard", f"killed:{replica.identity}")
                replica.kill()
                if replace:
                    with self._internal():
                        self.add_shard_replica()
                return replica.identity
        raise RuntimeError(f"no live shard replica matching {identity!r}")

    def stop_shard_replica(self, identity: Optional[str] = None) -> str:
        """Gracefully stop a sharded replica: shards are dropped
        locally first, then the leases released, so successors claim
        them without waiting out the lease duration."""
        for replica in self._replicas:
            if replica.dead:
                continue
            if identity is None or replica.identity == identity:
                self._record_control(
                    "stop_shard_replica", identity=replica.identity
                )
                self.scheduler.record("shard", f"released:{replica.identity}")
                replica.stop()
                return replica.identity
        raise RuntimeError(f"no live shard replica matching {identity!r}")

    def shard_ownership(self) -> dict[str, frozenset[int]]:
        """Live replicas' owned-shard sets — the exclusive-ownership
        oracle's subject."""
        return {
            replica.identity: replica.stack.manager.shard_membership.owned_shards()
            for replica in self.live_replicas()
        }

    def request_resize(self, target_count: int) -> int:
        """The live-resize verb (ISSUE 10): CAS the new shard-count
        target onto the ring lease; every replica's next membership
        tick begins the drain/handoff transition.  The key-level
        exclusive-ownership oracle arms itself for the transition."""
        from ..sharding import request_resize as _request_resize

        epoch = _request_resize(self._raw_cluster, target_count)
        self._resize_requests.append(target_count)
        self._record_control("request_resize", target=target_count, epoch=epoch)
        self.scheduler.record("resize", f"target:{target_count}@e{epoch}")
        return epoch

    def resize_states(self) -> dict[str, dict]:
        """Per-replica resize status (assertion surface)."""
        return {
            replica.identity: (
                replica.stack.manager.shard_membership.resize_status()
            )
            for replica in self.live_replicas()
        }

    def resize_settled(self, target_count: int) -> bool:
        """True once every live replica's membership runs the stable
        target-count ring with no handoffs pending."""
        for status in self.resize_states().values():
            if (
                status["state"] != "stable"
                or status["shard_count"] != target_count
                or status["handoff_pending"]
            ):
                return False
        return True

    def check_exclusive_ownership(self) -> None:
        """The no-key-owned-by-two-shards oracle, continuous form:
        called after every membership tick; any overlap between two
        LIVE replicas' owned sets is appended to ``violations``.
        (A dead replica's stale leases are unowned keyspace, not an
        overlap — nobody enqueues for them until a survivor steals.)

        During a live resize (ISSUE 10) shard indices are not the
        whole truth — a moving key's EFFECTIVE owner depends on the
        drain/handoff state — so the check drops to key granularity:
        every managed key must have at most one live owner through the
        whole transition, and a moving key's unowned window (donor
        drained, gainer not yet adopted) must stay within the handoff
        budget while both sides are alive."""
        ownership = sorted(self.shard_ownership().items())
        for i, (id_a, owned_a) in enumerate(ownership):
            for id_b, owned_b in ownership[i + 1:]:
                overlap = owned_a & owned_b
                if overlap:
                    self.violations.append(
                        f"exclusive-ownership: shards {sorted(overlap)} owned "
                        f"by BOTH {id_a} and {id_b} at "
                        f"t={self.scheduler.monotonic():.1f}"
                    )
        transitioning = any(
            replica.stack.manager.shard_membership.next_ring is not None
            for replica in self.live_replicas()
        )
        if transitioning or self._unowned_since:
            self._check_key_ownership(transitioning)

    # keys beyond which the key-level sweep would dominate the tick
    # (transitions in huge fleets fall back to the shard-set check)
    _KEY_ORACLE_CAP = 10_000

    def _handoff_budget(self) -> float:
        if self.config.handoff_window_budget > 0:
            return self.config.handoff_window_budget
        return 4.0 * self.config.lease.retry_period

    def _check_key_ownership(self, transitioning: bool) -> None:
        from ..controllers.globalaccelerator import is_managed_service
        from ..cluster.objects import meta_namespace_key

        live = self.live_replicas()
        live_identities = {replica.identity for replica in live}
        services, _ = self.cluster.list("Service")
        managed = [
            meta_namespace_key(svc) for svc in services if is_managed_service(svc)
        ]
        if len(managed) > self._KEY_ORACLE_CAP:
            return
        now = self.scheduler.monotonic()
        budget = self._handoff_budget()
        for key in managed:
            owners = [
                replica.identity
                for replica in live
                if replica.stack.manager.shard_filter.owns_key(key)
            ]
            if len(owners) > 1:
                self.violations.append(
                    f"exclusive-ownership: key {key!r} owned by "
                    f"{sorted(owners)} at t={now:.1f}"
                )
                self._unowned_since.pop(key, None)
            elif owners:
                since = self._unowned_since.pop(key, None)
                if since is not None and now - since > budget:
                    self.handoff_violations.append(
                        f"handoff-window: key {key!r} unowned for "
                        f"{now - since:.1f}s (budget {budget:.1f}s)"
                    )
            else:
                # unowned: only a PROTOCOL gap counts against the
                # handoff budget — a dead holder's keyspace waits for
                # the lease steal, which is failover latency, not a
                # drain/handoff defect
                if transitioning and self._key_holders_live(key, live_identities):
                    self._unowned_since.setdefault(key, now)
                else:
                    self._unowned_since.pop(key, None)

    def _key_holders_live(self, key: str, live_identities: set) -> bool:
        """True when every lease the key's handoff depends on — its
        old-ring shard, its new-ring shard, and every OTHER donor the
        gainer's adoption waits for — is held by a LIVE replica: the
        case where an unowned window is the protocol's own latency.
        A dead holder anywhere in that dependency set turns the window
        into failover latency (bounded by the lease steal, not the
        handoff budget), so the clock stops."""
        for replica in self.live_replicas():
            membership = replica.stack.manager.shard_membership
            if membership.next_ring is None or membership.plan is None:
                continue
            s_old = membership.ring.shard_for_key(key)
            s_new = membership.next_ring.shard_for_key(key)
            holders = membership.shard_map()["holders"]
            involved = {s_old, s_new}
            involved.update(membership.plan.donors_of.get(s_new, ()))
            return all(
                holders.get(str(shard)) in live_identities
                for shard in involved
            )
        return False

    # ------------------------------------------------------------------
    # leadership
    # ------------------------------------------------------------------
    def _add_replica(self) -> _SimElector:
        self._replica_serial += 1
        elector = _SimElector(self, f"replica-{self._replica_serial}")
        self._electors.append(elector)
        return elector

    def _on_leader_acquired(self, elector: _SimElector) -> None:
        if self._stack is not None:
            return  # split-brain guard: a live stack keeps running
        klog.infof("sim: %s acquired leadership", elector.identity)
        self.scheduler.record("leader", f"acquired:{elector.identity}")
        self._stack = _Stack(self, elector.identity)
        self.generations += 1
        if self.on_stack_built is not None:
            self.on_stack_built(self, self._stack)

    def _on_leader_lost(self, elector: _SimElector) -> None:
        if self._stack is not None and self._stack.identity == elector.identity:
            self.scheduler.record("leader", f"lost:{elector.identity}")
            self._drop_stack()

    def _drop_stack(self) -> None:
        self._stack = None
        # in-memory only by doctrine: the next generation rebuilds the
        # table from requeue (kill-mid-settle drill semantics)
        self.settle_table.reset()

    def leader(self) -> Optional[str]:
        return self._stack.identity if self._stack is not None else None

    def kill_leader(self) -> None:
        """Hard-kill the leading replica: its stack vanishes, the
        lease stays held — the standby (or a replacement replica)
        takes over one lease_duration after the last renewal it
        observed.  A replacement contender is added so the pool size
        is preserved."""
        for elector in self._electors:
            if self._stack is not None and elector.identity == self._stack.identity:
                self._record_control("kill_leader", identity=elector.identity)
                self.scheduler.record("leader", f"killed:{elector.identity}")
                elector.kill()
                self._drop_stack()
                with self._internal():
                    self._add_replica()
                return
        raise RuntimeError("no leader to kill")

    def _handle_crash(self, crash: SimulatedCrash) -> None:
        klog.warningf("sim: %s — killing leader generation", crash)
        self.scheduler.record("crash", f"{crash.op}:{crash.when}")
        if self._stack is not None:
            # crash recovery is a CONSEQUENCE of the recorded fault
            # plan, not a scenario verb — internal for the replay
            with self._internal():
                self.kill_leader()

    def _handle_crash_replica(
        self, replica: "_ShardReplica", crash: SimulatedCrash
    ) -> None:
        """Sharded-mode crash: the replica whose worker/tick hit the
        crash boundary dies (leases stay held); a replacement contender
        joins so the pool size is preserved, exactly like
        ``kill_leader``."""
        klog.warningf("sim: %s — killing %s", crash, replica.identity)
        self.scheduler.record("crash", f"{crash.op}:{crash.when}")
        self.scheduler.record("shard", f"crashed:{replica.identity}")
        replica.kill()
        with self._internal():
            self.add_shard_replica()

    def demote_leader(self) -> None:
        """Gracefully stop the leading replica (lease released)."""
        for elector in self._electors:
            if self._stack is not None and elector.identity == self._stack.identity:
                self._record_control("demote_leader", identity=elector.identity)
                self.scheduler.record("leader", f"released:{elector.identity}")
                elector.release()
                self._drop_stack()
                with self._internal():
                    self._add_replica()
                return
        raise RuntimeError("no leader to demote")

    # ------------------------------------------------------------------
    # recurring plumbing ticks
    # ------------------------------------------------------------------
    def _settle_tick(self) -> None:
        if self._sharded:
            for replica in self.live_replicas():
                if replica.world.settle_table.depth():
                    try:
                        replica.world.settle_table.poll_once()
                    except SimulatedCrash as crash:
                        self._handle_crash_replica(replica, crash)
            return
        if self._stack is not None and self.settle_table.depth():
            try:
                self.settle_table.poll_once()
            except SimulatedCrash as crash:
                self._handle_crash(crash)

    def _drift_tick(self) -> None:
        if self._sharded:
            for replica in self.live_replicas():
                try:
                    replica.stack.manager.drift_tick()
                except SimulatedCrash as crash:
                    self._handle_crash_replica(replica, crash)
            return
        if self._stack is not None:
            try:
                self._stack.manager.drift_tick()
            except SimulatedCrash as crash:
                self._handle_crash(crash)

    def _gc_tick(self) -> None:
        for stack in self.live_stacks():
            if stack.manager.gc is None:
                continue
            if self.on_gc_sweep_begin is not None:
                self.on_gc_sweep_begin(self)
            try:
                report = stack.manager.gc_sweep()
            except SimulatedCrash as crash:
                if self._sharded:
                    self._handle_crash_replica(stack._sim_replica, crash)
                    continue
                self._handle_crash(crash)
                return
            if self.on_gc_sweep is not None:
                self.on_gc_sweep(self, report)

    def _resync_tick(self) -> None:
        for stack in self.live_stacks():
            stack.resync(self)

    # ------------------------------------------------------------------
    # the cooperative executor
    # ------------------------------------------------------------------
    def live_stacks(self) -> list[_Stack]:
        """Every live stack, in deterministic construction order: the
        leader's (single mode) or one per live sharded replica."""
        if self._sharded:
            return [replica.stack for replica in self.live_replicas()]
        return [self._stack] if self._stack is not None else []

    def settle_tables(self) -> list:
        """Every live pending-settle table (one per process-world)."""
        if self._sharded:
            return [replica.world.settle_table for replica in self.live_replicas()]
        return [self.settle_table] if self.settle_table is not None else []

    def _stack_alive(self, stack: _Stack) -> bool:
        if self._sharded:
            replica = getattr(stack, "_sim_replica", None)
            return replica is not None and not replica.dead
        return self._stack is stack

    def _step_worker(self, stack: _Stack, entry: _WorkerEntry) -> None:
        key = entry.queue.peek()
        self.scheduler.record("work", f"{entry.name}:{key}")
        thread = threading.current_thread()
        original = thread.name
        # the reconcile kernel derives its controller label (metrics,
        # traces, heartbeats) from the worker thread's name
        thread.name = f"{entry.name}-worker-0"
        try:
            process_next_work_item(
                entry.queue,
                entry.key_to_obj,
                entry.process_delete,
                entry.process_create_or_update,
                entry.on_sync_result,
                reconcile_deadline=entry.reconcile_deadline,
            )
        except SimulatedCrash as crash:
            # the in-sim analog of os._exit(137): the "process" whose
            # worker hit this API boundary dies — its whole stack
            # vanishes, its lease(s) stay held, recovery is takeover +
            # level-triggered resync
            if self._sharded:
                self._handle_crash_replica(stack._sim_replica, crash)
            else:
                self._handle_crash(crash)
        finally:
            thread.name = original

    def _pump(self) -> None:
        """Drain everything runnable at the current virtual instant:
        informer deltas, matured queue delays, and every ready work
        item — one item per queue per round, round-robin over every
        live stack, until quiescent.  This is the cooperative
        thread-step executor; its iteration order (stacks in
        construction order; informers then queues in construction
        order within each) IS the deterministic ready-queue order."""
        if self._pumping:
            return  # re-entrancy guard (an actor stepping inside pump)
        self._pumping = True
        try:
            steps = 0
            while True:
                progress = False
                for stack in self.live_stacks():
                    if not self._stack_alive(stack):
                        continue  # crashed earlier in this round
                    progress |= stack.pump_informers(self)
                    for entry in stack.workers:
                        if not self._stack_alive(stack):
                            break  # a crash killed this stack
                        entry.queue.pop_due_delays()
                        if len(entry.queue):
                            self._step_worker(stack, entry)
                            progress = True
                            steps += 1
                if not progress:
                    return
                if steps > PUMP_STEP_LIMIT:
                    depths = {
                        e.name: len(e.queue)
                        for s in self.live_stacks()
                        for e in s.workers
                    }
                    raise RuntimeError(
                        f"sim pump livelock: {steps} worker steps without "
                        f"quiescing (queue depths {depths})"
                    )
        finally:
            self._pumping = False

    def _schedule_queue_wake(self) -> None:
        deadlines = [
            deadline
            for stack in self.live_stacks()
            for entry in stack.workers
            if (deadline := entry.queue.next_delay_deadline()) is not None
        ]
        if not deadlines:
            return
        deadline = min(deadlines)
        if self._queue_wake is not None and not self._queue_wake.cancelled:
            if self._queue_wake.deadline <= deadline:
                return
            self._queue_wake.cancel()
        self._queue_wake = self.scheduler.call_at(
            deadline, lambda: None, "queue-wake", priority=2
        )

    # ------------------------------------------------------------------
    # running
    # ------------------------------------------------------------------
    def run_until(self, deadline: float) -> None:
        """Advance the world to virtual time ``deadline``."""
        assert self._installed, "use `with SimHarness(...) as h:`"
        while True:
            self._pump()
            self._schedule_queue_wake()
            next_deadline = self.scheduler.next_deadline()
            if next_deadline is None or next_deadline > deadline:
                break
            self.scheduler.step()
        self.scheduler.advance_to(deadline)

    def run_for(self, seconds: float) -> None:
        self.run_until(self.scheduler.monotonic() + seconds)

    def run_until_quiescent(
        self, timeout: float, settle_window: float = 0.0
    ) -> bool:
        """Run until no queue holds ready OR delayed work, nothing is
        parked in the settle table, and (optionally) a further
        ``settle_window`` of virtual time passes without any AWS call
        — or until ``timeout`` virtual seconds elapse.  Returns True
        on quiescence."""
        deadline = self.scheduler.monotonic() + timeout
        while self.scheduler.monotonic() < deadline:
            self._pump()
            if not self._busy():
                if settle_window <= 0:
                    return True
                calls_before = len(self.aws.calls)
                self.run_for(settle_window)
                if len(self.aws.calls) == calls_before and not self._busy():
                    return True
                continue
            self._schedule_queue_wake()
            next_deadline = self.scheduler.next_deadline()
            if next_deadline is None or next_deadline > deadline:
                break
            self.scheduler.step()
        return not self._busy()

    def _busy(self) -> bool:
        stacks = self.live_stacks()
        if not stacks:
            return False
        for table in self.settle_tables():
            if table.depth():
                return True
        for stack in stacks:
            for entry in stack.workers:
                if len(entry.queue) or entry.queue.next_delay_deadline() is not None:
                    return True
        return False

    # ------------------------------------------------------------------
    # scenario actors + trace
    # ------------------------------------------------------------------
    def spawn(self, gen: Generator[float, None, None], name: str) -> None:
        self.scheduler.spawn(gen, name)

    def after(self, delay: float, fn: Callable[[], None], name: str) -> None:
        self.scheduler.call_after(delay, fn, name)

    def trace_hash(self) -> str:
        return self.scheduler.trace_hash()

    def fleet_metrics(self) -> str:
        """The fleet-merged exposition over every LIVE replica's
        private world registry plus the scenario's journey registry —
        the in-sim analog of scraping every replica's /metrics/fleet
        (counters/histograms summed, gauges labeled by shard)."""
        sources = {"journeys": self.journey_registry.render}
        for stack in self.live_stacks():
            sources[stack.identity] = stack.world.registry.render
        return obs_fleet.FleetView(sources).render()

    def stats(self) -> dict:
        stats = {
            "virtual_time": round(self.scheduler.monotonic(), 3),
            "events": self.scheduler.events_dispatched,
            "aws_calls": len(self.aws.calls),
            "generations": self.generations,
            "leader": self.leader(),
        }
        if self._sharded:
            stats["replicas"] = [r.identity for r in self.live_replicas()]
            stats["ownership"] = {
                identity: sorted(owned)
                for identity, owned in self.shard_ownership().items()
            }
            stats["settle"] = [table.stats() for table in self.settle_tables()]
        else:
            stats["settle"] = self.settle_table.stats()
            stats["batcher"] = self.batcher.stats() if self.batcher else None
        return stats
