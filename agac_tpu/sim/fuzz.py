"""Scenario fuzzer over the sim harness (ISSUE 7).

One integer seed fully determines a scenario: the churn stream
(create / delete / toggle-managed / flip-hostname / racing spec
edits), the fault composition (throttle bursts × brownout outages ×
ambiguous-commit chaos × one-shot crash faults × leader churn), and
their timing — all drawn from one ``random.Random(seed)`` and played
on the deterministic scheduler.  Same seed ⇒ identical event-trace
hash ⇒ byte-identical replay, which is the whole debugging story: a
CI failure artifact is just ``{seed, profile}``.

After the active phase every fault is lifted, the world runs to
quiescence, and the invariant-oracle battery (``sim/oracles.py``)
plus the continuous GC/circuit oracles decide pass/fail.  The
runtime race/lock-order watchdog (``analysis/racecheck.py``) is armed
for the whole run.

``canary=`` deliberately seeds a bug (used by the mutation test that
proves the fuzzer CAN catch what it claims to catch):

- ``drop-txt-delete`` — record cleanup "forgets" to delete owner TXT
  records, splitting the atomic TXT+A pair: caught by the
  record-atomicity and convergence oracles.
- ``gc-stale-owner-cache`` — the GC sweeper's owner cross-check
  trusts a (broken) cache claiming every owner absent, and the grace
  period is disabled: live owners' accelerators get reaped — caught
  by the live-owner deletion oracle and convergence.  (This is the
  exact bug class the sweeper's apiserver re-verify rail and the
  ``delete-without-ownership-check`` lint rule exist to prevent.)

CLI (the CI ``sim`` job's corpus runner)::

    python -m agac_tpu.sim.fuzz --seeds 1,2,3 --profile quick \
        --artifacts artifacts/

exits non-zero on any violation, writing one JSON artifact per
failing seed (violations + trace tail + replay instructions).
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import Optional

from .. import apis, klog
from ..analysis import racecheck
from ..autoscaler import (
    ACTION_IN,
    ACTION_OUT,
    RAIL_OBSERVE_ONLY,
    ScalePolicyConfig,
)
from ..cloudprovider.aws.health import GA_OPS, ROUTE53_OPS, HealthConfig
from .harness import SimHarness, SimHarnessConfig
from .oracles import (
    CircuitBudgetOracle,
    GCDeletionOracle,
    arm_explain_probes,
    check_explain,
    check_resize_handoffs,
    check_slo,
    standard_oracles,
)

# ops the brownout composition can black out, grouped by service
_SERVICE_OPS = {
    "route53": ROUTE53_OPS,
    "globalaccelerator": GA_OPS,
}

# ops worth throttling / crashing (mutating chain + hot reads)
_FAULTABLE_OPS = [
    "create_accelerator", "update_accelerator", "delete_accelerator",
    "create_listener", "create_endpoint_group", "add_endpoints",
    "describe_accelerator", "list_accelerators",
    "describe_load_balancers", "change_resource_record_sets",
    "list_resource_record_sets", "list_hosted_zones",
]

_CRASHABLE_OPS = [
    "create_accelerator", "update_accelerator", "delete_accelerator",
    "create_listener", "create_endpoint_group",
    "change_resource_record_sets",
]

CANARIES = (
    "drop-txt-delete", "gc-stale-owner-cache", "slo-brownout", "explain-lie",
)

# the slo-brownout / explain-lie canaries' scripted GA outage window
# (virtual seconds) — the explain oracle fuzzes its checkpoints inside
_BROWNOUT_WINDOW = (60.0, 660.0)


@dataclass
class FuzzProfile:
    service_slots: int = 10
    ingress_slots: int = 3
    churn_ops: int = 60
    # virtual length of the active (churn + faults) phase
    active_seconds: float = 2400.0
    heal_seconds: float = 7200.0
    fault_compositions: int = 4
    max_leader_churn: int = 2
    chaos_budget: int = 0  # randomized retryable faults on every op
    hostname_fraction: float = 0.4


PROFILES = {
    # tier-1 shape: one scenario in single-digit wall seconds, still
    # big enough that every canary bug is observable (records exist
    # and get deleted, GC sweeps run inside the active window)
    "mini": FuzzProfile(
        service_slots=6,
        ingress_slots=0,
        churn_ops=30,
        active_seconds=900.0,
        heal_seconds=3600.0,
        fault_compositions=2,
        max_leader_churn=1,
        hostname_fraction=0.6,
    ),
    "quick": FuzzProfile(),
    "deep": FuzzProfile(
        service_slots=25,
        ingress_slots=6,
        churn_ops=220,
        active_seconds=14400.0,
        heal_seconds=14400.0,
        fault_compositions=10,
        max_leader_churn=4,
        chaos_budget=25,
    ),
}


@dataclass
class ScenarioResult:
    seed: int
    profile: str
    canary: Optional[str]
    trace_hash: str
    violations: list[str]
    stats: dict
    trace_tail: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


def _nlb_hostname(i: int) -> str:
    return f"lb{i}-0123456789abcdef.elb.us-west-2.amazonaws.com"


def _install_canary(harness: SimHarness, canary: str) -> None:
    if canary == "drop-txt-delete":
        original = harness.aws.change_resource_record_sets

        def buggy(zone_id, changes):
            kept = [
                change
                for change in changes
                if not (
                    change.action == "DELETE"
                    and change.record_set.type == "TXT"
                )
            ]
            if not kept:
                return None
            return original(zone_id, kept)

        # instance attribute shadows the class method; the backend's
        # fault wrapper still applies on top
        harness.aws.change_resource_record_sets = buggy
    elif canary == "gc-stale-owner-cache":
        # the sweeper's owner cross-check reads a broken cache that
        # says every owner is gone, and grace is off: candidates are
        # "confirmed" and deleted while their owners live
        harness.controller_config.garbage_collector.grace_sweeps = 0

        def break_owner_check(h, stack):
            gc = stack.manager.gc
            if gc is not None:
                gc._owner_exists = lambda resource, ns, name: False

        harness.on_stack_built = break_owner_check
    elif canary == "slo-brownout":
        # the SLO oracle's mutation test (ISSUE 9): a sustained GA
        # outage far longer than the convergence objective — journeys
        # opened during it converge only after restore, burning the
        # error budget.  The oracle must flag the objectives AND the
        # burn-gated shedding (gates armed here, observe-only
        # elsewhere) must be observed deferring GC/drift work.
        harness.slo_engine.shed_gates = True
        ops = sorted(GA_OPS)
        harness.after(
            _BROWNOUT_WINDOW[0],
            lambda: harness.fault_plan.outage(*ops),
            "canary:slo-brownout",
        )
        harness.after(
            _BROWNOUT_WINDOW[1],
            lambda: harness.fault_plan.restore(*ops),
            "canary:slo-brownout-end",
        )
    elif canary == "explain-lie":
        # the explain oracle's mutation test (ISSUE 15): the same GA
        # brownout as slo-brownout, but every stack's classifier is
        # wrapped to swear everything is converged.  check_explain
        # must catch the lie (unconverged objects vouched for) — a
        # scenario where this canary passes means the oracle is blind.
        ops = sorted(GA_OPS)
        harness.after(
            _BROWNOUT_WINDOW[0],
            lambda: harness.fault_plan.outage(*ops),
            "canary:explain-lie",
        )
        harness.after(
            _BROWNOUT_WINDOW[1],
            lambda: harness.fault_plan.restore(*ops),
            "canary:explain-lie-end",
        )

        def lie(h, stack):
            engine = stack.manager.explain_engine
            if engine is None:
                return

            def lying_classify(controller, key, _orig=engine.classify):
                answer = _orig(controller, key)
                answer["verdict"] = "converged"
                return answer

            engine.classify = lying_classify

        harness.on_stack_built = lie
    else:
        raise ValueError(f"unknown canary {canary!r} (have {CANARIES})")


def _make_service(name: str, slot: int, hostname_annotated: bool):
    from ..cluster import ObjectMeta, Service, ServicePort
    from ..cluster.objects import LoadBalancerIngress, ServiceSpec

    annotations = {
        apis.AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external",
        apis.AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
    }
    if hostname_annotated:
        annotations[apis.ROUTE53_HOSTNAME_ANNOTATION] = f"app{slot}.example.com"
    svc = Service(
        metadata=ObjectMeta(
            name=name, namespace="default", annotations=annotations
        ),
        spec=ServiceSpec(
            type="LoadBalancer",
            ports=[ServicePort(name="p80", port=80, protocol="TCP")],
        ),
    )
    svc.status.load_balancer.ingress.append(
        LoadBalancerIngress(hostname=_nlb_hostname(slot))
    )
    return svc


def run_scenario(
    seed: int,
    profile: str = "quick",
    canary: Optional[str] = None,
    no_faults: bool = False,
) -> ScenarioResult:
    """Play one fully seeded scenario; returns the oracle verdicts and
    the replayable trace hash.

    ``no_faults`` drops every fault composition (and the chaos
    budget), keeping only the churn stream — the configuration under
    which the convergence-SLO oracle is ARMED: a fault-free run that
    misses an objective is a real regression, while fault-injected
    runs carry their SLO report in ``stats`` without failing on it
    (blowing the tail under a brownout is what the error budget is
    for; the ``slo-brownout`` canary proves the oracle catches when
    it must)."""
    shape = PROFILES[profile]
    rng = random.Random(seed)
    config = SimHarnessConfig(
        replicas=2,
        resync_period=600.0,
        drift_tick_period=900.0,
        gc_sweep_period=450.0,
        gc_grace_sweeps=2,
        health=HealthConfig(
            window=30.0,
            min_calls=6,
            failure_ratio=0.5,
            open_duration=15.0,
            probe_budget=1,
            aimd_qps=50.0,
        ),
        lease=_fast_lease(),
    )
    watchdog = racecheck.enable()
    try:
        with SimHarness(config=config) as harness:
            for slot in range(shape.service_slots):
                harness.aws.add_load_balancer(
                    f"lb{slot}", "us-west-2", _nlb_hostname(slot)
                )
            harness.aws.add_hosted_zone("example.com")
            if canary is not None:
                _install_canary(harness, canary)
            if canary in ("slo-brownout", "explain-lie"):
                # explain checkpoints (ISSUE 15), fuzzed inside the
                # scripted outage: mid-brownout every unconverged
                # object must classify to a brownout-shaped verdict
                probe_times = sorted(
                    rng.uniform(
                        _BROWNOUT_WINDOW[0] + 90.0,
                        _BROWNOUT_WINDOW[1] - 30.0,
                    )
                    for _ in range(3)
                )
                arm_explain_probes(
                    harness, probe_times, context={"outage": _BROWNOUT_WINDOW}
                )
            gc_oracle = GCDeletionOracle(config.cluster_name).attach(harness)
            harness.run_for(15.0)  # leadership + initial sync
            gc_oracle.prime()
            if shape.chaos_budget and not no_faults:
                harness.fault_plan.chaos(
                    rng.randrange(1 << 30), shape.chaos_budget, p=0.08,
                    ambiguous=0.3,
                )

            circuit_oracles: list[CircuitBudgetOracle] = []
            harness.spawn(
                _churn_actor(harness, rng, shape), "churn"
            )
            if not no_faults:
                _schedule_faults(harness, rng, shape, circuit_oracles)

            harness.run_for(shape.active_seconds)
            # lift standing faults (outages + chaos); any scripted
            # one-shots still queued fire as transients during heal
            harness.fault_plan.restore()
            harness.fault_plan.refill(0)
            quiesced = harness.run_until_quiescent(
                shape.heal_seconds, settle_window=3 * 60.0
            )

            violations = list(harness.violations)
            if not quiesced:
                violations.append(
                    "quiescence: world still busy after "
                    f"{shape.heal_seconds}s virtual heal window"
                )
            violations += standard_oracles(harness, config.cluster_name)
            violations += gc_oracle.violations
            for oracle in circuit_oracles:
                violations += oracle.violations
            # the convergence-SLO oracle (ISSUE 9): armed for
            # fault-free runs and for the canary built to trip it;
            # fault-injected runs carry the report in stats only
            harness.slo_engine.tick()  # final window advance
            slo_violations = check_slo(harness)
            if no_faults or canary == "slo-brownout":
                violations += slo_violations
            if canary in ("slo-brownout", "explain-lie"):
                violations += check_explain(harness)
            try:
                watchdog.assert_clean()
            except AssertionError as err:
                violations.append(f"racecheck: {err}")
            stats = harness.stats()
            stats["slo"] = {
                "violations": slo_violations,
                "shedding": harness.slo_engine.shedding,
                "shed_activations": harness.slo_engine.shed_activations,
                "journeys": harness.journey.stats(),
            }
            return ScenarioResult(
                seed=seed,
                profile=profile,
                canary=canary,
                trace_hash=harness.trace_hash(),
                violations=violations,
                stats=stats,
                trace_tail=list(harness.scheduler.trace_tail)[-200:],
            )
    finally:
        racecheck.disable()


def run_resize_scenario(
    seed: int,
    profile: str = "mini",
    no_faults: bool = False,
) -> ScenarioResult:
    """The resize-under-faults canary (ISSUE 10): a sharded fleet
    (2 shards, 3 replicas) churns while a mid-run live resize to 4
    shards is composed with replica death (kill -9 semantics: the
    closest sharded analog of leader churn) and a seeded service
    brownout.  Oracles armed: the standard battery PLUS key-level
    exclusive ownership through the transition and the handoff-window
    oracle — and the scenario itself asserts the transition COMPLETED
    despite the faults (a wedged resize is a failure even when nothing
    else broke)."""
    shape = PROFILES[profile]
    rng = random.Random(seed)
    config = SimHarnessConfig(
        replicas=3,
        shard_count=2,
        shards_per_replica=4,
        resync_period=600.0,
        drift_tick_period=900.0,
        # the GC sweeper mops up deletes whose events died with a
        # killed replica or landed in a handoff gap — the same
        # level-triggered safety net the standard scenario runs
        gc_sweep_period=450.0,
        gc_grace_sweeps=2,
        health=HealthConfig(
            window=30.0,
            min_calls=6,
            failure_ratio=0.5,
            open_duration=15.0,
            probe_budget=1,
            aimd_qps=50.0,
        ),
        lease=_fast_lease(),
    )
    watchdog = racecheck.enable()
    try:
        with SimHarness(config=config) as harness:
            for slot in range(shape.service_slots):
                harness.aws.add_load_balancer(
                    f"lb{slot}", "us-west-2", _nlb_hostname(slot)
                )
            harness.aws.add_hosted_zone("example.com")
            harness.run_for(15.0)  # membership + initial sync
            harness.spawn(_churn_actor(harness, rng, shape), "churn")
            resize_at = rng.uniform(0.25, 0.45) * shape.active_seconds
            harness.after(
                resize_at, lambda: harness.request_resize(4), "resize-to-4"
            )
            # explain checkpoints (ISSUE 15) fuzzed into and past the
            # transition window: each replica's answer must agree with
            # its own shard filter (owners never disclaim, non-owners
            # answer not-owner/unowned-resize) while keys are moving
            probe_times = sorted(
                resize_at + rng.uniform(2.0, 150.0) for _ in range(3)
            )
            arm_explain_probes(
                harness, probe_times, context={"sharded": True}
            )
            if not no_faults:
                # replica death composed INTO the transition window
                kill_at = resize_at + rng.uniform(
                    0.0, 3 * config.lease.retry_period
                )
                harness.after(
                    kill_at,
                    lambda: harness.kill_shard_replica(replace=True),
                    "kill-replica-mid-resize",
                )
                service = rng.choice(sorted(_SERVICE_OPS))
                window = rng.uniform(60.0, 180.0)
                _schedule_brownout(
                    harness,
                    resize_at + rng.uniform(0.0, 60.0),
                    service,
                    window,
                    [],
                )
            harness.run_for(shape.active_seconds)
            harness.fault_plan.restore()
            harness.fault_plan.refill(0)
            quiesced = harness.run_until_quiescent(
                shape.heal_seconds, settle_window=3 * 60.0
            )
            # orphans whose delete events died with a killed replica
            # (or in a handoff gap) clear only through GC grace — give
            # the sweeper its grace_sweeps+1 intervals, then re-settle
            harness.run_for(3 * 450.0)
            quiesced = quiesced and harness.run_until_quiescent(
                shape.heal_seconds, settle_window=3 * 60.0
            )
            violations = list(harness.violations)
            if not quiesced:
                violations.append(
                    "quiescence: world still busy after "
                    f"{shape.heal_seconds}s virtual heal window"
                )
            violations += standard_oracles(harness, config.cluster_name)
            violations += check_explain(harness)
            if not harness.resize_settled(4):
                violations.append(
                    f"resize: fleet never settled at 4 shards under faults: "
                    f"{harness.resize_states()}"
                )
            try:
                watchdog.assert_clean()
            except AssertionError as err:
                violations.append(f"racecheck: {err}")
            stats = harness.stats()
            stats["resize"] = {
                identity: status
                for identity, status in harness.resize_states().items()
            }
            stats["handoff_violations"] = check_resize_handoffs(harness)
            return ScenarioResult(
                seed=seed,
                profile=profile,
                canary="resize",
                trace_hash=harness.trace_hash(),
                violations=violations,
                stats=stats,
                trace_tail=list(harness.scheduler.trace_tail)[-200:],
            )
    finally:
        racecheck.disable()


# --- the SLO-driven autoscaler scenarios (ISSUE 13) -----------------------
#
# Sim-scale burn windows: the production 5 m / 1 h windows would let a
# load wave poison scale-in headroom for an hour of virtual time after
# it ended; 2 m / 10 m keeps trip AND decay inside one mini run.
AUTOSCALE_WINDOWS = (120.0, 600.0)
# the load wave: a burst of GA-managed services big enough to saturate
# per-replica workqueue admission (the scarce resource queue_qps
# models), and a post-reaction "echo" burst that must converge fast on
# the scaled-out fleet
_WAVE_SIZE = 28
_ECHO_SIZE = 8
_WAVE_AT = 180.0


def _autoscale_objectives():
    from ..observability.slo import GA_CONTROLLERS, SLOObjective

    # one fast-tripping objective: GA chains within 60 s (a
    # JOURNEY_BUCKETS bound) at a target relaxed enough that the
    # cumulative good fraction can recover above it after the
    # autoscaler reacts — keeping check_slo meaningful post-scale
    return (
        SLOObjective("ga_converge_wave_p99", 60.0, GA_CONTROLLERS, target=0.7),
    )


def _autoscale_policy(
    observe_only: bool = False, brownout_hold: float = 600.0
) -> ScalePolicyConfig:
    # production-shaped rails scaled to the mini profile's 900 s active
    # window; cooldowns still outlast the membership plane's ~30 s
    # placement hysteresis by 3x/15x
    return ScalePolicyConfig(
        min_shards=2,
        max_shards=4,
        burn_threshold=1.0,
        age_growth_evals=3,
        age_floor_seconds=90.0,
        headroom_evals=4,
        headroom_burn=0.25,
        cooldown_out_seconds=90.0,
        cooldown_in_seconds=450.0,
        brownout_hold_seconds=brownout_hold,
        observe_only=observe_only,
    )


def _autoscale_config(
    observe_only: bool = False, brownout_hold: float = 600.0
) -> SimHarnessConfig:
    return SimHarnessConfig(
        replicas=4,
        shard_count=2,
        shards_per_replica=2,
        resync_period=1800.0,
        gc_sweep_period=450.0,
        gc_grace_sweeps=2,
        # per-replica workqueue admission is the scarce resource the
        # wave saturates: more shards spread across more replicas =
        # more aggregate admission capacity, which is exactly the
        # lever the autoscaler pulls.  0.1 qps/burst 2 means a
        # 14-key-per-owner wave queues for ~2 minutes at 2 shards but
        # ~1 minute at 4 — the difference the objective sees
        queue_qps=0.1,
        queue_burst=2,
        health=HealthConfig(
            window=30.0,
            min_calls=6,
            failure_ratio=0.5,
            open_duration=15.0,
            probe_budget=1,
            aimd_qps=50.0,
        ),
        lease=_fast_lease(),
        slo_objectives=_autoscale_objectives(),
        slo_windows=AUTOSCALE_WINDOWS,
        autoscale=True,
        autoscale_interval=30.0,
        autoscale_policy=_autoscale_policy(
            observe_only=observe_only, brownout_hold=brownout_hold
        ),
    )


def _autoscale_stats(harness: SimHarness) -> dict:
    decisions = harness.autoscaler.history()
    return {
        "status": harness.autoscaler.status(),
        "decisions": len(decisions),
        "executed": [
            (d["time"], d["action"], d["target_shards"])
            for d in decisions
            if d["executed"]
        ],
        "suppressed_recommendations": sum(
            1 for d in decisions if RAIL_OBSERVE_ONLY in d["rails"]
        ),
        "resize": dict(harness.resize_states()),
    }


def run_autoscale_scenario(
    seed: int,
    profile: str = "mini",
    no_faults: bool = False,
    observe_only: bool = False,
) -> ScenarioResult:
    """The closed-loop canary (ISSUE 13): a sharded fleet (2 shards, 4
    replicas) under background churn takes a load wave that saturates
    per-replica queue admission and blows the convergence objective.
    The autoscaler — not the scenario script — must notice the burn /
    age growth and execute the 2→4 resize within its evaluation
    budget; a replica kill is composed into the wave window (the CI
    canary: load wave × replica kill).  After the wave the fleet must
    scale back in on sustained headroom, with zero oscillation and
    every decision flight-recorded.  ``observe_only`` flips the policy
    to recommendation-only and instead asserts the wave produced a
    suppressed recommendation and NO resize was ever requested."""
    shape = PROFILES[profile]
    rng = random.Random(seed)
    config = _autoscale_config(observe_only=observe_only)
    watchdog = racecheck.enable()
    try:
        with SimHarness(config=config) as harness:
            for slot in range(shape.service_slots):
                harness.aws.add_load_balancer(
                    f"lb{slot}", "us-west-2", _nlb_hostname(slot)
                )
            wave_slots = list(range(100, 100 + _WAVE_SIZE))
            echo_slots = list(range(200, 200 + _ECHO_SIZE))
            for slot in wave_slots + echo_slots:
                harness.aws.add_load_balancer(
                    f"lb{slot}", "us-west-2", _nlb_hostname(slot)
                )
            harness.aws.add_hosted_zone("example.com")
            harness.run_for(15.0)  # membership + initial sync
            harness.spawn(_churn_actor(harness, rng, shape), "churn")

            def wave_actor():
                # a tight burst: arrival rate far above the per-owner
                # admission rate, so the backlog actually queues
                for slot in wave_slots:
                    harness.cluster.create(
                        "Service", _make_service(f"wavesvc{slot}", slot, False)
                    )
                    yield rng.uniform(0.5, 1.5)

            def echo_actor():
                for slot in echo_slots:
                    harness.cluster.create(
                        "Service", _make_service(f"echosvc{slot}", slot, False)
                    )
                    yield rng.uniform(2.0, 6.0)

            harness.after(
                _WAVE_AT,
                lambda: harness.spawn(wave_actor(), "load-wave"),
                "load-wave-start",
            )
            # the echo burst lands after the reaction budget: it must
            # converge fast on the scaled-out fleet, which is what
            # pulls the cumulative good fraction back over target
            harness.after(
                _WAVE_AT + 480.0,
                lambda: harness.spawn(echo_actor(), "echo-wave"),
                "echo-wave-start",
            )
            # observe-only runs the SAME wave + fault as the acting run:
            # the whole point is proving the identical evidence produces
            # a suppressed recommendation instead of a resize
            if not no_faults:
                harness.after(
                    _WAVE_AT + 60.0,
                    lambda: harness.kill_shard_replica(replace=True),
                    "kill-replica-in-wave",
                )
            harness.run_for(shape.active_seconds)
            harness.fault_plan.restore()
            harness.fault_plan.refill(0)
            quiesced = harness.run_until_quiescent(
                shape.heal_seconds, settle_window=3 * 60.0
            )
            # the quiet the scale-in evidence needs: wave burn must
            # age out of the long window, then the headroom streak and
            # scale-in cooldown must elapse, then the 4→2 transition
            # itself must drain/hand off
            harness.run_for(900.0)
            quiesced = quiesced and harness.run_until_quiescent(
                shape.heal_seconds, settle_window=3 * 60.0
            )
            harness.run_for(3 * 450.0)  # GC grace for killed-replica orphans
            quiesced = quiesced and harness.run_until_quiescent(
                shape.heal_seconds, settle_window=3 * 60.0
            )

            violations = list(harness.violations)
            if not quiesced:
                violations.append(
                    "quiescence: world still busy after "
                    f"{shape.heal_seconds}s virtual heal window"
                )
            violations += standard_oracles(harness, config.cluster_name)
            decisions = harness.autoscaler.history()
            executed = [d for d in decisions if d["executed"]]
            outs = [d for d in executed if d["action"] == ACTION_OUT]
            ins = [d for d in executed if d["action"] == ACTION_IN]
            status = harness.autoscaler.status()
            recorded = harness.autoscaler_recorder.recorded_total
            if recorded != status["evaluations"]:
                violations.append(
                    f"autoscale: {status['evaluations']} evaluations but "
                    f"{recorded} flight-recorded decisions"
                )
            if observe_only:
                recommended = [
                    d for d in decisions if RAIL_OBSERVE_ONLY in d["rails"]
                ]
                if not recommended:
                    violations.append(
                        "autoscale: observe-only run never produced a "
                        "suppressed recommendation (proof is vacuous)"
                    )
                if executed or harness._resize_requests:
                    violations.append(
                        "autoscale: observe-only run requested a resize: "
                        f"{harness._resize_requests}"
                    )
                if not harness.resize_settled(2):
                    violations.append(
                        "autoscale: observe-only fleet left 2 shards: "
                        f"{harness.resize_states()}"
                    )
            else:
                if not outs:
                    violations.append(
                        "autoscale: load wave never produced an executed "
                        "scale-out"
                    )
                else:
                    reaction = outs[0]["time"] - _WAVE_AT
                    if reaction > 450.0:
                        violations.append(
                            "autoscale: scale-out reacted too slowly "
                            f"({reaction:.0f}s after the wave started)"
                        )
                    if outs[0]["target_shards"] != 4:
                        violations.append(
                            "autoscale: first scale-out targeted "
                            f"{outs[0]['target_shards']} shards, expected 4"
                        )
                if outs and not ins:
                    violations.append(
                        "autoscale: fleet never scaled back in after the wave"
                    )
                if not harness.resize_settled(2):
                    violations.append(
                        "autoscale: fleet did not return to 2 shards: "
                        f"{harness.resize_states()}"
                    )
                # post-reaction SLO verdict: cumulative good fraction
                # must have recovered over target — the whole point of
                # the reaction
                harness.slo_engine.tick()
                violations += check_slo(harness)
            try:
                watchdog.assert_clean()
            except AssertionError as err:
                violations.append(f"racecheck: {err}")
            stats = harness.stats()
            stats["autoscale"] = _autoscale_stats(harness)
            stats["slo"] = {
                "violations": check_slo(harness),
                "journeys": harness.journey.stats(),
            }
            return ScenarioResult(
                seed=seed,
                profile=profile,
                canary="autoscale-observe" if observe_only else "autoscale",
                trace_hash=harness.trace_hash(),
                violations=violations,
                stats=stats,
                trace_tail=list(harness.scheduler.trace_tail)[-200:],
            )
    finally:
        racecheck.disable()


def run_autoscale_brownout_scenario(
    seed: int, profile: str = "mini"
) -> ScenarioResult:
    """The brownout discrimination proof (ISSUE 13): a sustained GA
    outage wedges every journey opened during it; they converge — and
    burn the error budget — only AFTER the restore, when the circuit
    is already closed again.  The autoscaler must attribute that burn
    to the provider (open-circuit exclusion + the brownout hold) and
    execute ZERO scale-outs, and the proof must be non-vacuous: the
    decision history has to actually show both-window burn AND
    excluded objectives."""
    shape = PROFILES[profile]
    rng = random.Random(seed)
    config = _autoscale_config(brownout_hold=900.0)
    watchdog = racecheck.enable()
    try:
        with SimHarness(config=config) as harness:
            for slot in range(shape.service_slots):
                harness.aws.add_load_balancer(
                    f"lb{slot}", "us-west-2", _nlb_hostname(slot)
                )
            wedge_slots = list(range(100, 108))
            for slot in wedge_slots:
                harness.aws.add_load_balancer(
                    f"lb{slot}", "us-west-2", _nlb_hostname(slot)
                )
            harness.aws.add_hosted_zone("example.com")
            harness.run_for(15.0)
            harness.spawn(_churn_actor(harness, rng, shape), "churn")
            ops = sorted(GA_OPS)
            harness.after(
                120.0, lambda: harness.fault_plan.outage(*ops), "brownout"
            )

            def wedge_actor():
                # a burst INTO the outage: failing creates trip the
                # owner replicas' breakers and wedge enough journeys
                # that the post-restore burn is unmistakable
                for slot in wedge_slots:
                    harness.cluster.create(
                        "Service", _make_service(f"wavesvc{slot}", slot, False)
                    )
                    yield rng.uniform(1.0, 4.0)

            harness.after(
                130.0,
                lambda: harness.spawn(wedge_actor(), "wedge-wave"),
                "wedge-wave-start",
            )
            harness.after(
                420.0,
                lambda: harness.fault_plan.restore(*ops),
                "brownout-end",
            )
            harness.run_for(shape.active_seconds)
            harness.fault_plan.restore()
            harness.fault_plan.refill(0)
            quiesced = harness.run_until_quiescent(
                shape.heal_seconds, settle_window=3 * 60.0
            )
            violations = list(harness.violations)
            if not quiesced:
                violations.append(
                    "quiescence: world still busy after "
                    f"{shape.heal_seconds}s virtual heal window"
                )
            violations += standard_oracles(harness, config.cluster_name)
            decisions = harness.autoscaler.history()
            executed_out = [
                d
                for d in decisions
                if d["executed"] and d["action"] == ACTION_OUT
            ]
            if executed_out:
                violations.append(
                    "autoscale: scaled out on provider-outage burn at "
                    f"t={executed_out[0]['time']}"
                )
            if not any(
                d["evidence"].get("excluded_objectives") for d in decisions
            ):
                violations.append(
                    "autoscale: brownout never excluded an objective — "
                    "the no-scale-out assertion is vacuous"
                )

            def burning(decision):
                for per in decision["evidence"]["burn"].values():
                    if per and all(rate >= 1.0 for rate in per.values()):
                        return True
                return False

            if not any(burning(d) for d in decisions):
                violations.append(
                    "autoscale: outage never produced both-window burn — "
                    "the no-scale-out assertion is vacuous"
                )
            if not harness.resize_settled(2):
                violations.append(
                    "autoscale: brownout fleet left 2 shards: "
                    f"{harness.resize_states()}"
                )
            status = harness.autoscaler.status()
            recorded = harness.autoscaler_recorder.recorded_total
            if recorded != status["evaluations"]:
                violations.append(
                    f"autoscale: {status['evaluations']} evaluations but "
                    f"{recorded} flight-recorded decisions"
                )
            try:
                watchdog.assert_clean()
            except AssertionError as err:
                violations.append(f"racecheck: {err}")
            stats = harness.stats()
            stats["autoscale"] = _autoscale_stats(harness)
            return ScenarioResult(
                seed=seed,
                profile=profile,
                canary="autoscale-brownout",
                trace_hash=harness.trace_hash(),
                violations=violations,
                stats=stats,
                trace_tail=list(harness.scheduler.trace_tail)[-200:],
            )
    finally:
        racecheck.disable()


def _fast_lease():
    from ..leaderelection import LeaderElectionConfig

    # production shape scaled to scenario length (lease churn must be
    # observable inside the active window)
    return LeaderElectionConfig(
        lease_duration=60.0, renew_deadline=15.0, retry_period=5.0
    )


def _churn_actor(harness: SimHarness, rng: random.Random, shape: FuzzProfile):
    """Generator actor: one cluster mutation per step, spaced by
    seeded virtual delays.  Mixes creates, deletes, managed-annotation
    toggles, hostname flips, no-op touches, and racing double-edits."""
    live: dict[str, bool] = {}  # name -> hostname_annotated

    def step():
        slot = rng.randrange(shape.service_slots)
        name = f"svc{slot}"
        if name not in live:
            hostname = rng.random() < shape.hostname_fraction
            harness.cluster.create(
                "Service", _make_service(name, slot, hostname)
            )
            live[name] = hostname
            return
        roll = rng.random()
        if roll < 0.30:
            harness.cluster.delete("Service", "default", name)
            del live[name]
        elif roll < 0.50:  # toggle managed off/on
            obj = harness.cluster.get("Service", "default", name)
            if apis.AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION in obj.metadata.annotations:
                obj.metadata.annotations.pop(
                    apis.AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION
                )
                obj.metadata.annotations.pop(apis.ROUTE53_HOSTNAME_ANNOTATION, None)
                live[name] = False
            else:
                obj.metadata.annotations[
                    apis.AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION
                ] = "true"
            harness.cluster.update("Service", obj)
        elif roll < 0.70:  # flip the route53 hostname annotation
            obj = harness.cluster.get("Service", "default", name)
            if apis.ROUTE53_HOSTNAME_ANNOTATION in obj.metadata.annotations:
                obj.metadata.annotations.pop(apis.ROUTE53_HOSTNAME_ANNOTATION)
                live[name] = False
            elif (
                apis.AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION
                in obj.metadata.annotations
            ):
                obj.metadata.annotations[
                    apis.ROUTE53_HOSTNAME_ANNOTATION
                ] = f"app{slot}.example.com"
                live[name] = True
            harness.cluster.update("Service", obj)
        elif roll < 0.85:  # racing spec edits: two writes, same instant
            obj = harness.cluster.get("Service", "default", name)
            obj.metadata.labels["touched"] = str(rng.randrange(1 << 30))
            harness.cluster.update("Service", obj)
            obj = harness.cluster.get("Service", "default", name)
            obj.metadata.labels["touched"] = str(rng.randrange(1 << 30))
            harness.cluster.update("Service", obj)
        else:  # plain touch
            obj = harness.cluster.get("Service", "default", name)
            obj.metadata.labels["touched"] = str(rng.randrange(1 << 30))
            harness.cluster.update("Service", obj)

    spacing = shape.active_seconds * 0.75 / max(shape.churn_ops, 1)
    for _ in range(shape.churn_ops):
        step()
        yield rng.uniform(0.2 * spacing, 1.8 * spacing)


def _schedule_faults(
    harness: SimHarness,
    rng: random.Random,
    shape: FuzzProfile,
    circuit_oracles: list,
) -> None:
    """Compose fault primitives across the active window."""
    leader_churns = 0
    for index in range(shape.fault_compositions):
        at = rng.uniform(0.1, 0.8) * shape.active_seconds
        kind = rng.choice(["throttle", "brownout", "crash", "leader", "hang"])
        if kind == "throttle":
            op = rng.choice(_FAULTABLE_OPS)
            times = rng.randint(1, 5)
            harness.after(
                at,
                lambda op=op, times=times: harness.fault_plan.throttle(
                    op, times=times
                ),
                f"fault:throttle:{index}",
            )
        elif kind == "hang":
            op = rng.choice(_FAULTABLE_OPS)
            harness.after(
                at,
                lambda op=op: harness.fault_plan.hang_until_deadline(op),
                f"fault:hang:{index}",
            )
        elif kind == "brownout":
            service = rng.choice(sorted(_SERVICE_OPS))
            window = rng.uniform(60.0, 240.0)
            _schedule_brownout(
                harness, at, service, window, circuit_oracles
            )
        elif kind == "crash":
            op = rng.choice(_CRASHABLE_OPS)
            when = rng.choice(["before", "after-commit"])
            harness.after(
                at,
                lambda op=op, when=when: harness.fault_plan.crash(op, when=when),
                f"fault:crash:{index}",
            )
        elif kind == "leader" and leader_churns < shape.max_leader_churn:
            leader_churns += 1
            graceful = rng.random() < 0.5

            def churn(graceful=graceful):
                if harness.leader() is None:
                    return
                if graceful:
                    harness.demote_leader()
                else:
                    harness.kill_leader()

            harness.after(at, churn, f"fault:leader:{index}")


def _schedule_brownout(
    harness: SimHarness,
    at: float,
    service: str,
    window: float,
    circuit_oracles: list,
) -> None:
    ops = _SERVICE_OPS[service]
    oracle = CircuitBudgetOracle(harness, ops, service)
    circuit_oracles.append(oracle)
    health_config = harness.config.health

    def start():
        harness.fault_plan.outage(*sorted(ops))
        harness.scheduler.record("fault", f"brownout:{service}")
        # sample for the breaker trip a few times inside the window
        for i in range(1, 6):
            harness.after(
                i * window / 6.0, sample, f"brownout-probe:{service}"
            )

    def sample():
        if (
            harness.health is not None
            and harness.health.is_open(service)
            and oracle._open_observed_at_call_index is None
        ):
            oracle.circuit_opened()

    def end():
        harness.fault_plan.restore(*sorted(ops))
        harness.scheduler.record("fault", f"brownout-end:{service}")
        if health_config is not None:
            oracle.window_ended(
                health_config.open_duration, window, health_config.probe_budget
            )

    harness.after(at, start, f"fault:brownout:{service}")
    harness.after(at + window, end, f"fault:brownout-end:{service}")


# ---------------------------------------------------------------------------
# corpus runner (the CI `sim` job)
# ---------------------------------------------------------------------------


def replay_corpus(directory) -> int:
    """Replay every checked-in incident capture under ``directory``
    (the CI ``replay-corpus`` gate, ISSUE 19): each must re-run
    byte-identically and pass the oracle battery.  A capture that
    stops replaying identically means a behavior change reached the
    recorded external-input contract — either fix the regression or
    deliberately re-record the capture."""
    from .replay import replay_capture

    paths = sorted(directory.glob("*.jsonl"))
    if not paths:
        print(f"no captures under {directory}")
        return 0
    failures = 0
    for path in paths:
        try:
            result = replay_capture(path)
        except Exception as err:
            print(f"{path.name} FAIL replay crashed: {err!r}")
            failures += 1
            continue
        ok = result.identical and not result.violations
        print(
            f"{path.name} {'ok' if ok else 'FAIL'} "
            f"events={result.recorded_events} "
            f"hash={result.recorded_hash[:16]}"
        )
        if not ok:
            failures += 1
            if result.divergence is not None:
                print(result.divergence.describe())
            for violation in result.violations:
                print(f"  - {violation}")
            for note in result.notes:
                print(f"  note: {note}")
    return 1 if failures else 0


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import pathlib

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seeds", default="1,2,3,4,5")
    parser.add_argument("--profile", default="quick", choices=sorted(PROFILES))
    parser.add_argument("--canary", default=None, choices=CANARIES)
    parser.add_argument(
        "--scenario", default="standard",
        choices=("standard", "resize", "autoscale", "autoscale-brownout"),
        help="'resize' plays the sharded resize-under-faults scenario "
        "(live 2→4 resize composed with replica death + brownout, "
        "key-level ownership and handoff oracles armed) instead of the "
        "single-leader churn scenario; 'autoscale' plays the "
        "closed-loop canary (load wave × replica kill — the autoscaler "
        "itself must execute the 2→4→2 resizes, no-oscillation + "
        "ownership oracles armed); 'autoscale-brownout' proves burn "
        "caused by a provider outage never scales the fleet out",
    )
    parser.add_argument(
        "--no-faults", action="store_true",
        help="churn only, no fault compositions — ARMS the "
        "convergence-SLO oracle (a fault-free run missing an "
        "objective is a regression)",
    )
    parser.add_argument("--artifacts", default=None)
    parser.add_argument(
        "--captures", default=None, metavar="DIR",
        help="replay-corpus mode: replay every incident capture "
        "(*.jsonl) under DIR through the ReplayHarness and require a "
        "byte-identical event-trace hash plus a clean oracle battery; "
        "exits non-zero on any divergence — the regression gate for "
        "checked-in captures (seeds are ignored in this mode)",
    )
    args = parser.parse_args(argv)

    if args.captures:
        return replay_corpus(pathlib.Path(args.captures))

    failures = 0
    for seed in [int(s) for s in args.seeds.split(",") if s]:
        if args.scenario == "resize":
            result = run_resize_scenario(
                seed, profile=args.profile, no_faults=args.no_faults
            )
        elif args.scenario == "autoscale":
            result = run_autoscale_scenario(
                seed, profile=args.profile, no_faults=args.no_faults
            )
        elif args.scenario == "autoscale-brownout":
            result = run_autoscale_brownout_scenario(
                seed, profile=args.profile
            )
        else:
            result = run_scenario(
                seed, profile=args.profile, canary=args.canary,
                no_faults=args.no_faults,
            )
        status = "ok" if result.ok else "FAIL"
        print(
            f"seed {seed} [{args.profile}] {status} "
            f"trace={result.trace_hash[:16]} "
            f"virtual={result.stats['virtual_time']}s "
            f"calls={result.stats['aws_calls']}"
        )
        if not result.ok:
            failures += 1
            for violation in result.violations:
                print(f"  - {violation}")
            if args.artifacts:
                directory = pathlib.Path(args.artifacts)
                directory.mkdir(parents=True, exist_ok=True)
                artifact = directory / f"seed-{seed}.json"
                artifact.write_text(
                    json.dumps(
                        {
                            "seed": seed,
                            "profile": args.profile,
                            "canary": result.canary,
                            "trace_hash": result.trace_hash,
                            "violations": result.violations,
                            "stats": result.stats,
                            "trace_tail": result.trace_tail,
                            "replay": (
                                "python -m agac_tpu.sim.fuzz "
                                f"--seeds {seed} --profile {args.profile}"
                            ),
                        },
                        indent=2,
                    )
                )
                klog.infof("wrote %s", artifact)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
