"""The incident time machine's recording half (ISSUE 19): a bounded,
versioned, append-only JSONL ring of every EXTERNAL input a running
controller consumed.

The sim runtime (ISSUE 7) made a scenario byte-replayable *given its
script*; the explain plane (ISSUE 15) made a wedged live fleet
*diagnosable*.  What neither gives an operator is reproduction: a
failed chaos drill or live incident could be read about but not re-run.
This module closes that gap by taping the full external-input stream —
everything nondeterministic a controller observes — so ``replay.py``
can feed it back through the REAL manager stack on virtual time:

- **informer batches** — every list/watch delivery with its cursor
  (and relists, the 410-Gone path), per stack identity;
- **AWS call outcomes** — every service call post-classification
  (the ``InstrumentedAPI`` hook): success payload or typed error,
  exactly as the driver saw it;
- **lease observations** — every leader-election acquire/renew
  verdict (``LeaderElection.try_acquire_or_renew``);
- **delivered signals** — SIGINT/SIGTERM arrivals;
- **clockseam reads at capture boundaries** — start/stop/rotation
  timestamps anchoring the window;
- **control verbs and external cluster mutations** — the scenario's
  own actions (chaos kills, resizes, object writes), so a drill's
  script rides inside its own capture.

Divergence bisection rides on a rolling hash: every event embeds
``hash_k = sha256(hash_{k-1} + canonical(event_k))``.  A replay
recomputes the same chain over what actually happened and the FIRST
serial where the chains split IS the first divergent input — the
nondeterminism the static determinism audit (PR 12) cannot see.

Ring discipline: the active segment rotates to ``<path>.1`` when it
exceeds ``max_bytes`` (or ``max_age``); each segment re-emits a header
carrying the chain state and a fresh cluster snapshot, and the loader
tolerates a torn trailing record (a crashed writer's partial line),
so the capture is crash-safe by construction.

The process-global seam (``install``/``active``) mirrors the journey
tracker's: the sim harness installs a virtual-clock capture for a
scenario's lifetime; ``--capture-path`` installs a wall-clock one for
a live controller.  Every ``record_*`` entry point is strictly
contained — telemetry must never fail the hot path it observes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
from typing import Any, Callable, Iterator, Optional

from .. import clockseam, klog
from ..cluster import serde

CAPTURE_VERSION = 1
DEFAULT_MAX_BYTES = 16 * 1024 * 1024
# the chain's genesis: a constant, NOT a hash of the header — the
# replay's shadow chain must be comparable to the recorded chain
# without reproducing the header (whose snapshot a replay consumes,
# not re-emits)
GENESIS = "0" * 64

# fields the canonical form never hashes: server-filled identity and
# wall-clock stamps the fake apiserver mints from the REAL clock
# (``FakeCluster._now``/uuid4), which differ between a capture and its
# replay without any behavioral divergence; ``duration`` is wall-ish
# latency bookkeeping, not an input
_SCRUB_KEYS = frozenset({"uid", "creationTimestamp", "deletionTimestamp", "duration"})
# in real-clock captures the boundary clock reads themselves are
# content that can never match a virtual-time replay
_REAL_MODE_SCRUB = frozenset({"monotonic", "wall"})


class CaptureFormatError(Exception):
    """The file is not a loadable capture (bad header, wrong version)."""


# ---------------------------------------------------------------------------
# value codec: dataclasses round-trip through the serde wire format
# with a class-name tag; typed errors round-trip as code+message
# ---------------------------------------------------------------------------

_classes: Optional[dict[str, type]] = None


def _registered_classes() -> dict[str, type]:
    """Every dataclass the codec can revive by name: the cluster kinds
    and the AWS wire types.  Built lazily so importing the seam from
    observability code never drags the whole object model in."""
    global _classes
    if _classes is None:
        from .. import leaderelection
        from ..cloudprovider.aws import health as aws_health
        from ..cloudprovider.aws import types as aws_types
        from ..cluster import objects as cluster_objects

        registry: dict[str, type] = {}
        for mod in (cluster_objects, aws_types, aws_health, leaderelection):
            for name in dir(mod):
                cls = getattr(mod, name)
                if isinstance(cls, type) and dataclasses.is_dataclass(cls):
                    registry[name] = cls
        _classes = registry
    return _classes


def encode_value(value: Any) -> Any:
    """JSON-able encoding of anything a tap may record: dataclasses
    (tagged with their class name), exceptions, containers, scalars.
    Unknown objects degrade to their repr — a capture must always
    write, even for payloads it cannot revive."""
    if isinstance(value, BaseException):
        return encode_error(value)
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {"__dc__": type(value).__name__, "fields": serde.to_wire(value)}
    if isinstance(value, (list, tuple)):
        return [encode_value(v) for v in value]
    if isinstance(value, dict):
        return {str(k): encode_value(v) for k, v in value.items()}
    if value is None or isinstance(value, (str, int, float, bool)):
        return value
    return {"__repr__": repr(value)}


def decode_value(value: Any) -> Any:
    """Inverse of ``encode_value``; unknown class tags decode to their
    raw wire dicts rather than failing (forward compatibility)."""
    if isinstance(value, list):
        return [decode_value(v) for v in value]
    if isinstance(value, dict):
        if "__dc__" in value:
            cls = _registered_classes().get(value["__dc__"])
            if cls is None:
                return value.get("fields", value)
            return serde.from_wire(cls, value.get("fields") or {})
        if "__err__" in value:
            return decode_error(value)
        if "__repr__" in value:
            return value["__repr__"]
        return {k: decode_value(v) for k, v in value.items()}
    return value


def encode_error(err: BaseException) -> dict:
    out: dict[str, Any] = {"__err__": type(err).__name__, "message": str(err)}
    code = getattr(err, "code", None)
    if code:
        out["code"] = code
    # the fault plan's crash boundary (a BaseException) carries its
    # op/when so a replay re-raises the identical crash
    for attr in ("op", "when"):
        value = getattr(err, attr, None)
        if isinstance(value, str):
            out[attr] = value
    return out


def decode_error(data: dict) -> BaseException:
    """Revive a recorded error as the typed exception the driver saw:
    a ``SimulatedCrash`` by op/when, a known AWS error subclass by
    name, else a generic ``AWSAPIError`` carrying the recorded code."""
    name = data.get("__err__", "")
    if name == "SimulatedCrash":
        from ..cloudprovider.aws.fake_backend import SimulatedCrash

        return SimulatedCrash(data.get("op", "?"), data.get("when", "before"))
    from ..cloudprovider.aws import errors as aws_errors

    cls = getattr(aws_errors, name, None)
    code = data.get("code") or ""
    message = data.get("message", "")
    # the recorded message is str(err), which the AWSAPIError family
    # renders as "{code}: {body}" — strip the prefix before feeding a
    # constructor that re-applies it, so a revived error round-trips
    # to the identical wire form (the replay hash depends on it)
    body = message
    if code and message.startswith(code + ": "):
        body = message[len(code) + 2:]
    elif code and message == code:
        body = ""
    if (
        isinstance(cls, type)
        and issubclass(cls, BaseException)
        and cls is not aws_errors.AWSAPIError
    ):
        try:
            return cls(body)
        except TypeError:
            pass
    return aws_errors.AWSAPIError(code or name, body)


# ---------------------------------------------------------------------------
# the canonical form + rolling hash (the bisection substrate)
# ---------------------------------------------------------------------------


def _scrub(value: Any, extra: frozenset) -> Any:
    if isinstance(value, dict):
        return {
            k: _scrub(v, extra)
            for k, v in value.items()
            if k not in _SCRUB_KEYS and k not in extra
        }
    if isinstance(value, list):
        return [_scrub(v, extra) for v in value]
    return value


def canonical_form(event: dict, clock_mode: str) -> str:
    """The hashed view of one event: kind + payload (scrubbed of
    server-minted identity), plus the virtual timestamp in virtual-clock
    captures (timing IS behavior there) but not in real-clock ones
    (where only content can ever match a replay).  Serial and the
    embedded hash are excluded — alignment is positional, and the hash
    cannot cover itself."""
    body = {k: v for k, v in event.items() if k not in ("hash", "serial", "record")}
    extra = frozenset()
    if clock_mode != "virtual":
        body.pop("t", None)
        extra = _REAL_MODE_SCRUB
    return json.dumps(_scrub(body, extra), sort_keys=True, separators=(",", ":"))


def advance_hash(prev: str, canonical: str) -> str:
    return hashlib.sha256((prev + canonical).encode()).hexdigest()


# ---------------------------------------------------------------------------
# the capture tap
# ---------------------------------------------------------------------------


def _instruments():
    global _metrics
    if _metrics is None:
        from ..observability.instruments import capture_instruments

        _metrics = capture_instruments()
    return _metrics


_metrics = None


class IncidentCapture:
    """One recording: an append-only JSONL segment ring (or, with
    ``path=None``, an in-memory event list — the replay's shadow
    stream).  All ``record_*`` methods are contained: a serialization
    or I/O failure drops the event (counted) and never raises."""

    def __init__(
        self,
        path: Optional[str] = None,
        *,
        max_bytes: int = DEFAULT_MAX_BYTES,
        max_age: float = 0.0,
        clock_mode: str = "real",
        source: str = "live",
        clock: Callable[[], float] = clockseam.monotonic,
        snapshot_fn: Optional[Callable[[], dict]] = None,
        record_payloads: bool = True,
    ):
        self.path = path
        self.max_bytes = max(4096, int(max_bytes))
        self.max_age = max_age
        self.clock_mode = clock_mode
        self.source = source
        self.record_payloads = record_payloads
        self._clock = clock
        self._snapshot_fn = snapshot_fn
        self._lock = threading.Lock()
        self._serial = 0
        self._chain = GENESIS
        self._offset = 0
        self._segment_started = self._clock()
        self._closed = False
        self._rotate_pending = False
        self._file = None
        self._events: list[dict] = []  # in-memory mode only
        self.dropped = 0
        self.rotations = 0
        if path is not None:
            self._file = open(path, "w", encoding="utf-8")
            self._write_header(self._take_snapshot())

    # ---- ring mechanics ----------------------------------------------
    def _take_snapshot(self) -> dict:
        """Call the world-snapshot hook.  NEVER under ``self._lock``:
        the hook walks cluster/AWS state whose own paths record into
        this tap under their locks — snapshotting inside the capture
        lock would close a lock cycle (the lock-order gate catches
        exactly this pairing)."""
        if self._snapshot_fn is None:
            return {}
        try:
            return self._snapshot_fn()
        except Exception as err:
            klog.errorf("incident capture: snapshot failed: %s", err)
            return {"error": str(err)}

    def _header_record(self, snapshot: dict) -> dict:
        return {
            "record": "header",
            "version": CAPTURE_VERSION,
            "clockMode": self.clock_mode,
            "source": self.source,
            "baseSerial": self._serial,
            "chain": self._chain,
            "monotonic": round(clockseam.monotonic(), 6),
            "wall": round(clockseam.time(), 6),
            "snapshot": snapshot,
        }

    def _write_header(self, snapshot: dict) -> None:
        line = json.dumps(self._header_record(snapshot), sort_keys=True) + "\n"
        self._file.write(line)
        self._file.flush()
        self._offset = len(line.encode("utf-8"))

    def _rotate_locked(self, snapshot: dict) -> None:
        """Size/age cap reached: the active segment becomes ``.1``
        (evicting the previous rotation — the ring holds at most two
        segments) and a fresh segment opens with a header carrying the
        chain state, so each file verifies stand-alone.  ``snapshot``
        was taken by the caller before acquiring the lock."""
        self._file.close()
        os.replace(self.path, self.path + ".1")
        self.rotations += 1
        self._segment_started = self._clock()
        self._file = open(self.path, "w", encoding="utf-8")
        self._write_header(snapshot)
        try:
            _instruments().rotations.inc()
        except Exception:
            pass

    def _append_locked(self, event: dict) -> None:
        if self._file is not None:
            line = json.dumps(event, sort_keys=True) + "\n"
            data = line.encode("utf-8")
            self._file.write(line)
            self._file.flush()
            self._offset += len(data)
            aged = self.max_age > 0 and (
                self._clock() - self._segment_started > self.max_age
            )
            if self._offset >= self.max_bytes or aged:
                # rotation is DEFERRED to the next record: the fresh
                # header wants a world snapshot, and the snapshot hook
                # must run outside self._lock (see _take_snapshot) —
                # the crossing event stays in the old segment either
                # way, so the segmentation is unchanged
                self._rotate_pending = True
        else:
            self._events.append(event)

    # ---- the one true entry point ------------------------------------
    def record_event(self, kind: str, data: dict) -> None:
        if self._closed:
            return
        try:
            snapshot = None
            if self._rotate_pending:
                # racy read is fine: a concurrent recorder may have
                # rotated already (snapshot discarded below) or may
                # set the flag right after (rotation waits one event)
                snapshot = self._take_snapshot()
            with self._lock:
                if self._rotate_pending:
                    self._rotate_locked(
                        snapshot if snapshot is not None else {}
                    )
                    self._rotate_pending = False
                self._serial += 1
                event = {
                    "record": "event",
                    "serial": self._serial,
                    "t": round(self._clock(), 6),
                    "kind": kind,
                    "data": data,
                }
                self._chain = advance_hash(
                    self._chain, canonical_form(event, self.clock_mode)
                )
                event["hash"] = self._chain
                self._append_locked(event)
        except Exception as err:
            self.dropped += 1
            klog.errorf("incident capture: dropping %s event: %s", kind, err)
            try:
                _instruments().drops.inc()
            except Exception:
                pass
            return
        try:
            metrics = _instruments()
            metrics.events.labels(kind=kind).inc()
            metrics.last_serial.set(float(self._serial))
        except Exception:
            pass

    # ---- typed taps ---------------------------------------------------
    def record_aws_call(
        self,
        service: str,
        op: str,
        outcome: str,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        result: Any = None,
        error: Optional[BaseException] = None,
        duration: Optional[float] = None,
    ) -> None:
        data: dict[str, Any] = {"service": service, "op": op, "outcome": outcome}
        if self.record_payloads:
            data["args"] = encode_value(list(args))
            if kwargs:
                data["kwargs"] = encode_value(kwargs)
        if error is not None:
            data["error"] = encode_error(error)
        elif self.record_payloads:
            data["result"] = encode_value(result)
        if duration is not None:
            data["duration"] = round(duration, 6)
        self.record_event("aws", data)

    def record_informer_batch(
        self,
        identity: str,
        kind: str,
        events: list,
        cursor: str,
        relist: bool = False,
        delivered: int = 0,
    ) -> None:
        data: dict[str, Any] = {
            "identity": identity,
            "informerKind": kind,
            "cursor": str(cursor),
            "relist": relist,
            "delivered": delivered,
        }
        encoded = []
        for event in events:
            entry: dict[str, Any] = {"type": getattr(event, "type", "?")}
            obj = getattr(event, "obj", None)
            meta = getattr(obj, "metadata", None)
            if meta is not None:
                entry["name"] = meta.name
                entry["namespace"] = meta.namespace
                entry["resourceVersion"] = meta.resource_version
            if self.record_payloads:
                entry["obj"] = encode_value(obj)
            encoded.append(entry)
        data["events"] = encoded
        self.record_event("informer", data)

    def record_lease_observation(
        self, lease: str, identity: str, acquired: bool, holder: str
    ) -> None:
        self.record_event(
            "lease",
            {
                "lease": lease,
                "identity": identity,
                "acquired": bool(acquired),
                "holder": holder or "",
            },
        )

    def record_signal(self, signum: int) -> None:
        self.record_event("signal", {"signal": int(signum)})

    def record_clock(self, label: str) -> None:
        self.record_event(
            "clock",
            {
                "label": label,
                "monotonic": round(clockseam.monotonic(), 6),
                "wall": round(clockseam.time(), 6),
            },
        )

    def record_control(self, action: str, origin: str = "external", **fields) -> None:
        data = {"action": action, "origin": origin}
        for key, value in fields.items():
            data[key] = encode_value(value)
        self.record_event("control", data)

    def record_cluster_mutation(
        self,
        method: str,
        kind: str,
        namespace: str = "",
        name: str = "",
        obj: Any = None,
    ) -> None:
        data: dict[str, Any] = {
            "method": method,
            "kind": kind,
            "namespace": namespace or "",
            "name": name or "",
        }
        if obj is not None and self.record_payloads:
            data["obj"] = encode_value(obj)
        self.record_event("cluster", data)

    def echo(self, event: dict) -> None:
        """Re-record a foreign event verbatim on THIS chain (the replay
        harness re-emitting a non-reproducible input — a signal — at
        its recorded slot, keeping the shadow stream aligned)."""
        self.record_event(event.get("kind", "?"), event.get("data", {}))

    # ---- observation surface -----------------------------------------
    def cursor(self) -> dict:
        """Where the recording stands: the post-mortem pointer the
        flight recorder and /debug/flightrecorder surface, naming the
        exact capture window to replay."""
        with self._lock:
            return {
                "file": self.path or "<memory>",
                "offset": self._offset,
                "serial": self._serial,
            }

    def trace_hash(self) -> str:
        with self._lock:
            return self._chain

    def events(self) -> list[dict]:
        """In-memory mode's event list (the replay's shadow stream)."""
        with self._lock:
            return list(self._events)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._file is not None:
                self._file.close()
                self._file = None


# ---------------------------------------------------------------------------
# the process-global seam (the journey-tracker install pattern)
# ---------------------------------------------------------------------------

_active: Optional[IncidentCapture] = None


def install(tap: Optional[IncidentCapture]) -> Optional[IncidentCapture]:
    """Install ``tap`` as the process's capture (None uninstalls);
    returns the previous one so scopes nest correctly."""
    global _active
    previous = _active
    _active = tap
    return previous


def active() -> Optional[IncidentCapture]:
    return _active


# ---------------------------------------------------------------------------
# loading (crash-tolerant) + verification
# ---------------------------------------------------------------------------


class Capture:
    """One loaded segment: header + events, oldest first."""

    def __init__(
        self, header: dict, events: list[dict], path: str = "", truncated: bool = False
    ):
        self.header = header
        self.events = events
        self.path = path
        self.truncated = truncated

    @property
    def clock_mode(self) -> str:
        return self.header.get("clockMode", "real")

    @property
    def snapshot(self) -> dict:
        return self.header.get("snapshot") or {}

    def final_hash(self) -> str:
        if self.events:
            return self.events[-1].get("hash", "")
        return self.header.get("chain", GENESIS)

    def events_of(self, *kinds: str) -> Iterator[dict]:
        for event in self.events:
            if event.get("kind") in kinds:
                yield event

    def verify(self) -> Optional[int]:
        """Recompute the rolling hash over the recorded events; returns
        the serial of the first event whose embedded hash does not
        match (a torn or tampered record), or None when the chain
        holds end to end."""
        chain = self.header.get("chain", GENESIS)
        for event in self.events:
            chain = advance_hash(chain, canonical_form(event, self.clock_mode))
            if event.get("hash") != chain:
                return event.get("serial")
        return None


def load_capture(path: str) -> Capture:
    """Load one segment, tolerating a torn trailing record (the
    partial line a crashed writer leaves): decoding stops at the first
    unparseable line and the capture is marked ``truncated``."""
    header: Optional[dict] = None
    events: list[dict] = []
    truncated = False
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            stripped = line.strip()
            if not stripped:
                continue
            if not line.endswith("\n"):
                truncated = True  # torn tail: no newline ever made it
                break
            try:
                record = json.loads(stripped)
            except ValueError:
                truncated = True
                break
            if header is None:
                if record.get("record") != "header":
                    raise CaptureFormatError(f"{path}: first record is not a header")
                if record.get("version") != CAPTURE_VERSION:
                    raise CaptureFormatError(
                        f"{path}: capture version {record.get('version')!r} "
                        f"(want {CAPTURE_VERSION})"
                    )
                header = record
            elif record.get("record") == "event":
                events.append(record)
    if header is None:
        raise CaptureFormatError(f"{path}: no header record")
    return Capture(header, events, path=path, truncated=truncated)
