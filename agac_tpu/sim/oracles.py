"""Invariant oracles for simulated scenarios (ISSUE 7).

Each oracle returns a list of violation strings (empty = clean), so a
scenario can accumulate every broken invariant instead of dying on
the first.  The final-state oracles assume the scenario has reached
quiescence (faults cleared, queues drained); the continuous oracles
(`gc_deletion_oracle`) hook into the harness mid-run, at the moment a
deletion decision lands.

The invariant list (the ISSUE's acceptance contract):

- **no orphan deletion of live owners** — whenever the GC sweeper
  deletes an accelerator or record owner, the Kubernetes owner object
  did not exist at that instant (checked against the cluster, the
  authority, synchronously inside the sweep event);
- **ownership-TXT/record atomicity** — at quiescence, every managed
  A-alias record has its owner TXT twin and vice versa: a half pair
  means a crash/batch path split the atomic submission;
- **pending-settle table drains** — nothing stays parked at
  quiescence, and nothing expired without resolution during a healthy
  (fault-free) run;
- **circuit-open call budget** — while a service's circuit is open,
  wire traffic to it is bounded by the half-open probe budget (no
  retry storms into a brownout);
- **eventual convergence to spec** — AWS state is exactly the image
  of the final cluster state: one complete chain per managed object
  with correct ownership, records matching surviving annotations,
  nothing for deleted/unmanaged objects.
"""

from __future__ import annotations

from .. import apis
from ..cloudprovider.aws.driver import parse_route53_owner_value
from ..controllers.globalaccelerator import is_managed_ingress, is_managed_service
from ..observability import explain as explain_plane

OWNER_TAG = "aws-global-accelerator-owner"
RR_TYPE_A = "A"
RR_TYPE_TXT = "TXT"


# ---------------------------------------------------------------------------
# expected state, derived from the cluster (the spec)
# ---------------------------------------------------------------------------


def expected_owners(cluster) -> set[str]:
    """Owner-tag values that SHOULD have an accelerator chain."""
    owners: set[str] = set()
    services, _ = cluster.list("Service")
    for svc in services:
        if is_managed_service(svc) and svc.status.load_balancer.ingress:
            owners.add(
                f"service/{svc.metadata.namespace}/{svc.metadata.name}"
            )
    ingresses, _ = cluster.list("Ingress")
    for ing in ingresses:
        if is_managed_ingress(ing) and ing.status.load_balancer.ingress:
            owners.add(
                f"ingress/{ing.metadata.namespace}/{ing.metadata.name}"
            )
    return owners


def expected_records(cluster) -> set[tuple[str, str]]:
    """(record name, type) pairs that SHOULD exist across zones."""
    records: set[tuple[str, str]] = set()
    for kind in ("Service", "Ingress"):
        objs, _ = cluster.list(kind)
        for obj in objs:
            hostnames = obj.metadata.annotations.get(
                apis.ROUTE53_HOSTNAME_ANNOTATION, ""
            )
            if not hostnames or not obj.status.load_balancer.ingress:
                continue
            if kind == "Service" and not is_managed_service(obj):
                # route53 records require the accelerator to exist;
                # an unmanaged service keeps no records
                continue
            for hostname in filter(None, hostnames.split(",")):
                records.add((hostname + ".", RR_TYPE_A))
                records.add((hostname + ".", RR_TYPE_TXT))
    return records


# ---------------------------------------------------------------------------
# final-state oracles
# ---------------------------------------------------------------------------


def check_convergence(harness) -> list[str]:
    """AWS state == image of the cluster spec (complete chains, exact
    owner set, exact record set)."""
    violations = []
    want_owners = expected_owners(harness.cluster)
    have_owners = {
        owner
        for owner in harness.aws.accelerator_owners().values()
        if owner is not None
    }
    missing = want_owners - have_owners
    extra = have_owners - want_owners
    if missing:
        violations.append(f"convergence: accelerators missing for {sorted(missing)}")
    if extra:
        violations.append(f"convergence: orphan accelerators for {sorted(extra)}")
    accelerators, listeners, endpoint_groups = harness.aws.chain_counts()
    if not (accelerators == listeners == endpoint_groups == len(want_owners)):
        violations.append(
            "convergence: incomplete chains "
            f"(accelerators={accelerators}, listeners={listeners}, "
            f"endpoint_groups={endpoint_groups}, want={len(want_owners)})"
        )
    want_records = expected_records(harness.cluster)
    have_records = {
        (record.name, record.type)
        for zone_id in harness.aws.all_hosted_zone_ids()
        for record in harness.aws.records_in_zone(zone_id)
        if record.type in (RR_TYPE_A, RR_TYPE_TXT)
    }
    if want_records != have_records:
        violations.append(
            f"convergence: records mismatch (missing "
            f"{sorted(want_records - have_records)}, extra "
            f"{sorted(have_records - want_records)})"
        )
    return violations


def check_record_atomicity(harness, cluster_name: str = "default") -> list[str]:
    """Every owner TXT has its A twin and vice versa — the atomic
    TXT+A submission was never split by any fault path."""
    violations = []
    for zone_id in harness.aws.all_hosted_zone_ids():
        records = harness.aws.records_in_zone(zone_id)
        a_names = {r.name for r in records if r.type == RR_TYPE_A}
        txt_names = set()
        for record in records:
            if record.type != RR_TYPE_TXT:
                continue
            values = [rr.value for rr in (record.resource_records or [])]
            if any(
                parse_route53_owner_value(v, cluster_name) is not None
                for v in values
            ):
                txt_names.add(record.name)
        for name in sorted(a_names - txt_names):
            violations.append(
                f"atomicity: A record {name!r} in {zone_id} has no owner TXT"
            )
        for name in sorted(txt_names - a_names):
            violations.append(
                f"atomicity: owner TXT {name!r} in {zone_id} has no A record"
            )
    return violations


def check_settle_drained(harness) -> list[str]:
    """Nothing parked at quiescence, across every live process-world
    (one settle table per sharded replica)."""
    violations = []
    for table in harness.settle_tables():
        depth = table.depth()
        if depth:
            violations.append(
                "pending-settle: "
                f"{depth} entries still parked at quiescence "
                f"({table.depth_by_group()})"
            )
    return violations


def check_no_residue(harness) -> list[str]:
    """Every workqueue of every live stack fully drained (ready AND
    delayed)."""
    violations = []
    for stack in harness.live_stacks():
        for entry in stack.workers:
            if len(entry.queue):
                violations.append(
                    f"residue: {stack.identity}/{entry.name} has ready items"
                )
            if entry.queue.next_delay_deadline() is not None:
                violations.append(
                    f"residue: {stack.identity}/{entry.name} has delayed items parked"
                )
    return violations


def check_exclusive_shard_ownership(harness) -> list[str]:
    """The no-key-owned-by-two-shards oracle (ISSUE 8), final-state
    form: live replicas' owned-shard sets are pairwise disjoint AND
    every violation the continuous per-tick check accumulated is
    surfaced.  (Key exclusivity follows: the ring is deterministic and
    shared, so disjoint shard sets ⇒ disjoint key sets.)"""
    violations = [
        v for v in harness.violations if v.startswith("exclusive-ownership")
    ]
    ownership = sorted(harness.shard_ownership().items())
    for i, (id_a, owned_a) in enumerate(ownership):
        for id_b, owned_b in ownership[i + 1:]:
            overlap = owned_a & owned_b
            if overlap:
                entry = (
                    f"exclusive-ownership: shards {sorted(overlap)} owned by "
                    f"BOTH {id_a} and {id_b} at quiescence"
                )
                if entry not in violations:
                    violations.append(entry)
    return violations


def check_resize_handoffs(harness) -> list[str]:
    """The live-resize handoff oracle (ISSUE 10): every moving key's
    unowned window stayed within the handoff budget while both sides
    of its handoff were alive, no transition is still in flight at
    quiescence, and every live replica settled on the same ring."""
    violations = list(getattr(harness, "handoff_violations", ()))
    states = harness.resize_states()
    for identity, status in sorted(states.items()):
        if status["state"] != "stable" or status["handoff_pending"]:
            violations.append(
                f"resize: {identity} still {status['state']} "
                f"({status['handoff_pending']} handoffs pending) at quiescence"
            )
    rings = {status["ring"] for status in states.values()}
    if len(rings) > 1:
        violations.append(
            f"resize: live replicas disagree on the ring at quiescence: "
            f"{sorted(rings)}"
        )
    return violations


def check_slo(harness) -> list[str]:
    """The convergence-SLO oracle (ISSUE 9): every declared objective's
    CUMULATIVE good fraction over the whole scenario meets its target.
    NOT part of ``standard_oracles`` — fault-injected scenarios
    legitimately blow convergence tails (that is what the budget is
    for), so callers arm this only for fault-free runs, for soaks
    whose faults the objectives are expected to absorb, and for the
    ``slo-brownout`` canary that proves the oracle can catch."""
    engine = getattr(harness, "slo_engine", None)
    if engine is None:
        return ["slo: harness has no SLO engine (slo_eval_interval 0?)"]
    return engine.violations()


def arm_explain_probes(harness, times, context=None) -> None:
    """Schedule the explain oracle's checkpoints (ISSUE 15): at each
    virtual time in ``times`` (fuzzed by the scenario), every managed
    object's fleet-merged ``/debug/explain`` verdict is checked against
    ground truth the probe derives independently — AWS state for
    convergence, the settle tables for parks, the shard filters for
    ownership.  Violations accumulate on the harness; ``check_explain``
    surfaces them at the end.

    ``context`` keys: ``outage`` — a ``(start, end)`` virtual-time
    window during which unconverged objects must classify to a
    brownout-shaped verdict; ``sharded`` — arm the per-replica
    ownership-consistency check."""
    context = dict(context or {})
    if not hasattr(harness, "explain_violations"):
        harness.explain_violations = []
        harness.explain_probes = 0

    def probe():
        harness.explain_probes += 1
        harness.explain_violations.extend(
            _explain_ground_truth_violations(harness, context)
        )

    for t in times:
        harness.after(max(0.0, float(t)), probe, name="explain-probe")


def check_explain(harness) -> list[str]:
    """The explain-plane oracle's final gate: every probe's violations,
    plus a guard that the armed probes actually fired (a scenario that
    quiesces before its checkpoints proves nothing)."""
    violations = list(getattr(harness, "explain_violations", []))
    if not getattr(harness, "explain_probes", 0):
        violations.append(
            "explain: probes were armed but none fired before the "
            "scenario ended"
        )
    return violations


# the brownout-shaped verdicts: what an unconverged object may look
# like while the backend is dark (everything except ownership /
# informer / terminal answers)
_BROWNOUT_VERDICTS = frozenset({
    explain_plane.VERDICT_CIRCUIT_OPEN,
    explain_plane.VERDICT_PARKED_SETTLE,
    explain_plane.VERDICT_QUOTA_PACED,
    explain_plane.VERDICT_BACKOFF,
    explain_plane.VERDICT_IN_FLIGHT,
    explain_plane.VERDICT_SHED,
})
# a parked key may simultaneously be circuit-blocked under another
# controller; most-blocking ranks those above parked-settle
_PARKED_OK_VERDICTS = frozenset({
    explain_plane.VERDICT_PARKED_SETTLE,
    explain_plane.VERDICT_CIRCUIT_OPEN,
    explain_plane.VERDICT_QUOTA_PACED,
})
_OWNERSHIP_VERDICTS = frozenset({
    explain_plane.VERDICT_NOT_OWNER,
    explain_plane.VERDICT_UNOWNED_RESIZE,
})


def _explain_ground_truth_violations(harness, context) -> list[str]:
    """One checkpoint's worth of explain-vs-ground-truth comparison."""
    violations: list[str] = []
    stacks = [
        stack
        for stack in harness.live_stacks()
        if getattr(stack.manager, "explain_engine", None) is not None
    ]
    if not stacks:
        return violations
    now = harness.scheduler.monotonic()
    stamp = f"t={now:.0f}"
    blocked = frozenset(explain_plane.BLOCKED_VERDICTS)

    def fleet_explain(key: str) -> tuple[dict, dict]:
        answers = {}
        for stack in stacks:
            try:
                answers[stack.identity] = stack.manager.explain_engine.explain(key)
            except Exception as err:  # an explain crash is itself a finding
                answers[stack.identity] = {"error": str(err)}
        return explain_plane.merge_fleet_explains(answers), answers

    # ground truth #1: AWS state — a managed object whose accelerator
    # chain is absent is unconverged, whatever the classifier claims
    want = expected_owners(harness.cluster)
    have = {
        owner
        for owner in harness.aws.accelerator_owners().values()
        if owner is not None
    }
    outage = context.get("outage")
    in_outage = bool(outage) and outage[0] <= now <= outage[1]
    brownout_evidence: list[str] = []
    for owner in sorted(want):
        _, namespace, name = owner.split("/", 2)
        key = f"{namespace}/{name}"
        merged, answers = fleet_explain(key)
        verdict = merged["verdict"]
        if verdict not in explain_plane.VERDICTS:
            violations.append(
                f"explain: {stamp} {key}: verdict {verdict!r} is outside "
                "the closed catalog"
            )
            continue
        unconverged = owner not in have
        if unconverged and verdict not in blocked:
            violations.append(
                f"explain: {stamp} {key} has no accelerator chain yet the "
                f"fleet-merged verdict is {verdict!r} — the classifier is "
                "vouching for convergence that has not happened"
            )
        if unconverged and in_outage and verdict not in _BROWNOUT_VERDICTS:
            violations.append(
                f"explain: {stamp} {key} is unconverged mid-brownout but "
                f"classifies {verdict!r}, not a brownout-shaped verdict "
                f"{sorted(_BROWNOUT_VERDICTS)}"
            )
        if unconverged and in_outage:
            brownout_evidence.append(verdict)
        # ground truth #3: per-replica ownership — a replica whose
        # shard filter disclaims the key must answer not-owner /
        # unowned-resize, and an owner must never disclaim it
        if context.get("sharded"):
            for stack in stacks:
                answer = answers.get(stack.identity)
                if not isinstance(answer, dict) or "error" in answer:
                    violations.append(
                        f"explain: {stamp} {key}: replica {stack.identity} "
                        f"failed to answer: {answer.get('error') if isinstance(answer, dict) else answer}"
                    )
                    continue
                shard_filter = stack.manager.shard_filter
                if shard_filter is None:
                    continue
                owned = shard_filter.owns_key(key)
                replica_verdict = answer.get("verdict")
                if owned and replica_verdict in _OWNERSHIP_VERDICTS:
                    violations.append(
                        f"explain: {stamp} {key}: {stack.identity} owns the "
                        f"key but answered {replica_verdict!r}"
                    )
                elif not owned and replica_verdict not in _OWNERSHIP_VERDICTS:
                    violations.append(
                        f"explain: {stamp} {key}: {stack.identity} does not "
                        f"own the key but answered {replica_verdict!r} "
                        "instead of not-owner/unowned-resize"
                    )

    # mid-brownout with circuits actually open, SOMETHING unconverged
    # must pin the blame on the breaker (or a park) — an explain plane
    # that never says circuit-open during an outage is not explaining
    if (
        in_outage
        and brownout_evidence
        and harness.world.health.open_services()
        and not any(
            v in (explain_plane.VERDICT_CIRCUIT_OPEN,
                  explain_plane.VERDICT_PARKED_SETTLE)
            for v in brownout_evidence
        )
    ):
        violations.append(
            f"explain: {stamp} circuits are open mid-brownout yet none of "
            f"{len(brownout_evidence)} unconverged objects classifies "
            f"circuit-open/parked-settle (saw {sorted(set(brownout_evidence))})"
        )

    # ground truth #2: the settle tables — a parked key IS parked
    for table in harness.settle_tables():
        for key in table.parked_keys():
            merged, _ = fleet_explain(key)
            if merged["verdict"] not in _PARKED_OK_VERDICTS:
                violations.append(
                    f"explain: {stamp} {key} is parked in the settle table "
                    f"but classifies {merged['verdict']!r}"
                )
    return violations


def check_autoscaler_oscillation(
    harness, max_flips: int = 2, window: float = 3600.0
) -> list[str]:
    """The no-oscillation oracle (ISSUE 13): EXECUTED scale decisions
    must not flip direction more than ``max_flips`` times within any
    sliding ``window`` of virtual seconds — a flapping autoscaler
    churns the keyspace through drain/handoff transitions for nothing
    and is strictly worse than no autoscaler.  A harness without an
    autoscaler is vacuously clean."""
    loop = getattr(harness, "autoscaler", None)
    if loop is None:
        return []
    executed = [d for d in loop.history() if d["executed"]]
    flips = [
        current["time"]
        for previous, current in zip(executed, executed[1:])
        if current["action"] != previous["action"]
    ]
    for i, start in enumerate(flips):
        in_window = [t for t in flips[i:] if t - start <= window]
        if len(in_window) > max_flips:
            return [
                f"autoscaler-oscillation: {len(in_window)} direction flips "
                f"within {window:g}s starting t={start:.0f} "
                f"(allowed {max_flips})"
            ]
    return []


def standard_oracles(harness, cluster_name: str = "default") -> list[str]:
    """The full final-state battery."""
    violations = (
        check_convergence(harness)
        + check_record_atomicity(harness, cluster_name)
        + check_settle_drained(harness)
        + check_no_residue(harness)
    )
    if getattr(harness, "_sharded", False):
        violations += check_exclusive_shard_ownership(harness)
        violations += check_resize_handoffs(harness)
    if getattr(harness, "autoscaler", None) is not None:
        violations += check_autoscaler_oscillation(harness)
    return violations


# ---------------------------------------------------------------------------
# continuous oracles
# ---------------------------------------------------------------------------


class GCDeletionOracle:
    """No orphan deletion of live owners: snapshots accelerator/record
    ownership before each sweep and verifies, for everything that
    vanished during the sweep, that the owner object was absent from
    the cluster at that moment.  Install via
    ``harness.on_gc_sweep = oracle.after_sweep`` plus a pre-sweep
    snapshot hook, or simply wrap ``attach(harness)``."""

    def __init__(self, cluster_name: str = "default"):
        self.cluster_name = cluster_name
        self.violations: list[str] = []
        self._harness = None

    def attach(self, harness) -> "GCDeletionOracle":
        self._harness = harness
        harness.on_gc_sweep_begin = self._before_sweep
        harness.on_gc_sweep = self._after_sweep
        self._before: dict = {}
        return self

    def _before_sweep(self, harness) -> None:
        # snapshot at the sweep boundary: deletions between sweeps are
        # the ordinary reconcile paths' business, not the sweeper's
        self._before["state"] = self._snapshot()

    def _snapshot(self):
        harness = self._harness
        owners = {
            owner
            for owner in harness.aws.accelerator_owners().values()
            if owner is not None
        }
        record_owners = set()
        for zone_id in harness.aws.all_hosted_zone_ids():
            for record in harness.aws.records_in_zone(zone_id):
                if record.type != RR_TYPE_TXT:
                    continue
                for rr in record.resource_records or []:
                    parsed = parse_route53_owner_value(rr.value, self.cluster_name)
                    if parsed is not None:
                        record_owners.add(parsed)
        return owners, record_owners

    def _owner_exists(self, resource: str, ns: str, name: str) -> bool:
        kind = "Service" if resource == "service" else "Ingress"
        try:
            self._harness.cluster.get(kind, ns, name)
            return True
        except Exception:
            return False

    def _after_sweep(self, harness, report: dict) -> None:
        owners_after, record_owners_after = self._snapshot()
        before_owners, before_record_owners = self._before.pop(
            "state", (owners_after, record_owners_after)
        )
        for owner in before_owners - owners_after:
            parts = owner.split("/")
            if len(parts) == 3 and self._owner_exists(*parts):
                self.violations.append(
                    f"gc: deleted accelerator for LIVE owner {owner!r} "
                    f"(sweep {report.get('sweep')})"
                )
        for owner in before_record_owners - record_owners_after:
            if self._owner_exists(*owner):
                self.violations.append(
                    f"gc: deleted records for LIVE owner {owner!r} "
                    f"(sweep {report.get('sweep')})"
                )

    def prime(self) -> None:
        """Take the initial snapshot (call once the world is built)."""
        self._before = {"state": self._snapshot()}


class CircuitBudgetOracle:
    """While a circuit is open, wire calls to the dead service must
    stay within the half-open probe budget — brownouts shed load
    instead of feeding retry storms.  Used by scenarios that schedule
    an outage window: call ``window_started``/``window_ended`` around
    it and the oracle bounds the calls made *after* the breaker
    opened."""

    def __init__(self, harness, service_ops: frozenset, label: str):
        self.harness = harness
        self.service_ops = {self._camel(op) for op in service_ops}
        self.label = label
        self.violations: list[str] = []
        self._open_observed_at_call_index = None

    @staticmethod
    def _camel(op: str) -> str:
        return "".join(part.capitalize() for part in op.split("_"))

    def _calls_to_service(self) -> int:
        return sum(
            1 for call in self.harness.aws.calls if call[0] in self.service_ops
        )

    def circuit_opened(self) -> None:
        self._open_observed_at_call_index = self._calls_to_service()

    def window_ended(self, open_duration: float, window: float, probe_budget: int):
        if self._open_observed_at_call_index is None:
            return  # breaker never opened — nothing to bound
        made = self._calls_to_service() - self._open_observed_at_call_index
        # one probe allowance per open_duration interval, plus slack
        # for the transition calls racing the trip
        allowed = probe_budget * (int(window / max(open_duration, 0.001)) + 2) + 5
        if made > allowed:
            self.violations.append(
                f"circuit-budget: {made} calls to {self.label} while its "
                f"circuit was open (allowed ~{allowed})"
            )
