"""SimClock + SimScheduler: the discrete-event core (ISSUE 7).

A single real thread owns the whole simulated world.  Virtual time is
a number that only moves two ways:

- the scheduler dispatches the earliest pending event and jumps the
  clock to its deadline;
- code running INSIDE an event calls ``sleep`` (through the clock
  seam), which advances the clock in place — the discrete-event
  equivalent of a busy thread holding its core.  No nested dispatch
  happens during a sleep; timers that come due while an event sleeps
  fire right after it returns, in deadline order.  This is what makes
  re-entrancy (and therefore deadlock) structurally impossible, and it
  is also exactly how a saturated single-core machine behaves.

Determinism: the ready queue is ordered by ``(deadline, priority,
sequence)`` — sequence is allocation order, so equal-deadline events
run in registration order, every run.  Every dispatch and sleep folds
into a rolling SHA-256 **event-trace hash**; two runs of the same
scenario from the same seed produce the same hash, which is the replay
contract the fuzzer's failure artifacts rely on.  (Within one process
this holds unconditionally; across processes set ``PYTHONHASHSEED``
so set-iteration order in application code cannot diverge.)

Recurring timers **coalesce**: a timer whose period was slept past
fires once and reschedules from *now*, rather than replaying every
missed tick — a 3-hour virtual sleep does not unleash 10,800 settle
polls.
"""

from __future__ import annotations

import contextlib
import hashlib
import heapq
from collections import deque
from typing import Callable, Generator, Iterator, Optional

from .. import clockseam

# epoch base for the virtual wall clock: an arbitrary fixed instant
# (2026-01-01T00:00:00Z) so persisted timestamps are stable run-to-run
SIM_EPOCH = 1767225600.0

# how many recent trace entries are kept readable for debugging and
# failure artifacts (the hash covers ALL entries regardless)
TRACE_TAIL = 4096


class SimClock:
    """The virtual clock, shaped like the seams the stack injects:
    ``monotonic`` / ``time`` / ``sleep``."""

    def __init__(self, scheduler: "SimScheduler"):
        self._scheduler = scheduler

    def monotonic(self) -> float:
        return self._scheduler.now

    def time(self) -> float:
        return SIM_EPOCH + self._scheduler.now

    def sleep(self, seconds: float) -> None:
        self._scheduler.consume(seconds)


class _Event:
    __slots__ = ("deadline", "priority", "seq", "name", "fn", "interval", "cancelled")

    def __init__(self, deadline, priority, seq, name, fn, interval):
        self.deadline = deadline
        self.priority = priority
        self.seq = seq
        self.name = name
        self.fn = fn
        self.interval = interval  # None = one-shot
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class SimScheduler:
    """Deterministic event heap + virtual clock + trace hash."""

    def __init__(self, start: float = 0.0):
        self.now = start
        self.clock = SimClock(self)
        self._heap: list[tuple[float, int, int, _Event]] = []
        self._seq = 0
        self._hash = hashlib.sha256()
        self.events_dispatched = 0
        self.slept_virtual = 0.0
        self.trace_tail: deque[str] = deque(maxlen=TRACE_TAIL)

    # ------------------------------------------------------------------
    # clock views
    # ------------------------------------------------------------------
    def monotonic(self) -> float:
        return self.now

    def time(self) -> float:
        return SIM_EPOCH + self.now

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------
    def _push(self, event: _Event) -> None:
        heapq.heappush(
            self._heap, (event.deadline, event.priority, event.seq, event)
        )

    def call_at(
        self, deadline: float, fn: Callable[[], None], name: str, priority: int = 0
    ) -> _Event:
        """One-shot event at an absolute virtual time (clamped to now)."""
        self._seq += 1
        event = _Event(max(deadline, self.now), priority, self._seq, name, fn, None)
        self._push(event)
        return event

    def call_after(
        self, delay: float, fn: Callable[[], None], name: str, priority: int = 0
    ) -> _Event:
        return self.call_at(self.now + max(delay, 0.0), fn, name, priority)

    def every(
        self,
        interval: float,
        fn: Callable[[], None],
        name: str,
        priority: int = 0,
        first_after: Optional[float] = None,
    ) -> _Event:
        """Recurring event; missed periods coalesce (see module doc)."""
        if interval <= 0:
            raise ValueError(f"recurring event {name!r} needs interval > 0")
        self._seq += 1
        first = self.now + (interval if first_after is None else max(first_after, 0.0))
        event = _Event(first, priority, self._seq, name, fn, interval)
        self._push(event)
        return event

    def spawn(self, gen: Generator[float, None, None], name: str) -> None:
        """Run a cooperative actor: a generator that yields the delay
        (virtual seconds) until its next step.  Each resume is an
        ordinary event, so actor steps interleave deterministically
        with timers and with each other."""

        def resume():
            try:
                delay = next(gen)
            except StopIteration:
                return
            self.call_after(float(delay), resume, name)

        self.call_after(0.0, resume, name)

    # ------------------------------------------------------------------
    # time advancement
    # ------------------------------------------------------------------
    def consume(self, seconds: float) -> None:
        """Advance virtual time in place — the sleep seam.  Called
        from inside a dispatched event (or between events); never
        dispatches, so it cannot re-enter application code."""
        if seconds <= 0:
            return
        self.now += seconds
        self.slept_virtual += seconds
        self._record("sleep", f"{seconds:.6f}")

    def advance_to(self, deadline: float) -> None:
        """Idle the clock forward to ``deadline`` (no-op if past)."""
        if deadline > self.now:
            self.now = deadline

    def next_deadline(self) -> Optional[float]:
        """Earliest pending event's deadline, skipping cancelled."""
        while self._heap and self._heap[0][3].cancelled:
            heapq.heappop(self._heap)
        return self._heap[0][0] if self._heap else None

    def step(self) -> bool:
        """Dispatch exactly one event (the earliest); False when the
        heap is empty.  The clock jumps to the event's deadline; the
        event may consume further virtual time while running."""
        while self._heap:
            _, _, _, event = heapq.heappop(self._heap)
            if event.cancelled:
                continue
            if event.deadline > self.now:
                self.now = event.deadline
            self.events_dispatched += 1
            self._record("event", event.name)
            event.fn()
            if event.interval is not None and not event.cancelled:
                # coalescing reschedule: next tick measured from NOW
                # (which the handler may have advanced), never from the
                # original phase — missed periods collapse into one
                event.deadline = self.now + event.interval
                self._push(event)
            return True
        return False

    # ------------------------------------------------------------------
    # the event-trace hash (the replay contract)
    # ------------------------------------------------------------------
    def _record(self, kind: str, detail: str) -> None:
        line = f"{self.now:.6f}|{kind}|{detail}"
        self._hash.update(line.encode())
        self._hash.update(b"\n")
        self.trace_tail.append(line)

    def record(self, kind: str, detail: str) -> None:
        """Fold an application-level observation into the trace — the
        harness records every worker step and informer delta so the
        hash covers the full interleaving, not just timer firings."""
        self._record(kind, detail)

    def trace_hash(self) -> str:
        return self._hash.hexdigest()


@contextlib.contextmanager
def installed(scheduler: SimScheduler) -> Iterator[SimScheduler]:
    """Install the scheduler's clock into the process-wide clock seam
    (``agac_tpu/clockseam.py``) for the duration of the block.  With
    ``threads=False`` every thread-optional component (workqueue delay
    wakers, event-recorder persistence workers) constructed inside
    falls back to synchronous, scheduler-pumped operation."""
    clock = scheduler.clock
    clockseam.install(
        monotonic=clock.monotonic, wall=clock.time, sleep=clock.sleep, threads=False
    )
    try:
        yield scheduler
    finally:
        clockseam.reset()
