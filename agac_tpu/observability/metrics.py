"""Thread-safe metrics registry with Prometheus text exposition.

The stack had four subsystems each hoarding private counter dicts
(read-plane ``stats()``, health-plane ``_counters``, GC totals, bench
op counts) with no uniform export.  This module is the one place
counters live: ``Counter`` / ``Gauge`` / ``Histogram`` primitives
behind a ``MetricsRegistry`` that renders the Prometheus text format
(version 0.0.4) for the ``/metrics`` endpoint on the manager's health
server.

Design constraints, in order:

- **Stdlib only.**  CI and the bare container never pip-install a
  prometheus client; the text format is simple enough to emit
  directly.
- **Construction goes through the registry.**  ``registry.counter(...)``
  is get-or-create (same name → same metric; a type/label mismatch is
  a programming error and raises).  Direct ``Counter(...)``
  construction outside this module is flagged by the
  ``unregistered-metric`` lint rule — an unregistered metric is
  invisible to ``/metrics``, which is exactly the private-dict drift
  this subsystem deletes.
- **Bounded label cardinality.**  A metric accepts at most
  ``max_series`` distinct label sets; past the cap every new label set
  collapses into one ``overflow`` series (and the drop is counted), so
  a key or error-code explosion can never OOM the registry or melt the
  scrape.  Label *names* are fixed at registration; label *values* are
  strings.
- **Callback samples.**  A gauge child can carry a callable evaluated
  at collection time (``set_function``) so live state — circuit state,
  AIMD rate, queue depth of an object that already owns the number —
  is exposed as a view instead of a copied-and-drifting dict.

There is one process-global registry (``registry()``), the default for
the hot-path instruments; components that tests instantiate many times
per process (HealthTracker, GarbageCollector, Manager) take an
explicit ``registry`` parameter instead and default to a private one.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Iterable, Optional

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# Prometheus client_golang's default buckets: spans the 5 ms..10 s
# range a control-plane RPC or reconcile actually occupies.
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# distinct label sets per metric before collapsing into the overflow
# series; generous for legitimate label spaces (ops x outcomes,
# queues, services) while bounding a runaway (keys, raw error text)
DEFAULT_MAX_SERIES = 256

_OVERFLOW = "overflow"


def _escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _render_labels(names: tuple[str, ...], values: tuple[str, ...],
                   extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = list(zip(names, values)) + list(extra)
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
    return "{" + body + "}"


class _Child:
    """One labeled series.  Counters/gauges hold a float behind a
    lock; a gauge may instead hold a callback evaluated at collection
    time.  Histogram children hold bucket counts + sum + count."""

    __slots__ = ("_metric", "_values", "_value", "_sum", "_count", "_fn", "_lock")

    def __init__(self, metric: "Metric"):
        self._metric = metric
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None
        if metric.type == HISTOGRAM:
            self._values = [0] * len(metric.buckets)
            self._sum = 0.0
            self._count = 0

    # -- counter/gauge -------------------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        if self._metric.type == COUNTER and amount < 0:
            raise ValueError(f"{self._metric.name}: counters only go up")
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        if self._metric.type != GAUGE:
            raise ValueError(f"{self._metric.name}: only gauges can dec()")
        with self._lock:
            self._value -= amount

    def set(self, value: float) -> None:
        if self._metric.type != GAUGE:
            raise ValueError(f"{self._metric.name}: only gauges can set()")
        with self._lock:
            self._value = float(value)
            self._fn = None

    def set_function(self, fn: Callable[[], float]) -> None:
        """Expose live state as a collection-time view — the
        single-source-of-truth seam for circuit state, AIMD rate and
        queue depth (the owner keeps the number; the registry reads
        it, never copies it)."""
        if self._metric.type != GAUGE:
            raise ValueError(f"{self._metric.name}: only gauges take callbacks")
        with self._lock:
            self._fn = fn

    def value(self) -> float:
        with self._lock:
            fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return float("nan")
        with self._lock:
            return self._value

    # -- histogram -----------------------------------------------------
    def observe(self, value: float) -> None:
        if self._metric.type != HISTOGRAM:
            raise ValueError(f"{self._metric.name}: only histograms observe()")
        buckets = self._metric.buckets
        with self._lock:
            for i, bound in enumerate(buckets):
                if value <= bound:
                    self._values[i] += 1
            self._sum += value
            self._count += 1

    def histogram_snapshot(self) -> tuple[list[int], float, int]:
        """(per-bucket non-cumulative-free cumulative counts, sum,
        count) — buckets are already cumulative by construction."""
        with self._lock:
            return list(self._values), self._sum, self._count


class Metric:
    """One metric family: name + type + help + fixed label names, and
    the labeled children.  Never construct directly — go through
    ``MetricsRegistry`` (enforced by the unregistered-metric lint
    rule)."""

    def __init__(
        self,
        name: str,
        help: str,
        type: str,
        label_names: tuple[str, ...] = (),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
        max_series: int = DEFAULT_MAX_SERIES,
    ):
        self.name = name
        self.help = help
        self.type = type
        self.label_names = tuple(label_names)
        self.buckets = tuple(sorted(buckets)) if type == HISTOGRAM else ()
        self.max_series = max(1, max_series)
        self.dropped_series = 0  # label sets collapsed into overflow
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], _Child] = {}
        if not self.label_names:
            # an unlabeled metric IS its single child: metric.inc()
            self._children[()] = _Child(self)

    # unlabeled convenience: delegate to the () child
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def set_function(self, fn: Callable[[], float]) -> None:
        self.labels().set_function(fn)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def value(self) -> float:
        return self.labels().value()

    def labels(self, **labels: str) -> _Child:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got "
                f"{tuple(sorted(labels))}"
            )
        key = tuple(str(labels[name]) for name in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is not None:
                return child
            if len(self._children) >= self.max_series:
                # cardinality cap: collapse into ONE overflow series so
                # a label-value explosion is visible but bounded
                self.dropped_series += 1
                overflow_key = tuple(_OVERFLOW for _ in self.label_names)
                child = self._children.get(overflow_key)
                if child is None:
                    child = self._children[overflow_key] = _Child(self)
                return child
            child = self._children[key] = _Child(self)
            return child

    def samples(self) -> Iterable[tuple[str, str, float]]:
        """(name+labels, "", value) sample lines for exposition."""
        with self._lock:
            children = sorted(self._children.items())
        for values, child in children:
            if self.type == HISTOGRAM:
                counts, total, count = child.histogram_snapshot()
                for bound, bucket_count in zip(self.buckets, counts):
                    yield (
                        self.name + "_bucket",
                        _render_labels(
                            self.label_names, values, (("le", _format_value(bound)),)
                        ),
                        bucket_count,
                    )
                yield (
                    self.name + "_bucket",
                    _render_labels(self.label_names, values, (("le", "+Inf"),)),
                    count,
                )
                yield (self.name + "_sum", _render_labels(self.label_names, values), total)
                yield (self.name + "_count", _render_labels(self.label_names, values), count)
            else:
                yield (self.name, _render_labels(self.label_names, values), child.value())


# The constructor aliases the lint rule knows: all construction flows
# through MetricsRegistry below, so these exist for isinstance checks
# and the rule's vocabulary, not for direct use.
Counter = Metric
Gauge = Metric
Histogram = Metric


class MetricsRegistry:
    """Get-or-create registry + text exposition.

    ``counter``/``gauge``/``histogram`` return the existing metric when
    the name is already registered (type/labels must match — a mismatch
    is a bug, not a merge).  ``render()`` produces the Prometheus text
    format the ``/metrics`` endpoint serves; ``describe()`` feeds the
    generated metric catalog in docs/operations.md."""

    def __init__(self, max_series: int = DEFAULT_MAX_SERIES):
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}
        self._max_series = max_series

    def _get_or_create(
        self,
        name: str,
        help: str,
        type: str,
        labels: tuple[str, ...],
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Metric:
        labels = tuple(labels)
        with self._lock:
            metric = self._metrics.get(name)
            if metric is not None:
                if metric.type != type or metric.label_names != labels:
                    raise ValueError(
                        f"metric {name!r} already registered as {metric.type}"
                        f"{metric.label_names}, requested {type}{labels}"
                    )
                return metric
            metric = self._metrics[name] = Metric(
                name, help, type, labels, buckets=buckets, max_series=self._max_series
            )
            return metric

    def counter(self, name: str, help: str, labels: tuple[str, ...] = ()) -> Metric:
        return self._get_or_create(name, help, COUNTER, labels)

    def gauge(self, name: str, help: str, labels: tuple[str, ...] = ()) -> Metric:
        return self._get_or_create(name, help, GAUGE, labels)

    def histogram(
        self,
        name: str,
        help: str,
        labels: tuple[str, ...] = (),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Metric:
        return self._get_or_create(name, help, HISTOGRAM, labels, buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def describe(self) -> list[dict]:
        """[{name, type, labels, help}] sorted by name — the metric
        catalog's source (``observability.catalog``)."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        return [
            {
                "name": m.name,
                "type": m.type,
                "labels": list(m.label_names),
                "help": m.help,
            }
            for m in metrics
        ]

    def render(self) -> str:
        """The Prometheus text exposition format (0.0.4): HELP + TYPE
        headers per family, then one line per sample, deterministic
        order (sorted families, sorted label values)."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: list[str] = []
        for metric in metrics:
            lines.append(f"# HELP {metric.name} {_escape_help(metric.help)}")
            lines.append(f"# TYPE {metric.name} {metric.type}")
            for sample_name, label_str, value in metric.samples():
                lines.append(f"{sample_name}{label_str} {_format_value(value)}")
        return "\n".join(lines) + "\n"


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# ---------------------------------------------------------------------------
# the process-global registry (the analog of controller-runtime's
# metrics.Registry): hot-path instruments default to it; tests that
# need isolation build their own MetricsRegistry
# ---------------------------------------------------------------------------

_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _registry


def parse_text(text: str) -> dict[str, float]:
    """Parse a text-format exposition into {sample_with_labels: value}
    — the helper the bench's per-phase scrape and the e2e scrape tests
    share (strict enough to catch a malformed render, not a full
    OpenMetrics parser)."""
    samples: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        if not name:
            raise ValueError(f"unparseable sample line: {line!r}")
        samples[name] = float(value)
    return samples
