"""Sampling wall/stack profiler (ISSUE 14).

The stage accountant (``profile.py``) answers "which stage costs CPU";
this module answers "which *code* is on-CPU (or parked) right now" —
a stdlib-only sampling profiler that walks every thread's frame via
``sys._current_frames()`` at a configurable hz and aggregates the
walks into folded-stack form (the ``root;caller;leaf count`` lines
flamegraph tooling eats) plus a ranked top-N function table
(self/cumulative sample counts).

Three consumers:

- ``/debug/profile?seconds=N`` (manager health server) runs a fresh
  timed capture in the handler thread and serves the folded text or a
  JSON top table — the on-demand "what is this replica doing" drill.
- The continuous sampler (``run`` on a daemon thread, armed by
  ``--profile-hz`` and gated on ``clockseam.threads_enabled()`` — the
  sim's cooperative executor must never see a wild thread) keeps a
  rolling aggregate whose top table the SIGTERM handler dumps into the
  log next to the FlightRecorder tail: a terminating pod's last
  artifact says where it was spending its time.
- Tests feed a synthetic ``frames_fn`` so folded-stack aggregation and
  top-N ranking are exercised deterministically with zero real
  threads.

The sampler thread's own frame is excluded from every walk.
"""

from __future__ import annotations

import sys
import threading
from typing import Callable, Dict, Optional

from .. import clockseam, klog
from ..analysis import racecheck

DEFAULT_HZ = 97.0  # prime-ish: avoids phase-locking with 10ms tickers
MAX_STACK_DEPTH = 64
TOP_DEFAULT = 20


def _frame_label(frame) -> str:
    code = frame.f_code
    return f"{code.co_name} ({code.co_filename}:{frame.f_lineno})"


class FoldedStacks:
    """Aggregated samples: {(root, ..., leaf): count} plus per-frame
    self/cumulative tallies.  Thread-safe (the continuous sampler
    writes while the endpoint reads)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[tuple, int] = {}
        self.samples = 0

    def add_frame(self, frame, max_depth: int = MAX_STACK_DEPTH) -> None:
        stack = []
        while frame is not None and len(stack) < max_depth:
            stack.append(_frame_label(frame))
            frame = frame.f_back
        if not stack:
            return
        key = tuple(reversed(stack))  # root first, leaf last
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            self.samples += 1

    def merge(self, other: "FoldedStacks") -> None:
        with other._lock:
            items = list(other._counts.items())
        with self._lock:
            for key, count in items:
                self._counts[key] = self._counts.get(key, 0) + count
                self.samples += count

    def folded(self) -> str:
        """One ``root;caller;leaf count`` line per distinct stack,
        deterministic order (count desc, then stack lexicographic)."""
        with self._lock:
            items = list(self._counts.items())
        items.sort(key=lambda kv: (-kv[1], kv[0]))
        return "\n".join(f"{';'.join(key)} {count}" for key, count in items)

    def top(self, n: int = TOP_DEFAULT) -> list[dict]:
        """Ranked per-function table: ``self`` = samples with the
        function on top of a stack, ``cum`` = samples with it anywhere
        (counted once per stack).  Deterministic: self desc, cum desc,
        then name."""
        self_counts: Dict[str, int] = {}
        cum_counts: Dict[str, int] = {}
        with self._lock:
            items = list(self._counts.items())
            total = self.samples
        for key, count in items:
            leaf = key[-1]
            self_counts[leaf] = self_counts.get(leaf, 0) + count
            for func in set(key):
                cum_counts[func] = cum_counts.get(func, 0) + count
        rows = [
            {
                "func": func,
                "self": self_counts.get(func, 0),
                "cum": cum,
                "self_pct": round(100.0 * self_counts.get(func, 0) / total, 2)
                if total
                else 0.0,
            }
            for func, cum in cum_counts.items()
        ]
        rows.sort(key=lambda r: (-r["self"], -r["cum"], r["func"]))
        return rows[:n]

    def clear(self) -> None:
        with self._lock:
            self._counts.clear()
            self.samples = 0


class StackProfiler:
    """The sampling loop.  ``frames_fn`` defaults to
    ``sys._current_frames``; tests inject a synthetic feed.  ``clock``
    and ``sleep`` ride the process seam so a capture's pacing is
    injectable too."""

    def __init__(
        self,
        hz: float = DEFAULT_HZ,
        frames_fn: Optional[Callable[[], dict]] = None,
        clock: Callable[[], float] = clockseam.monotonic,
        sleep: Callable[[float], None] = clockseam.sleep,
        max_depth: int = MAX_STACK_DEPTH,
    ):
        # guards hz and the continuous-sampler thread handle: the
        # process-global profiler is shared (configure() from cmd/root,
        # start() from the manager, capture() from health handlers), so
        # its mutable fields take the racecheck-visible lock — the
        # shared-state census classifies `_profiler` off this site
        self._mu = racecheck.make_lock("stackprof")
        self.hz = max(1.0, float(hz))
        self._frames_fn = frames_fn or sys._current_frames
        self._clock = clock
        self._sleep = sleep
        self._max_depth = max_depth
        # the rolling aggregate the continuous sampler feeds and the
        # SIGTERM dump reads
        self.aggregate = FoldedStacks()
        self._thread: Optional[threading.Thread] = None

    def sample_once(self, into: FoldedStacks, skip_threads: frozenset = frozenset()) -> None:
        for thread_id, frame in list(self._frames_fn().items()):
            if thread_id in skip_threads:
                continue
            into.add_frame(frame, self._max_depth)

    def capture(self, seconds: float, hz: Optional[float] = None) -> dict:
        """A fresh timed capture (blocking the calling thread — the
        /debug/profile handler runs on its own connection thread, so
        blocking there is free).  Returns the JSON-ready dict the
        endpoint serves."""
        rate = max(1.0, float(hz or self.hz))
        seconds = max(0.0, min(float(seconds), 60.0))
        stacks = FoldedStacks()
        skip = frozenset({threading.get_ident()})
        deadline = self._clock() + seconds
        interval = 1.0 / rate
        while True:
            self.sample_once(stacks, skip_threads=skip)
            if self._clock() >= deadline:
                break
            self._sleep(interval)
        return {
            "hz": rate,
            "seconds": seconds,
            "samples": stacks.samples,
            "folded": stacks.folded(),
            "top": stacks.top(),
        }

    # -- continuous mode ------------------------------------------------
    def run(self, stop: threading.Event) -> None:
        """The continuous sampling loop body (daemon thread target)."""
        skip = frozenset({threading.get_ident()})
        interval = 1.0 / self.hz
        while not stop.is_set():
            try:
                self.sample_once(self.aggregate, skip_threads=skip)
            except Exception:  # sampling must never kill the thread
                pass
            stop.wait(interval)

    def start(self, stop: threading.Event) -> Optional[threading.Thread]:
        """Start the continuous sampler — only when the runtime allows
        threads (the sim's cooperative executor must own every
        interleaving decision, so under it this is a refusal, not a
        fallback)."""
        if not clockseam.threads_enabled():
            return None
        with self._mu:
            existing = self._thread
            # ident is None while created-but-unstarted: a concurrent
            # starter must piggyback on it, not double-spawn
            if existing is not None and (
                existing.ident is None or existing.is_alive()
            ):
                return existing
            thread = threading.Thread(
                target=self.run, args=(stop,), daemon=True, name="stack-profiler"
            )
            self._thread = thread
        # started outside the lock: .start() never runs under _mu, so
        # the profiler's lock stays a leaf in the static lock order
        thread.start()
        return thread

    def set_rate(self, hz: float) -> None:
        with self._mu:
            self.hz = max(1.0, float(hz))

    def log_top(self, n: int = 10) -> None:
        """Dump the continuous aggregate's top table via klog — the
        SIGTERM post-mortem companion to the FlightRecorder tail."""
        rows = self.aggregate.top(n)
        if not rows:
            return
        klog.infof(
            "stack profiler top (of %d samples):", self.aggregate.samples
        )
        for row in rows:
            klog.infof(
                "  %5.1f%% self=%d cum=%d %s",
                row["self_pct"], row["self"], row["cum"], row["func"],
            )


# ---------------------------------------------------------------------------
# the process-global profiler, configured by --profile-hz (cmd/root)
# ---------------------------------------------------------------------------

_profiler = StackProfiler()


def profiler() -> StackProfiler:
    return _profiler


def configure(hz: Optional[float] = None) -> None:
    if hz is not None and hz > 0:
        _profiler.set_rate(hz)


def capture(seconds: float, hz: Optional[float] = None) -> dict:
    """Module-level capture off the global profiler (the default
    ``/debug/profile`` hook)."""
    return _profiler.capture(seconds, hz=hz)
