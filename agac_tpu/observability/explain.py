"""The convergence explain plane: per-object blocked-on diagnosis.

The stack can measure *how slow* (journey histograms, ISSUE 9), *how
burned* (SLO windows), and *where the CPU goes* (stage accountant,
ISSUE 14) — but none of it answers the operator's actual question:
**why is ``ns/name`` not converged right now?**  Every plane holds one
shard of the answer and none of them talk:

- the JourneyTracker knows the object is in flight and what its last
  stage was;
- the PendingSettleTable knows it is parked on an AWS wait (group +
  deadline);
- the workqueue knows it is sitting in a backoff delay (count +
  next-eta);
- the HealthTracker knows the service circuit is open and when a probe
  will be admitted;
- the ShardFilter/ring knows this replica does not own the key — or
  that nobody does for a moment, mid-resize;
- the SLO engine knows deferrable work is being shed under burn.

``ExplainEngine`` assembles those into a single **blocked-on verdict**
per (controller, key) plus a causal timeline, fed by *structured
reason codes* attached at every requeue/park/skip site (the
``unexplained-requeue`` lint rule keeps the sites honest) rather than
inferred after the fact.  Every lookup is O(1) per key — dict gets
against live state, never a fleet enumeration (the unit tier
micro-asserts it).

The verdict vocabulary is a closed catalog; ``unknown`` is not in it.
A managed object always classifies to something actionable:

========================  ==================================================
verdict                   meaning
========================  ==================================================
``converged``             no journey in flight; the object matches AWS
``in-flight``             queued/processing, or waiting a scheduled re-check
``parked-settle``         parked on an AWS wait state (group + deadline)
``circuit-open``          requeued by an open service circuit (retry hint)
``quota-paced``           adaptive pacing pushed the call past its deadline
``backoff``               failing and retried with exponential backoff
``shed``                  deferrable work held back under SLO budget burn
``unowned-resize``        key is mid-handoff in a live resize (ring epoch)
``not-owner``             another replica's shards own the key
``informer-unsynced``     local caches have not completed their first sync
``not-managed``           the object exists but carries no managed marker
``deleted``               the object is gone from the cluster
========================  ==================================================

Surfaces: ``/debug/explain?key=ns/name[&controller=]`` (manager health
server), the ``explain`` CLI subcommand (fleet-wide over
``--fleet-peers``: the owning shard answers, non-owners report
``not-owner`` with their ring epoch), the
``agac_explain_blocked{reason}`` callback gauge (fleet-merged like
every gauge), and the SIGTERM post-mortem's top-blocked-on table.

One process-global engine (``engine()``/``install()``, the journey
tracker seam pattern); the manager wires the real one at build time
and the sim harness reads each replica's own.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from .. import clockseam, klog
from ..errors import NotFoundError
from . import instruments, journey, recorder

# ---------------------------------------------------------------------------
# the verdict catalog (closed vocabulary — never "unknown")
# ---------------------------------------------------------------------------

VERDICT_CONVERGED = "converged"
VERDICT_IN_FLIGHT = "in-flight"
VERDICT_PARKED_SETTLE = "parked-settle"
VERDICT_CIRCUIT_OPEN = "circuit-open"
VERDICT_QUOTA_PACED = "quota-paced"
VERDICT_BACKOFF = "backoff"
VERDICT_SHED = "shed"
VERDICT_UNOWNED_RESIZE = "unowned-resize"
VERDICT_NOT_OWNER = "not-owner"
VERDICT_INFORMER_UNSYNCED = "informer-unsynced"
VERDICT_NOT_MANAGED = "not-managed"
VERDICT_DELETED = "deleted"

VERDICTS = (
    VERDICT_CONVERGED,
    VERDICT_IN_FLIGHT,
    VERDICT_PARKED_SETTLE,
    VERDICT_CIRCUIT_OPEN,
    VERDICT_QUOTA_PACED,
    VERDICT_BACKOFF,
    VERDICT_SHED,
    VERDICT_UNOWNED_RESIZE,
    VERDICT_NOT_OWNER,
    VERDICT_INFORMER_UNSYNCED,
    VERDICT_NOT_MANAGED,
    VERDICT_DELETED,
)

# the reason codes a requeue/park/skip call site may literally assert
# (the subset of the catalog that is a *cause* a site can know, not a
# state the engine derives) — the unexplained-requeue lint rule keeps
# a literal copy and a sync test pins the two equal
REASON_CODES = frozenset({
    VERDICT_IN_FLIGHT,
    VERDICT_BACKOFF,
    VERDICT_CIRCUIT_OPEN,
    VERDICT_QUOTA_PACED,
    VERDICT_PARKED_SETTLE,
    VERDICT_SHED,
    VERDICT_NOT_OWNER,
})

# most-blocking first: the envelope's summary verdict and the fleet
# merge both pick the highest-priority verdict present.  ``converged``
# outranks the terminal non-answers: an object one controller manages
# and has converged, while another controller's predicate rejects it
# (a service without a hostname annotation, say), IS converged.
_PRIORITY = (
    VERDICT_CIRCUIT_OPEN,
    VERDICT_QUOTA_PACED,
    VERDICT_PARKED_SETTLE,
    VERDICT_SHED,
    VERDICT_BACKOFF,
    VERDICT_UNOWNED_RESIZE,
    VERDICT_INFORMER_UNSYNCED,
    VERDICT_IN_FLIGHT,
    VERDICT_NOT_OWNER,
    VERDICT_CONVERGED,
    VERDICT_NOT_MANAGED,
    VERDICT_DELETED,
)

# the non-terminal verdicts the agac_explain_blocked gauge exports a
# series for (terminal states never appear in a blocked histogram)
BLOCKED_VERDICTS = (
    VERDICT_IN_FLIGHT,
    VERDICT_PARKED_SETTLE,
    VERDICT_CIRCUIT_OPEN,
    VERDICT_QUOTA_PACED,
    VERDICT_BACKOFF,
    VERDICT_SHED,
    VERDICT_UNOWNED_RESIZE,
    VERDICT_NOT_OWNER,
    VERDICT_INFORMER_UNSYNCED,
)

# blocked_counts() classifies every in-flight journey — O(unconverged),
# fine at scrape cadence but not per label collection (the gauge has
# one series per blocked verdict), so one sweep is cached briefly
BLOCKED_CACHE_TTL = 1.0


def most_blocking(verdicts) -> str:
    """The highest-priority verdict present (``converged`` when the
    iterable is empty) — the envelope summary and the fleet merge."""
    present = set(verdicts)
    for verdict in _PRIORITY:
        if verdict in present:
            return verdict
    return VERDICT_CONVERGED


class _Worker:
    """One registered reconcile queue: the per-controller hooks a
    classification consults, all O(1) per key."""

    __slots__ = ("controller", "queue", "key_to_obj", "managed")

    def __init__(self, controller, queue, key_to_obj, managed=None):
        self.controller = controller
        self.queue = queue
        self.key_to_obj = key_to_obj
        self.managed = managed


def _resolve(value):
    """Wired planes may be live objects or late-bound callables (the
    manager wires its settle table after build)."""
    return value() if callable(value) else value


class ExplainEngine:
    """Assembles one blocked-on verdict + causal timeline per
    (controller, key) from the planes wired in.  Every input is
    optional: an unwired plane simply cannot contribute its verdicts,
    it never makes classification fail."""

    def __init__(
        self,
        journeys: Optional["journey.JourneyTracker"] = None,
        clock: Optional[Callable[[], float]] = None,
        identity: str = "",
        settle_table=None,
        health=None,
        shard_filter=None,
        resize_status: Optional[Callable[[], dict]] = None,
        informers_synced: Optional[Callable[[], bool]] = None,
        slo_shedding: Optional[Callable[[], bool]] = None,
        flight_recorder=None,
    ):
        # None = the process-global tracker at query time (it may be
        # install()ed after this engine is built — sim/bench isolation)
        self._journeys = journeys
        self._clock = clock or clockseam.monotonic
        self.identity = identity
        self._settle_table = settle_table
        self._health = health
        self._shard_filter = shard_filter
        self._resize_status = resize_status
        self._informers_synced = informers_synced
        self._slo_shedding = slo_shedding
        self._recorder = flight_recorder
        self._workers: dict[str, _Worker] = {}
        self._lock = threading.Lock()
        self._counts_cache: tuple[Optional[float], dict] = (None, {})
        self._metrics = None

    # ------------------------------------------------------------------
    # wiring
    # ------------------------------------------------------------------
    def register_worker(self, controller, queue, key_to_obj, managed=None) -> None:
        """Register one reconcile queue under its worker label (the
        same ``spec["name"]`` the journey plane keys on)."""
        with self._lock:
            self._workers[controller] = _Worker(controller, queue, key_to_obj, managed)

    def controllers(self) -> list[str]:
        with self._lock:
            return sorted(self._workers)

    def bind_metrics(self, registry=None) -> None:
        """Bind the ``agac_explain_blocked{reason}`` callback gauge:
        one series per blocked verdict, each reading the cached
        blocked-count sweep (so a scrape costs one O(unconverged)
        classification pass, not one per series)."""
        self._metrics = instruments.explain_instruments(registry)
        for verdict in BLOCKED_VERDICTS:
            self._metrics.blocked.labels(reason=verdict).set_function(
                lambda v=verdict: self.blocked_counts().get(v, 0)
            )

    def _count_query(self, surface: str) -> None:
        if self._metrics is not None:
            self._metrics.queries.labels(surface=surface).inc()

    def _journey_tracker(self):
        return self._journeys if self._journeys is not None else journey.tracker()

    def _flight_recorder(self):
        resolved = _resolve(self._recorder)
        return resolved if resolved is not None else recorder.flight_recorder()

    def ring_epoch(self) -> int:
        """The live resize epoch (0 when sharding/resize is not wired)
        — stamped into flight-recorder reconcile entries so a recorded
        outcome is attributable to the ring it ran under."""
        if self._resize_status is None:
            return 0
        try:
            return int((self._resize_status() or {}).get("epoch", 0))
        except Exception:
            return 0

    # ------------------------------------------------------------------
    # classification
    # ------------------------------------------------------------------
    def classify(self, controller: str, key: str) -> dict:
        """One (controller, key)'s verdict + detail + timeline.  Every
        consult is a per-key lookup (dict get / heap-index get) — never
        a fleet enumeration."""
        worker = self._workers.get(controller)
        detail: dict = {}
        verdict = self._verdict(controller, key, worker, detail)
        return {
            "controller": controller,
            "key": key,
            "verdict": verdict,
            "detail": detail,
            "timeline": self._timeline(controller, key),
        }

    def _verdict(self, controller, key, worker, detail) -> str:
        # 1. ownership: a key this replica's shards do not cover is
        # another replica's problem — report so, with the ring epoch,
        # and distinguish the transient mid-resize window
        shard_filter = _resolve(self._shard_filter)
        if shard_filter is not None and not shard_filter.all_shards:
            ownership = shard_filter.explain_key(key)
            if not ownership.get("owned", True):
                resize = {}
                if self._resize_status is not None:
                    try:
                        resize = self._resize_status() or {}
                    except Exception:
                        resize = {}
                detail.update(ownership)
                detail["ring_epoch"] = resize.get("epoch", 0)
                detail["resize_state"] = resize.get("state", "stable")
                if ownership.get("moving"):
                    # the drain/handoff window: the key left this
                    # replica (or has not been adopted yet) — the owner
                    # answer arrives once the transition's per-key
                    # protocol completes
                    return VERDICT_UNOWNED_RESIZE
                return VERDICT_NOT_OWNER

        # 2. a cache that never synced cannot answer object questions
        if self._informers_synced is not None and not self._informers_synced():
            detail["note"] = "informer caches have not completed their first sync"
            return VERDICT_INFORMER_UNSYNCED

        # 3. an in-flight journey: find WHERE the key currently waits
        journey_view = self._journey_tracker().view(controller, key)
        if journey_view is not None:
            detail["journey"] = journey_view
            return self._inflight_verdict(key, worker, detail)

        # 4. no journey: the object is terminal — converged, unmanaged,
        # or gone (single-key cache get, never a list)
        if worker is None:
            detail["note"] = f"no worker registered for controller {controller!r}"
            return VERDICT_NOT_MANAGED
        try:
            obj = worker.key_to_obj(key)
        except NotFoundError:
            detail["note"] = "object absent from the informer cache"
            return VERDICT_DELETED
        except Exception as err:
            detail["lookup_error"] = str(err)
            return VERDICT_NOT_MANAGED
        if worker.managed is not None and not worker.managed(obj):
            detail["note"] = "object exists but carries no managed marker"
            return VERDICT_NOT_MANAGED
        return VERDICT_CONVERGED

    def _inflight_verdict(self, key, worker, detail) -> str:
        now = self._clock()
        # parked on an AWS wait state?
        table = _resolve(self._settle_table)
        if table is not None:
            parked = table.parked_info(key)
            if parked is not None:
                detail["parked"] = {
                    "group": parked["group"],
                    "token": str(parked["token"]),
                    "parked_for_s": round(max(0.0, now - parked["parked_at"]), 3),
                    "deadline_in_s": round(parked["deadline"] - now, 3),
                }
                return VERDICT_PARKED_SETTLE
        # sitting in a backoff/requeue delay?
        if worker is not None:
            delayed = worker.queue.delayed_peek(key)
            if delayed is not None:
                detail["delayed"] = delayed
                reason = delayed.get("reason", "")
                if reason == VERDICT_CIRCUIT_OPEN:
                    health = _resolve(self._health)
                    if health is not None:
                        detail["open_circuits"] = health.open_services()
                    return VERDICT_CIRCUIT_OPEN
                if reason == VERDICT_QUOTA_PACED:
                    return VERDICT_QUOTA_PACED
                if reason == VERDICT_SHED:
                    return VERDICT_SHED
                if reason == VERDICT_IN_FLIGHT:
                    return VERDICT_IN_FLIGHT
                return VERDICT_BACKOFF
            if worker.queue.contains(key):
                detail["queue"] = "ready-or-processing"
                return VERDICT_IN_FLIGHT
        # journey open but the key is nowhere in the machinery: either
        # deferrable work is being shed under burn, or we caught the
        # instant between two queue moves
        if self._slo_shedding is not None:
            try:
                if self._slo_shedding():
                    detail["note"] = "work deferred under SLO budget burn"
                    return VERDICT_SHED
            except Exception:
                pass
        detail["note"] = "journey open; between queue movements"
        return VERDICT_IN_FLIGHT

    # ------------------------------------------------------------------
    # timeline
    # ------------------------------------------------------------------
    def _timeline(self, controller, key) -> list[dict]:
        """The causal timeline: the journey's opening stamp, then this
        key's flight-recorder entries oldest → newest (the ring buffer
        is bounded, so the scan is O(capacity), independent of fleet
        size), then the current wait if any."""
        events: list[dict] = []
        journey_view = self._journey_tracker().view(controller, key)
        if journey_view is not None:
            events.append({
                "event": "enqueued",
                "age_s": journey_view["age_s"],
                "trigger": journey_view["trigger"],
                "generation": journey_view["generation"],
                "journey": journey_view["id"],
            })
        try:
            entries = self._flight_recorder().dump()
        except Exception:
            entries = []
        for entry in entries:
            if entry.get("key") != key:
                continue
            if entry.get("controller") not in ("", None, controller):
                continue
            event = {
                "event": entry.get("kind", ""),
                "seq": entry.get("seq"),
                "time": entry.get("time"),
            }
            for field in ("result", "reason", "error", "duration", "ring_epoch"):
                if entry.get(field) not in ("", None):
                    event[field] = entry[field]
            events.append(event)
        if journey_view is not None:
            events.append({
                "event": "last-stage",
                "stage": journey_view["last_stage"],
                "reason": journey_view.get("last_reason", ""),
            })
        return events

    # ------------------------------------------------------------------
    # envelopes
    # ------------------------------------------------------------------
    def explain(
        self, key: str, controller: Optional[str] = None,
        surface: str = "debug-endpoint",
    ) -> dict:
        """The ``/debug/explain`` answer: per-controller verdicts for
        ``key`` (or just the named controller's), the replica identity
        and ring epoch, and a summary verdict (most blocking wins).
        Raises ``KeyError`` for an unregistered controller name (the
        endpoint's 404)."""
        self._count_query(surface)
        with self._lock:
            names = sorted(self._workers)
        if controller:
            if controller not in names:
                raise KeyError(controller)
            names = [controller]
        verdicts = {name: self.classify(name, key) for name in names}
        # an engine with no registered workers cannot vouch for
        # convergence — not-managed is the honest empty answer
        summary = (
            most_blocking(v["verdict"] for v in verdicts.values())
            if verdicts
            else VERDICT_NOT_MANAGED
        )
        return {
            "key": key,
            "identity": self.identity,
            "ring_epoch": self.ring_epoch(),
            "verdict": summary,
            "controllers": verdicts,
        }

    # ------------------------------------------------------------------
    # the blocked histogram (gauge + post-mortem table)
    # ------------------------------------------------------------------
    def blocked_counts(self) -> dict[str, int]:
        """Verdict → count over every in-flight journey — the
        ``agac_explain_blocked`` gauge's collection sweep.  O(number of
        unconverged objects); cached for ``BLOCKED_CACHE_TTL`` so the
        gauge's per-series callbacks share one sweep."""
        now = self._clock()
        stamp, cached = self._counts_cache
        if stamp is not None and 0 <= now - stamp < BLOCKED_CACHE_TTL:
            return cached
        counts: dict[str, int] = {}
        for controller, key in self._journey_tracker().inflight_keys():
            try:
                verdict = self.classify(controller, key)["verdict"]
            except Exception:
                continue
            counts[verdict] = counts.get(verdict, 0) + 1
        self._counts_cache = (now, counts)
        return counts

    def top_blocked(self, limit: int = 8) -> list[tuple[str, int]]:
        counts = self.blocked_counts()
        return sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[:limit]

    def log_top_blocked(self, limit: int = 8) -> None:
        """Dump the top blocked-on table via klog — the SIGTERM
        post-mortem companion to the flight-recorder tail and the
        stack profiler's top table."""
        self._count_query("post-mortem")
        rows = self.top_blocked(limit)
        if not rows:
            return
        total = sum(count for _, count in rows)
        klog.infof("explain: top blocked-on verdicts (%d unconverged):", total)
        for reason, count in rows:
            klog.infof("  %6d  %s", count, reason)


# ---------------------------------------------------------------------------
# fleet merge (the `explain` CLI's resolution over --fleet-peers)
# ---------------------------------------------------------------------------


def merge_fleet_explains(answers: dict[str, dict]) -> dict:
    """Resolve per-peer ``/debug/explain`` answers into one fleet
    verdict: the owning shard's answer (any verdict that is not
    ``not-owner``/``unowned-resize``) is authoritative; non-owners only
    contribute their ring epoch.  Multiple owner-shaped answers (a
    resize race) resolve most-blocking-first; peers that failed to
    answer are reported, never silently dropped."""
    peers: dict[str, dict] = {}
    owners: list[tuple[str, dict]] = []
    for peer, answer in sorted(answers.items()):
        if not isinstance(answer, dict) or "error" in answer:
            peers[peer] = {
                "error": (answer or {}).get("error", "no answer")
                if isinstance(answer, dict)
                else "no answer",
            }
            continue
        verdict = answer.get("verdict", VERDICT_NOT_OWNER)
        peers[peer] = {
            "verdict": verdict,
            "identity": answer.get("identity", ""),
            "ring_epoch": answer.get("ring_epoch", 0),
        }
        if verdict not in (VERDICT_NOT_OWNER, VERDICT_UNOWNED_RESIZE):
            owners.append((peer, answer))
    if owners:
        ranked = {answer.get("verdict"): peer for peer, answer in owners}
        verdict = most_blocking(ranked)
        owner = ranked[verdict]
        authoritative = dict(answers[owner])
    else:
        owner = None
        verdict = most_blocking(
            info.get("verdict") for info in peers.values() if "verdict" in info
        ) if any("verdict" in info for info in peers.values()) else VERDICT_NOT_OWNER
        authoritative = {}
    return {
        "verdict": verdict,
        "owner": owner,
        "peers": peers,
        "answer": authoritative,
    }


# ---------------------------------------------------------------------------
# the process-global engine (manager wires the real one at build; the
# default is journey-tracker-only so every surface degrades gracefully)
# ---------------------------------------------------------------------------

_engine = ExplainEngine()


def engine() -> ExplainEngine:
    return _engine


def install(new_engine: ExplainEngine) -> ExplainEngine:
    """Swap the process engine (manager build / tests); returns the
    previous one so the caller can restore it."""
    global _engine
    previous = _engine
    _engine = new_engine
    return previous


def ring_epoch() -> int:
    """The installed engine's live resize epoch — the reconcile loop's
    one-call seam for stamping flight-recorder entries."""
    return _engine.ring_epoch()
