"""The convergence SLO engine: declared objectives, multi-window burn
rates, and burn-gated shedding of deferrable load.

Arcturus (arxiv 2507.10928) attributes global-accelerator stability to
continuously monitored, budget-gated control actions; Swift (arxiv
2501.19051) shows control-plane TAIL latency is what bites at elastic
scale.  This engine turns the journey tracker's convergence-latency
histograms (``observability/journey.py``) into exactly that control
signal:

- **Objectives** are declarative: "99% of spec-triggered
  GlobalAccelerator journeys converge within 120 s"
  (``ga_converge_p99 < 120s``).  Thresholds MUST sit on a
  ``JOURNEY_BUCKETS`` bound — "good" journeys are counted straight off
  the histogram's cumulative buckets, so there is nothing to sample
  and nothing to store per journey.
- **Burn rates** are computed over sliding windows (default 5 m and
  1 h) from periodic snapshots of each objective's cumulative
  (good, total) counters: ``burn = bad_fraction / error_budget``.
  1.0 burns the budget exactly at the sustainable rate; the classic
  multi-window rule (BOTH windows burning) separates a real sustained
  regression from a transient blip.
- **Shedding**: while every window burns past ``shed_burn``, the
  engine flags ``shedding`` — consumers (the GC sweeper, the drift
  resync ticker, ``Manager.drift_tick``/``gc_sweep``) skip their next
  deferrable round and count it in ``agac_slo_sheds_total``.  The shed
  order doctrine: GC sweeps first (pure background), then drift
  resync pacing (repair latency degrades, correctness does not);
  user-facing event reconciles are NEVER shed — they are the very
  thing the budget protects.  Hysteresis clears shedding once the
  short window cools to half the trip threshold.

Everything exports as metrics, rides ``/healthz`` as a summary block,
and serves in full (objectives, burn rates, slowest in-flight
journeys) on the new ``/slo`` endpoint.

One process-global engine slot (``engine()``/``install_engine()``):
``cmd/root`` installs the production engine, the sim harness installs
a per-scenario one on virtual time, and the default (no engine) makes
every gate a no-op — exactly the tracer/recorder pattern.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from .. import clockseam
from . import instruments
from . import journey as journey_mod
from . import metrics as metrics_mod
from .instruments import JOURNEY_BUCKETS
from .metrics import MetricsRegistry

# the controller queue labels the three controllers stamp journeys
# under (worker-spec names == workqueue names == reconcile labels)
GA_CONTROLLERS = (
    "global-accelerator-controller-service",
    "global-accelerator-controller-ingress",
)
RECORD_CONTROLLERS = (
    "route53-controller-service",
    "route53-controller-ingress",
)
BINDING_CONTROLLERS = ("endpoint-group-binding-controller",)
ALL_CONTROLLERS = GA_CONTROLLERS + RECORD_CONTROLLERS + BINDING_CONTROLLERS

DEFAULT_WINDOWS = (300.0, 3600.0)
# both windows past this burn rate trips shedding; the short window
# cooling below half of it clears (hysteresis)
DEFAULT_SHED_BURN = 1.0


@dataclass(frozen=True)
class SLOObjective:
    """One declared objective: ``target`` of the selected journeys
    must converge within ``threshold_seconds`` (a JOURNEY_BUCKETS
    bound).  ``controllers`` selects histogram series; ``trigger``
    narrows to one journey trigger ("" = all)."""

    name: str
    threshold_seconds: float
    controllers: tuple[str, ...]
    trigger: str = journey_mod.TRIGGER_SPEC
    target: float = 0.99

    def __post_init__(self):
        if self.threshold_seconds not in JOURNEY_BUCKETS:
            raise ValueError(
                f"objective {self.name!r}: threshold "
                f"{self.threshold_seconds} must be one of the journey "
                f"histogram bucket bounds {JOURNEY_BUCKETS}"
            )


def default_objectives() -> tuple[SLOObjective, ...]:
    """The shipped objective set (docs/operations.md "Convergence
    SLOs"): GA chains within 2 minutes, Route53 records and bindings
    within 1 minute, drift repairs within 2 minutes — each at p99."""
    return (
        SLOObjective("ga_converge_p99", 120.0, GA_CONTROLLERS),
        SLOObjective("record_converge_p99", 60.0, RECORD_CONTROLLERS),
        SLOObjective("binding_converge_p99", 60.0, BINDING_CONTROLLERS),
        SLOObjective(
            "drift_repair_p99", 120.0, ALL_CONTROLLERS,
            trigger=journey_mod.TRIGGER_DRIFT,
        ),
    )


def estimate_quantile(
    buckets: list[tuple[float, float]], count: float, q: float
) -> float:
    """Linear-interpolated quantile from cumulative (le, count)
    buckets — Prometheus's histogram_quantile, for the /slo view."""
    if count <= 0:
        return 0.0
    rank = q * count
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in buckets:
        if cum >= rank:
            span = cum - prev_cum
            if span <= 0:
                return bound
            return prev_bound + (bound - prev_bound) * (rank - prev_cum) / span
        prev_bound, prev_cum = bound, cum
    return buckets[-1][0] if buckets else 0.0


@dataclass
class _Snapshot:
    time: float
    # objective name -> (good, total)
    counts: dict[str, tuple[float, float]] = field(default_factory=dict)


class SLOEngine:
    """Periodically ``tick()``-ed evaluator over the journey converge
    histogram in ``registry`` (where the active tracker writes)."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        objectives: Optional[tuple[SLOObjective, ...]] = None,
        clock: Callable[[], float] = clockseam.monotonic,
        windows: tuple[float, ...] = DEFAULT_WINDOWS,
        shed_burn: float = DEFAULT_SHED_BURN,
        journey_tracker: Optional["journey_mod.JourneyTracker"] = None,
        shed_gates: bool = True,
    ):
        self._clock = clock
        self._registry = registry
        self.objectives = (
            objectives if objectives is not None else default_objectives()
        )
        self.windows = tuple(sorted(windows))
        self.shed_burn = shed_burn
        self._journey = journey_tracker
        self._lock = threading.Lock()
        self._history: deque[_Snapshot] = deque()
        self._burn: dict[str, dict[float, float]] = {}
        self.shedding = False
        self.shed_activations = 0
        # False = observe-only: the burn state machine (and its
        # metrics) still run, but should_shed() never defers work —
        # the sim harness's default, so scenario timing only changes
        # when a scenario opts into shedding
        self.shed_gates = shed_gates
        self._metrics = instruments.slo_instruments(registry)
        self._metrics.shedding.set_function(lambda: 1.0 if self.shedding else 0.0)

    # ------------------------------------------------------------------
    # histogram reads
    # ------------------------------------------------------------------
    def _converge_metric(self):
        registry = (
            self._registry
            if self._registry is not None
            else metrics_mod.registry()
        )
        return registry.get("agac_journey_converge_seconds")

    def _objective_counts(self, objective: SLOObjective) -> tuple[float, float]:
        """Cumulative (good, total) for one objective off the journey
        histogram's bucket counters — good = journeys ≤ threshold."""
        metric = self._converge_metric()
        if metric is None:
            return 0.0, 0.0
        bucket_index = metric.buckets.index(objective.threshold_seconds)
        good = total = 0.0
        with metric._lock:
            children = list(metric._children.items())
        for values, child in children:
            labels = dict(zip(metric.label_names, values))
            if labels.get("controller") not in objective.controllers:
                continue
            if objective.trigger and labels.get("trigger") != objective.trigger:
                continue
            counts, _sum, count = child.histogram_snapshot()
            good += counts[bucket_index]
            total += count
        return good, total

    def _objective_buckets(self, objective: SLOObjective) -> tuple[list, float]:
        """Merged cumulative (le, count) buckets for the quantile
        estimate."""
        metric = self._converge_metric()
        if metric is None:
            return [], 0.0
        merged = [0.0] * len(metric.buckets)
        total = 0.0
        with metric._lock:
            children = list(metric._children.items())
        for values, child in children:
            labels = dict(zip(metric.label_names, values))
            if labels.get("controller") not in objective.controllers:
                continue
            if objective.trigger and labels.get("trigger") != objective.trigger:
                continue
            counts, _sum, count = child.histogram_snapshot()
            for i, c in enumerate(counts):
                merged[i] += c
            total += count
        return list(zip(metric.buckets, merged)), total

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def tick(self) -> dict:
        """One evaluation round: snapshot cumulative counts, compute
        per-window burn rates, update gauges and the shed state.
        Returns the burn map (tests/logging)."""
        now = self._clock()
        snapshot = _Snapshot(time=now)
        for objective in self.objectives:
            snapshot.counts[objective.name] = self._objective_counts(objective)
        with self._lock:
            self._history.append(snapshot)
            horizon = now - self.windows[-1] - 60.0
            while len(self._history) > 2 and self._history[1].time <= horizon:
                self._history.popleft()
            burn = {
                objective.name: {
                    window: self._burn_rate_locked(objective, window, snapshot)
                    for window in self.windows
                }
                for objective in self.objectives
            }
            self._burn = burn
            worst = {
                window: max(
                    (burn[obj.name][window] for obj in self.objectives),
                    default=0.0,
                )
                for window in self.windows
            }
            if not self.shedding and all(
                rate >= self.shed_burn for rate in worst.values()
            ):
                self.shedding = True
                self.shed_activations += 1
            elif self.shedding and worst[self.windows[0]] < self.shed_burn / 2:
                self.shedding = False
        for objective in self.objectives:
            good, total = snapshot.counts[objective.name]
            healthy = total == 0 or good / total >= objective.target
            self._metrics.healthy.labels(objective=objective.name).set(
                1.0 if healthy else 0.0
            )
            buckets, count = self._objective_buckets(objective)
            self._metrics.p99.labels(objective=objective.name).set(
                estimate_quantile(buckets, count, objective.target)
            )
            for window in self.windows:
                self._metrics.burn_rate.labels(
                    objective=objective.name, window=f"{window:g}s"
                ).set(burn[objective.name][window])
        self._metrics.evaluations.inc()
        return burn

    def _burn_rate_locked(
        self, objective: SLOObjective, window: float, latest: _Snapshot
    ) -> float:
        """bad_fraction over the window / the objective's error budget
        (1 - target); 0 with no observations in the window."""
        base: Optional[_Snapshot] = None
        cutoff = latest.time - window
        for snapshot in self._history:
            if snapshot.time <= cutoff:
                base = snapshot
            else:
                break
        if base is None:
            base = self._history[0]
        good0, total0 = base.counts.get(objective.name, (0.0, 0.0))
        good1, total1 = latest.counts.get(objective.name, (0.0, 0.0))
        total_delta = total1 - total0
        if total_delta <= 0:
            return 0.0
        bad_delta = max(0.0, total_delta - (good1 - good0))
        budget = max(1e-9, 1.0 - objective.target)
        return (bad_delta / total_delta) / budget

    # ------------------------------------------------------------------
    # gates + views
    # ------------------------------------------------------------------
    def should_shed(self, action: str) -> bool:
        """The deferrable-load gate: True while shedding (and gates
        are armed), counting the skipped action."""
        if not self.shed_gates or not self.shedding:
            return False
        self._metrics.sheds.labels(action=action).inc()
        return True

    def burn_snapshot(self) -> dict[str, dict[float, float]]:
        """The last ``tick()``'s burn map, copied under the lock:
        ``{objective name: {window seconds: burn rate}}``.  This is
        the stable in-process read the autoscaler's signal collector
        consumes — identical numbers to the ``/slo`` endpoint's
        ``burn`` blocks, but keyed by the raw float window (no string
        formatting) and safe to call from any thread.  Empty until
        the first tick."""
        with self._lock:
            return {name: dict(per) for name, per in self._burn.items()}

    def violations(self) -> list[str]:
        """Objectives whose CUMULATIVE good fraction misses the target
        — the sim/fuzz oracle's verdict (a whole-run property, not a
        window)."""
        out = []
        for objective in self.objectives:
            good, total = self._objective_counts(objective)
            if total > 0 and good / total < objective.target:
                out.append(
                    f"slo: {objective.name} violated — "
                    f"{total - good:.0f}/{total:.0f} journeys exceeded "
                    f"{objective.threshold_seconds:g}s "
                    f"(good {good / total:.4f} < target {objective.target})"
                )
        return out

    def status(self) -> dict:
        """The /slo endpoint body + the /healthz summary block."""
        with self._lock:
            burn = {
                name: {f"{window:g}s": round(rate, 3) for window, rate in per.items()}
                for name, per in self._burn.items()
            }
            shedding = self.shedding
            activations = self.shed_activations
        objectives = []
        for objective in self.objectives:
            good, total = self._objective_counts(objective)
            buckets, count = self._objective_buckets(objective)
            objectives.append(
                {
                    "name": objective.name,
                    "threshold_s": objective.threshold_seconds,
                    "target": objective.target,
                    "trigger": objective.trigger,
                    "journeys": int(total),
                    "good_fraction": round(good / total, 5) if total else None,
                    "estimated_quantile_s": round(
                        estimate_quantile(buckets, count, objective.target), 3
                    ),
                    "burn": burn.get(objective.name, {}),
                    "healthy": total == 0 or good / total >= objective.target,
                }
            )
        status = {
            "enabled": True,
            "objectives": objectives,
            "windows_s": list(self.windows),
            "shed_burn": self.shed_burn,
            "shed_gates": self.shed_gates,
            "shedding": shedding,
            "shed_activations": activations,
        }
        if self._journey is not None:
            status["journeys"] = self._journey.stats()
            status["slowest_unconverged"] = self._journey.slowest()
        return status


# ---------------------------------------------------------------------------
# the process-global engine slot: None by default (every gate no-ops),
# installed by cmd/root (production) and the sim harness (virtual time)
# ---------------------------------------------------------------------------

_engine: Optional[SLOEngine] = None


def engine() -> Optional[SLOEngine]:
    return _engine


def install_engine(new_engine: Optional[SLOEngine]) -> Optional[SLOEngine]:
    global _engine
    previous = _engine
    _engine = new_engine
    return previous


def should_shed(action: str) -> bool:
    """The global deferrable-load gate the GC sweeper and drift
    tickers consult: False when no engine is installed."""
    current = _engine
    return current is not None and current.should_shed(action)


def status_or_disabled() -> dict:
    current = _engine
    return current.status() if current is not None else {"enabled": False}
