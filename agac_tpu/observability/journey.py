"""Per-object journey tracking: spec edit → converged AWS state.

The observability plane (ISSUE 5) measures component health — queue
depths, call latencies, circuit states — but none of it answers the
only question a *user* of the controller has: "I edited my Service;
how long until AWS matched it?"  This module stamps every lifecycle
stage of a reconcile key's journey:

    spec observed / enqueued → reconcile attempts → requeues →
    parked-settle waits → shard handoffs → converged (or deleted)

keyed by (controller, namespace/name) with the spec generation that
opened the journey, and feeds three fleet-facing signals:

- ``agac_journey_converge_seconds{controller,trigger}`` — the
  end-to-end convergence-latency histogram (the SLO engine's input);
  ``trigger`` says what opened the journey: a ``spec`` edit, a
  ``drift`` resync, or a shard ``handoff`` adoption;
- ``agac_journey_inflight{controller}`` and
  ``agac_journey_oldest_unconverged_age_seconds{controller}`` — the
  live backlog view (depth alone hides a single wedged object; the
  oldest-age gauge is what pages);
- ``agac_journey_stages_total{controller,stage}`` — stage flow
  counters, so a requeue storm or settle-expiry burst is visible as a
  rate, not only as latency.

Every journey carries an id (``<key>@g<generation>#<serial>``) that
the reconcile loop writes into each flight-recorder entry — a slow
convergence surfaced by ``/slo`` is one grep away from its recorded
attempts.

There is one process-global tracker (``tracker()``), the default for
the reconcile loop and the controllers' enqueue stamps; the sim
harness and the bench ``install()`` private trackers (bound to private
registries) for per-scenario isolation, exactly like the clock seam.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from .. import clockseam
from . import instruments
from .metrics import MetricsRegistry

# journey triggers (the converge histogram's second label)
TRIGGER_SPEC = "spec"
TRIGGER_DRIFT = "drift"
TRIGGER_HANDOFF = "handoff"
# a live shard-count resize re-homed the key (ISSUE 10): distinct
# from a failover handoff so resize-driven re-homes get their own
# convergence histogram series
TRIGGER_RESIZE = "resize"

# stage names (the stages_total label values)
STAGE_ENQUEUED = "enqueued"
STAGE_ATTEMPT = "attempt"
STAGE_REQUEUED = "requeued"
STAGE_PARKED = "parked"
STAGE_SETTLE_RESOLVED = "settle-resolved"
STAGE_SETTLE_FAILED = "settle-failed"
STAGE_SETTLE_EXPIRED = "settle-expired"
STAGE_HANDOFF = "handoff"
STAGE_CONVERGED = "converged"
STAGE_DELETED = "deleted"
STAGE_DROPPED = "dropped"

# in-flight journeys tracked before new opens are dropped (counted):
# bounds a key explosion the same way the metric registry's series cap
# does — 4x the largest simulated fleet per controller is generous
DEFAULT_MAX_INFLIGHT = 262_144


class Journey:
    """One object's in-flight journey: opened by an enqueue stamp,
    closed by a converged/deleted reconcile pass."""

    __slots__ = (
        "controller", "key", "generation", "trigger", "started",
        "attempts", "requeues", "parks", "handoffs", "last_stage",
        "last_reason", "serial",
    )

    def __init__(self, controller: str, key: str, generation: int,
                 trigger: str, started: float, serial: int):
        self.controller = controller
        self.key = key
        self.generation = generation
        self.trigger = trigger
        self.started = started
        self.serial = serial
        self.attempts = 0
        self.requeues = 0
        self.parks = 0
        self.handoffs = 0
        self.last_stage = STAGE_ENQUEUED
        self.last_reason = ""

    @property
    def id(self) -> str:
        return f"{self.key}@g{self.generation}#{self.serial}"

    def to_dict(self, now: float) -> dict:
        return {
            "id": self.id,
            "controller": self.controller,
            "key": self.key,
            "generation": self.generation,
            "trigger": self.trigger,
            "age_s": round(max(0.0, now - self.started), 3),
            "attempts": self.attempts,
            "requeues": self.requeues,
            "parks": self.parks,
            "handoffs": self.handoffs,
            "last_stage": self.last_stage,
            "last_reason": self.last_reason,
        }


class JourneyTracker:
    """Thread-safe (controller, key) → Journey table + the metric
    stamps.  Every method is a cheap no-op for keys it has never been
    told about, so instrumented paths never branch on tracker state."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        clock: Callable[[], float] = clockseam.monotonic,
        max_inflight: int = DEFAULT_MAX_INFLIGHT,
    ):
        self._clock = clock
        self._lock = threading.Lock()
        self._inflight: dict[tuple[str, str], Journey] = {}
        self._serial = 0
        self._max_inflight = max(1, max_inflight)
        self._metrics = instruments.journey_instruments(registry)
        self._bound_controllers: set[str] = set()
        # cumulative close counters (stats()/tests; the histogram's
        # _count carries the same totals per label)
        self.converged_total = 0
        self.deleted_total = 0
        self.dropped_total = 0  # opens refused at the inflight cap

    # ------------------------------------------------------------------
    # opening stamps
    # ------------------------------------------------------------------
    def observe_enqueued(
        self,
        controller: str,
        key: str,
        generation: int = 0,
        trigger: str = TRIGGER_SPEC,
    ) -> None:
        """The journey's opening stamp, from the controllers' enqueue
        paths.  A key already in flight keeps its clock UNLESS a newer
        spec generation arrives — the user experiences latency to the
        generation they last wrote, so the clock restarts there."""
        now = self._clock()
        with self._lock:
            journey = self._inflight.get((controller, key))
            if journey is not None:
                if generation > journey.generation:
                    # a newer spec superseded the in-flight journey:
                    # restart the clock at the edit the user now waits on
                    journey.generation = generation
                    journey.started = now
                    journey.trigger = trigger
                journey.last_stage = STAGE_ENQUEUED
            else:
                if len(self._inflight) >= self._max_inflight:
                    self.dropped_total += 1
                    return
                self._serial += 1
                journey = Journey(
                    controller, key, generation, trigger, now, self._serial
                )
                self._inflight[(controller, key)] = journey
                if journey.handoffs == 0 and trigger == TRIGGER_HANDOFF:
                    journey.handoffs = 1
            self._bind_controller_views(controller)
        self._metrics.stages.labels(
            controller=controller, stage=STAGE_ENQUEUED
        ).inc()
        if trigger == TRIGGER_HANDOFF:
            self._metrics.stages.labels(
                controller=controller, stage=STAGE_HANDOFF
            ).inc()

    # ------------------------------------------------------------------
    # in-flight stamps
    # ------------------------------------------------------------------
    def stage(
        self, controller: str, key: str, stage: str, reason: str = ""
    ) -> None:
        """A mid-journey stamp (requeued / parked / settle outcomes).
        Unknown keys still count the stage — the flow counters must see
        every requeue even when the open stamp was dropped.  ``reason``
        is the structured explain-catalog code attached at the
        requeue/park site; the explain plane reads it back as the
        journey's last known cause."""
        with self._lock:
            journey = self._inflight.get((controller, key))
            if journey is not None:
                journey.last_stage = stage
                if reason:
                    journey.last_reason = reason
                if stage == STAGE_REQUEUED:
                    journey.requeues += 1
                elif stage == STAGE_PARKED:
                    journey.parks += 1
                elif stage == STAGE_HANDOFF:
                    journey.handoffs += 1
        self._metrics.stages.labels(controller=controller, stage=stage).inc()

    def attempt(self, controller: str, key: str) -> None:
        with self._lock:
            journey = self._inflight.get((controller, key))
            if journey is not None:
                journey.attempts += 1
                journey.last_stage = STAGE_ATTEMPT
        self._metrics.stages.labels(
            controller=controller, stage=STAGE_ATTEMPT
        ).inc()

    # ------------------------------------------------------------------
    # closing stamps
    # ------------------------------------------------------------------
    def converged(self, controller: str, key: str) -> Optional[float]:
        return self._close(controller, key, STAGE_CONVERGED)

    def deleted(self, controller: str, key: str) -> Optional[float]:
        return self._close(controller, key, STAGE_DELETED)

    def drop(self, controller: str, key: str) -> None:
        """Close a journey that can NEVER converge (permanent error:
        the retry policy dropped the item) WITHOUT observing a
        latency — a dropped item is not a convergence, and folding it
        into the histogram would poison the SLO with infinities."""
        with self._lock:
            self._inflight.pop((controller, key), None)
        self._metrics.stages.labels(
            controller=controller, stage=STAGE_DROPPED
        ).inc()

    def _close(self, controller: str, key: str, stage: str) -> Optional[float]:
        now = self._clock()
        with self._lock:
            journey = self._inflight.pop((controller, key), None)
            if journey is None:
                return None
            if stage == STAGE_CONVERGED:
                self.converged_total += 1
            else:
                self.deleted_total += 1
            trigger = journey.trigger
            latency = max(0.0, now - journey.started)
        self._metrics.stages.labels(controller=controller, stage=stage).inc()
        self._metrics.converge.labels(
            controller=controller, trigger=trigger
        ).observe(latency)
        return latency

    # ------------------------------------------------------------------
    # views
    # ------------------------------------------------------------------
    def journey_id(self, controller: str, key: str) -> Optional[str]:
        with self._lock:
            journey = self._inflight.get((controller, key))
            return journey.id if journey is not None else None

    def view(self, controller: str, key: str) -> Optional[dict]:
        """One journey's snapshot dict (None when not in flight) — a
        single dict get, the explain plane's O(1) per-key read."""
        now = self._clock()
        with self._lock:
            journey = self._inflight.get((controller, key))
            return journey.to_dict(now) if journey is not None else None

    def inflight_keys(self) -> list[tuple[str, str]]:
        """Every in-flight (controller, key) — the explain plane's
        blocked-histogram sweep (O(unconverged), never per-lookup)."""
        with self._lock:
            return list(self._inflight)

    def inflight(self, controller: Optional[str] = None) -> int:
        with self._lock:
            if controller is None:
                return len(self._inflight)
            return sum(
                1 for (ctrl, _key) in self._inflight if ctrl == controller
            )

    def oldest_age(self, controller: Optional[str] = None) -> float:
        with self._lock:
            oldest = min(
                (
                    journey.started
                    for (ctrl, _key), journey in self._inflight.items()
                    if controller is None or ctrl == controller
                ),
                default=None,
            )
        if oldest is None:
            return 0.0
        return max(0.0, self._clock() - oldest)

    def oldest_unconverged_age(self, controller: Optional[str] = None) -> float:
        """Age in seconds of the oldest journey still in flight
        (0.0 when nothing is in flight) — the documented public
        accessor the autoscaler's signal collector reads, and the same
        number ``agac_journey_oldest_unconverged_age_seconds``
        exports.  ``controller`` narrows to one controller's
        journeys; the default spans the whole tracker."""
        return self.oldest_age(controller)

    def slowest(self, limit: int = 10) -> list[dict]:
        """The oldest unconverged journeys, oldest first — the
        ``/slo`` endpoint's drill-down list (each entry's id is
        grep-able in the flight recorder)."""
        now = self._clock()
        with self._lock:
            journeys = sorted(self._inflight.values(), key=lambda j: j.started)
        return [journey.to_dict(now) for journey in journeys[:limit]]

    def stats(self) -> dict:
        with self._lock:
            inflight = len(self._inflight)
        return {
            "inflight": inflight,
            "converged_total": self.converged_total,
            "deleted_total": self.deleted_total,
            "dropped_total": self.dropped_total,
            "oldest_age_s": round(self.oldest_age(), 3),
        }

    def _bind_controller_views(self, controller: str) -> None:
        """Bind the per-controller inflight/oldest-age callback gauges
        the first time a controller appears (called under the lock)."""
        if controller in self._bound_controllers:
            return
        self._bound_controllers.add(controller)
        self._metrics.inflight.labels(controller=controller).set_function(
            lambda ctrl=controller: self.inflight(ctrl)
        )
        self._metrics.oldest_age.labels(controller=controller).set_function(
            lambda ctrl=controller: self.oldest_age(ctrl)
        )


# ---------------------------------------------------------------------------
# the process-global tracker (reconcile loop + controllers default to
# it); the sim harness and the bench install private trackers
# ---------------------------------------------------------------------------

_tracker = JourneyTracker()


def tracker() -> JourneyTracker:
    return _tracker


def install(new_tracker: JourneyTracker) -> JourneyTracker:
    """Swap the process tracker (sim harness / bench isolation);
    returns the previous one so the caller can restore it."""
    global _tracker
    previous = _tracker
    _tracker = new_tracker
    return previous
