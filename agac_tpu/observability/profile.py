"""The stage accountant: per-stage CPU/wall attribution (ISSUE 14).

The roadmap's next perf item — breaking the single-core wall — begins
with "profile the per-reconcile CPU path", and nothing in the stack
could attribute *CPU* cost to stages: the workqueue/reconcile
histograms (PR 5) measure wall clock, the journey plane (PR 9)
measures end-to-end latency, and both hide where a core actually goes
between queue pop and queue done.  This module is the attribution
layer every later perf PR reads first.

Mechanics:

- ``stage(name)`` is a context manager charging the bracketed code's
  CPU (``clockseam.thread_cpu()``, i.e. ``time.thread_time`` in
  production and the virtual clock under the sim) and wall time to the
  named stage.  Stages nest; a parent is charged its EXCLUSIVE time
  only (children's inclusive time is subtracted), so the per-stage
  table sums to the measured total instead of double-counting.
- Stage NAMES are closed over by the ``STAGES`` catalog below; the
  ``unattributed-stage`` lint rule (``analysis/rules.py``) rejects a
  ``stage(...)`` call whose literal name is not catalogued, exactly
  like ``unregistered-metric`` does for metric names.  The one dynamic
  family — per-AWS-call attribution — goes through ``api_stage`` and
  is namespaced ``aws:{service}.{op}``.
- ``reconcile_scope(controller)`` brackets one work item: stages
  closed inside it accumulate into the scope and are flushed on exit
  into the ``agac_profile_stage_cpu_seconds`` /
  ``..._wall_seconds{stage,controller}`` histograms plus the
  per-reconcile cpu/wall ratio gauge.  Stages closed OUTSIDE a scope
  (drift tick, GC sweep, batcher flush on a non-worker thread) flush
  immediately under the stage's own name.
- Everything also lands in a process-global aggregate the bench's
  ``profiling`` phase snapshots into its ranked attribution table
  (``attribution_table``); the same table shape can be computed from a
  (possibly fleet-merged) ``/metrics`` exposition via
  ``attribution_from_exposition`` — stage histograms are ordinary
  registry histograms, so the PR 9 fleet-merge path sums them across
  shard replicas with no extra code.

The accountant is ON by default: its hot-path cost is two clock reads
per stage plus dict arithmetic, and the bench's profiling phase
asserts the measured overhead stays ≤ 5% of headline obj/s.
``--profile-stages=off`` (cmd/root) or ``configure(stages=False)``
turns every bracket into a shared no-op context manager.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Optional

from .. import clockseam
from . import instruments

# ---------------------------------------------------------------------------
# the stage catalog — every static stage name the accountant may be
# handed, with the one-line meaning an operator reads in docs.  The
# ``unattributed-stage`` lint rule carries a literal copy of these
# names (the linter never imports the package it lints);
# tests/test_profiling.py pins the two sets equal.
# ---------------------------------------------------------------------------

STAGES: dict[str, str] = {
    "queue-pop": "popping the next item from the workqueue (wall time "
    "includes idle wait; CPU is the pop bookkeeping itself)",
    "shard-filter": "pop-time shard-ownership re-check (hash-ring "
    "lookup behind the ShardFilter memo)",
    "informer-lookup": "resolving the key to its cached object through "
    "the lister",
    "serialize": "deep-copying the cached object before mutation (the "
    "reference's DeepCopy) plus any hashing of it",
    "driver-mutate": "the controller's process func: ensure/verify "
    "logic and driver calls (per-call CPU splits out into aws:* "
    "child stages)",
    "settle-park": "parking the item in the pending-settle table "
    "after an AWS wait state",
    "self-tax": "the observability plane's own cost: metric "
    "increments, journey stamps, trace annotation, flight-recorder "
    "writes",
    "drift-tick": "one drift-resync round: walking every controller's "
    "drift sources and re-enqueueing managed objects",
    "gc-sweep": "one orphan-GC sweep: AWS/apiserver cross-checks and "
    "grace bookkeeping",
    "r53-batch-flush": "committing one gathered Route53 change batch "
    "(merge, wire call, ticket fan-out)",
}

# dynamic per-AWS-call stages are namespaced under this prefix
# (``aws:globalaccelerator.create_accelerator`` and friends); they are
# created by ``api_stage`` only, so the lint rule's literal-name check
# never sees them
API_STAGE_PREFIX = "aws:"

# the controller label immediate-flush (out-of-reconcile) stages carry
# unless the call site passes its own
DEFAULT_CONTROLLER = "manager"

_enabled = True


def configure(stages: Optional[bool] = None) -> None:
    """Arm/disarm the stage accountant (cmd/root's ``--profile-stages``)."""
    global _enabled
    if stages is not None:
        _enabled = bool(stages)


def stages_enabled() -> bool:
    return _enabled


# ---------------------------------------------------------------------------
# thread-local stage stack + per-reconcile scope
# ---------------------------------------------------------------------------

_tls = threading.local()


class _Frame:
    __slots__ = ("name", "cpu0", "wall0", "child_cpu", "child_wall")

    def __init__(self, name: str, cpu0: float, wall0: float):
        self.name = name
        self.cpu0 = cpu0
        self.wall0 = wall0
        self.child_cpu = 0.0
        self.child_wall = 0.0


class _Scope:
    """One reconcile's stage totals: {stage: [cpu, wall, hits]}."""

    __slots__ = ("controller", "totals")

    def __init__(self, controller: str):
        self.controller = controller
        self.totals: dict[str, list[float]] = {}

    def add(self, name: str, cpu: float, wall: float) -> None:
        entry = self.totals.get(name)
        if entry is None:
            self.totals[name] = [cpu, wall, 1.0]
        else:
            entry[0] += cpu
            entry[1] += wall
            entry[2] += 1.0

    def breakdown_us(self) -> dict[str, int]:
        """{stage: exclusive CPU microseconds} of the stages closed so
        far — the trace-annotation view ("where did this reconcile's
        time go" on one flight-recorder line)."""
        return {
            name: int(entry[0] * 1e6) for name, entry in sorted(self.totals.items())
        }


class _NullScope:
    # no instance state: the singleton is shared across every thread,
    # and empty __slots__ makes that structurally true (the confinement
    # census proves it stateless rather than trusting the comment)
    __slots__ = ()

    controller = ""

    def breakdown_us(self) -> dict[str, int]:
        return {}


_NULL_SCOPE = _NullScope()


class _NullStage:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_STAGE = _NullStage()


class _StageContext:
    __slots__ = ("_name", "_controller", "_frame")

    def __init__(self, name: str, controller: str):
        self._name = name
        self._controller = controller

    def __enter__(self):
        stack = getattr(_tls, "frames", None)
        if stack is None:
            stack = _tls.frames = []
        self._frame = _Frame(
            self._name, clockseam.thread_cpu(), clockseam.monotonic()
        )
        stack.append(self._frame)
        return self

    def __exit__(self, *exc):
        frame = self._frame
        incl_cpu = clockseam.thread_cpu() - frame.cpu0
        incl_wall = clockseam.monotonic() - frame.wall0
        stack = _tls.frames
        stack.pop()
        if stack:
            parent = stack[-1]
            parent.child_cpu += incl_cpu
            parent.child_wall += incl_wall
        excl_cpu = max(0.0, incl_cpu - frame.child_cpu)
        excl_wall = max(0.0, incl_wall - frame.child_wall)
        scope = getattr(_tls, "scope", None)
        if scope is not None:
            scope.add(frame.name, excl_cpu, excl_wall)
        else:
            _flush_stage(frame.name, self._controller, excl_cpu, excl_wall)
        return False


def stage(name: str, controller: str = DEFAULT_CONTROLLER):
    """Charge the bracketed code to ``name``.  ``controller`` labels
    the flush only when no reconcile scope is active (the scope's own
    controller wins inside one)."""
    if not _enabled:
        return _NULL_STAGE
    return _StageContext(name, controller)


def api_stage(service: str, op: str):
    """The dynamic per-AWS-call stage (``aws:{service}.{op}``) the
    driver's instrumented handles bracket each call with — a child of
    ``driver-mutate``, so per-op CPU splits out of the process func's
    exclusive time."""
    if not _enabled:
        return _NULL_STAGE
    return _StageContext(f"{API_STAGE_PREFIX}{service}.{op}", DEFAULT_CONTROLLER)


class _ReconcileScope:
    __slots__ = ("_controller", "_scope", "_prev", "_cpu0", "_wall0")

    def __init__(self, controller: str):
        self._controller = controller

    def __enter__(self) -> _Scope:
        self._scope = _Scope(self._controller)
        self._prev = getattr(_tls, "scope", None)
        _tls.scope = self._scope
        self._cpu0 = clockseam.thread_cpu()
        self._wall0 = clockseam.monotonic()
        return self._scope

    def __exit__(self, *exc):
        total_cpu = clockseam.thread_cpu() - self._cpu0
        total_wall = clockseam.monotonic() - self._wall0
        _tls.scope = self._prev
        _flush_scope(self._scope, total_cpu, total_wall)
        return False


def reconcile_scope(controller: str):
    """Bracket one work item: stages closed inside accumulate into the
    yielded scope and flush to the histograms + aggregate on exit."""
    if not _enabled:
        return _NullReconcileScope()
    return _ReconcileScope(controller)


class _NullReconcileScope:
    __slots__ = ()

    def __enter__(self) -> _NullScope:
        return _NULL_SCOPE

    def __exit__(self, *exc):
        return False


def current_scope():
    """The thread's active reconcile scope (``_NULL_SCOPE`` outside
    one) — the seam the trace-annotation call site reads the stage-CPU
    breakdown from."""
    scope = getattr(_tls, "scope", None)
    return scope if scope is not None else _NULL_SCOPE


def current_stages() -> tuple[str, ...]:
    """Names of the thread's open stage brackets, outermost first
    (``("driver-mutate", "aws:ga.CreateAccelerator")`` inside an
    instrumented AWS call).  The runtime side of the confinement
    cross-check: racecheck tags observed shared-state mutations with
    this tuple so they can be matched against the static stage
    footprint table."""
    stack = getattr(_tls, "frames", None)
    if not stack:
        return ()
    return tuple(frame.name for frame in stack)


def current_stage() -> Optional[str]:
    """The innermost open stage bracket, or None outside any."""
    stack = getattr(_tls, "frames", None)
    return stack[-1].name if stack else None


# ---------------------------------------------------------------------------
# flush + process-global aggregate
# ---------------------------------------------------------------------------

_agg_lock = threading.Lock()
_agg: dict[str, list[float]] = {}  # stage -> [cpu, wall, hits]
_agg_reconciles = 0


def _flush_stage(name: str, controller: str, cpu: float, wall: float) -> None:
    metrics = instruments.profile_instruments()
    metrics.stage_cpu.labels(stage=name, controller=controller).observe(cpu)
    metrics.stage_wall.labels(stage=name, controller=controller).observe(wall)
    with _agg_lock:
        entry = _agg.get(name)
        if entry is None:
            _agg[name] = [cpu, wall, 1.0]
        else:
            entry[0] += cpu
            entry[1] += wall
            entry[2] += 1.0


def _flush_scope(scope: _Scope, total_cpu: float, total_wall: float) -> None:
    global _agg_reconciles
    metrics = instruments.profile_instruments()
    for name, (cpu, wall, hits) in scope.totals.items():
        metrics.stage_cpu.labels(stage=name, controller=scope.controller).observe(cpu)
        metrics.stage_wall.labels(stage=name, controller=scope.controller).observe(wall)
    if total_wall > 0:
        metrics.cpu_wall_ratio.labels(controller=scope.controller).set(
            min(1.0, total_cpu / total_wall)
        )
    metrics.reconciles.labels(controller=scope.controller).inc()
    with _agg_lock:
        _agg_reconciles += 1
        for name, (cpu, wall, hits) in scope.totals.items():
            entry = _agg.get(name)
            if entry is None:
                _agg[name] = [cpu, wall, hits]
            else:
                entry[0] += cpu
                entry[1] += wall
                entry[2] += hits


def reset_aggregate() -> None:
    """Zero the process-global attribution aggregate (bench phase
    boundaries; tests)."""
    global _agg_reconciles
    with _agg_lock:
        _agg.clear()
        _agg_reconciles = 0


def aggregate_snapshot() -> dict:
    """{"reconciles": N, "stages": {stage: {cpu_seconds, wall_seconds,
    hits}}} — the raw aggregate ``attribution_table`` ranks."""
    with _agg_lock:
        return {
            "reconciles": _agg_reconciles,
            "stages": {
                name: {
                    "cpu_seconds": entry[0],
                    "wall_seconds": entry[1],
                    "hits": int(entry[2]),
                }
                for name, entry in sorted(_agg.items())
            },
        }


def attribution_table(top: Optional[int] = None) -> list[dict]:
    """The ranked CPU attribution table off the process aggregate:
    one row per stage, hottest CPU first, each carrying total CPU/wall
    seconds, hit count, and ``cpu_ns_per_reconcile`` (the per-stage
    regression rail the bench pins — total stage CPU spread over every
    reconcile the accountant closed)."""
    snap = aggregate_snapshot()
    per = max(1, snap["reconciles"])
    rows = [
        {
            "stage": name,
            "cpu_seconds": round(entry["cpu_seconds"], 9),
            "wall_seconds": round(entry["wall_seconds"], 9),
            "hits": entry["hits"],
            "cpu_ns_per_reconcile": int(entry["cpu_seconds"] / per * 1e9),
        }
        for name, entry in snap["stages"].items()
    ]
    rows.sort(key=lambda r: (-r["cpu_seconds"], r["stage"]))
    return rows[:top] if top else rows


# ---------------------------------------------------------------------------
# exposition-based attribution (fleet-merged view)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^agac_profile_stage_(?P<kind>cpu|wall)_seconds_(?P<part>sum|count)"
    r"\{(?P<labels>[^}]*)\}\s+(?P<value>\S+)$"
)
_STAGE_LABEL_RE = re.compile(r'stage="((?:[^"\\]|\\.)*)"')


def attribution_from_exposition(text: str, top: Optional[int] = None) -> list[dict]:
    """The same ranked table computed from a Prometheus text
    exposition — pointed at ``/metrics/fleet`` this is the
    fleet-merged attribution across every shard replica (the PR 9
    merge path sums the stage histograms sample-by-sample, so summing
    per-stage ``_sum``/``_count`` over controllers here completes the
    merge)."""
    stages: dict[str, dict[str, float]] = {}
    for line in text.splitlines():
        m = _SAMPLE_RE.match(line.strip())
        if m is None:
            continue
        label_m = _STAGE_LABEL_RE.search(m.group("labels"))
        if label_m is None:
            continue
        name = label_m.group(1)
        entry = stages.setdefault(
            name, {"cpu_sum": 0.0, "wall_sum": 0.0, "count": 0.0}
        )
        value = float(m.group("value"))
        if m.group("kind") == "cpu":
            if m.group("part") == "sum":
                entry["cpu_sum"] += value
            else:
                entry["count"] += value
        elif m.group("part") == "sum":
            entry["wall_sum"] += value
    rows = [
        {
            "stage": name,
            "cpu_seconds": round(entry["cpu_sum"], 9),
            "wall_seconds": round(entry["wall_sum"], 9),
            "hits": int(entry["count"]),
            "cpu_ns_per_hit": (
                int(entry["cpu_sum"] / entry["count"] * 1e9) if entry["count"] else 0
            ),
        }
        for name, entry in stages.items()
    ]
    rows.sort(key=lambda r: (-r["cpu_seconds"], r["stage"]))
    return rows[:top] if top else rows
