"""The flight recorder: a fixed-size ring buffer of recent reconcile
outcomes and errors.

Logs rotate and sampling drops most traces; what an operator actually
needs after a wedge or a crash is "what were the last few hundred
things this controller did, and which of them failed".  The recorder
keeps exactly that, bounded:

- every completed reconcile records one entry (controller, key,
  outcome, error text, duration, requeue count);
- drift ticks and GC sweeps record their reports;
- the buffer is a ``deque(maxlen=capacity)`` — O(1) append, oldest
  entries evicted, memory strictly bounded;
- ``dump()`` returns the entries oldest → newest for the
  ``/debug/flightrecorder`` endpoint, and ``log_dump()`` writes a
  compact tail to the log — wired to SIGTERM so a terminating pod
  leaves its last moments in the pod log where the kubelet keeps them.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Callable

from .. import clockseam, klog

DEFAULT_CAPACITY = 512


class FlightRecorder:
    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        clock: Callable[[], float] = clockseam.time,
    ):
        self.capacity = max(1, capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: deque[dict] = deque(maxlen=self.capacity)
        self._seq = 0
        self.recorded_total = 0

    def record(self, kind: str, **fields) -> None:
        """Append one entry; never raises (telemetry must not fail the
        hot path) and never grows past capacity."""
        try:
            with self._lock:
                self._seq += 1
                self.recorded_total += 1
                entry = {"seq": self._seq, "time": round(self._clock(), 3), "kind": kind}
                entry.update(fields)
                self._entries.append(entry)
        except Exception as err:  # a bad field must not kill a worker
            klog.errorf("flight recorder: dropping entry: %s", err)

    def dump(self, limit: int = 0) -> list[dict]:
        """Entries oldest → newest; ``limit`` > 0 keeps only the most
        recent that many."""
        with self._lock:
            entries = list(self._entries)
        if limit > 0:
            entries = entries[-limit:]
        return entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def log_dump(self, limit: int = 64) -> None:
        """Write the most recent entries to the log as one compact
        JSON line each — the SIGTERM post-mortem (a terminating pod's
        log survives in the kubelet; its /debug endpoint does not)."""
        entries = self.dump(limit=limit)
        klog.infof(
            "flight recorder: dumping last %d of %d recorded entries",
            len(entries), self.recorded_total,
        )
        # the active incident capture's cursor (ISSUE 19): the dying
        # pod names the exact capture window — file, byte offset, last
        # event serial — so the post-mortem points straight at the
        # replayable artifact.  Contained like the rest of the dump.
        try:
            from ..sim.capture import active

            tap = active()
            if tap is not None:
                klog.infof(
                    "flight capture-cursor %s",
                    json.dumps(tap.cursor(), separators=(",", ":"), sort_keys=True),
                )
        except Exception:
            pass
        for entry in entries:
            try:
                klog.infof("flight %s", json.dumps(entry, separators=(",", ":"), sort_keys=True))
            except Exception:
                klog.infof("flight %r", entry)


# ---------------------------------------------------------------------------
# the process-global recorder (one reconcile plane per process; tests
# build their own FlightRecorder and pass it where they need isolation)
# ---------------------------------------------------------------------------

_recorder = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    return _recorder
