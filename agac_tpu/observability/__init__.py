"""The observability plane (ISSUE 5) + the convergence SLO plane
(ISSUE 9): metrics, trace spans, the flight recorder, object journeys,
SLO burn rates, and fleet-merged scrapes.

Dependency-free modules give the whole stack one telemetry surface
(Arcturus' stability argument applied to *this* control plane: you
cannot operate what you cannot measure):

- ``metrics``: a thread-safe Prometheus-style registry
  (Counter/Gauge/Histogram with bounded label cardinality and text
  exposition) — every subsystem's counters live here instead of in
  private dicts;
- ``trace``: sampled per-reconcile trace spans (queue wait, sync,
  each AWS call, settle polls, the requeue decision) emitted as
  structured log lines;
- ``recorder``: a fixed-size ring buffer of recent reconcile
  outcomes/errors, dumpable via ``/debug/flightrecorder`` and on
  SIGTERM — the post-mortem the logs have usually rotated away;
- ``journey``: per-object lifecycle stamps (enqueued → attempts →
  parks → handoffs → converged) feeding the end-to-end
  convergence-latency histograms — the only latency a *user* of the
  controller experiences;
- ``slo``: declared convergence objectives, multi-window error-budget
  burn rates, and the burn-gated shedding of deferrable load (GC
  sweeps, drift pacing) — served on ``/slo``;
- ``fleet``: merges shard replicas' scrapes (counters summed, gauges
  shard-labeled, journey histograms aggregated) into the one fleet
  view ``/metrics/fleet`` serves.

``instruments`` centralizes every metric declaration so the exposed
catalog (``python -m agac_tpu.observability.catalog``) can never drift
from the instrumented code.
"""

from .journey import JourneyTracker
from .metrics import Counter, Gauge, Histogram, MetricsRegistry, registry
from .recorder import FlightRecorder, flight_recorder
from .slo import SLOEngine, SLOObjective
from .trace import Span, Trace, Tracer, tracer

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "FlightRecorder",
    "flight_recorder",
    "JourneyTracker",
    "SLOEngine",
    "SLOObjective",
    "Span",
    "Trace",
    "Tracer",
    "tracer",
]
