"""The observability plane (ISSUE 5): metrics, trace spans, and the
flight recorder.

Three dependency-free modules give the whole stack one telemetry
surface (Arcturus' stability argument applied to *this* control plane:
you cannot operate what you cannot measure):

- ``metrics``: a thread-safe Prometheus-style registry
  (Counter/Gauge/Histogram with bounded label cardinality and text
  exposition) — every subsystem's counters live here instead of in
  private dicts;
- ``trace``: sampled per-reconcile trace spans (queue wait, sync,
  each AWS call, settle polls, the requeue decision) emitted as
  structured log lines;
- ``recorder``: a fixed-size ring buffer of recent reconcile
  outcomes/errors, dumpable via ``/debug/flightrecorder`` and on
  SIGTERM — the post-mortem the logs have usually rotated away.

``instruments`` centralizes every metric declaration so the exposed
catalog (``python -m agac_tpu.observability.catalog``) can never drift
from the instrumented code.
"""

from .metrics import Counter, Gauge, Histogram, MetricsRegistry, registry
from .recorder import FlightRecorder, flight_recorder
from .trace import Span, Trace, Tracer, tracer

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "FlightRecorder",
    "flight_recorder",
    "Span",
    "Trace",
    "Tracer",
    "tracer",
]
