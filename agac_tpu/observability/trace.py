"""Per-reconcile trace spans with sampled structured emission.

A reconcile's latency hides in places a single duration metric cannot
separate: queue wait, the sync body, each AWS call (and its pacing /
retry time), settle polls, and the requeue decision.  This module
gives the reconcile loop a lightweight tracer:

- ``process_next_work_item`` starts a trace per work item (sampling
  decides up front, so an unsampled item costs one integer increment);
- the trace rides a thread-local, so the driver's call proxy and the
  settle poll attach spans without any parameter plumbing
  (``record_call`` / ``span``);
- a finished sampled trace is emitted as ONE structured JSON log line
  via klog — greppable, no collector dependency.

Sampling is deterministic (every Nth trace per tracer, from the
configured rate), so tests drive it without randomness and a fleet's
sampled volume is exactly rate * traffic.  The clock is injectable;
the default reads the process clock seam (``clockseam.monotonic``),
so spans run on virtual time under the simulation runtime.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Optional

from .. import clockseam, klog


class Span:
    """One timed segment of a trace: name, [start, end) on the trace's
    clock, and a small attribute dict (op, outcome, arn, ...)."""

    __slots__ = ("name", "start", "end", "attrs")

    def __init__(self, name: str, start: float, end: float = 0.0,
                 attrs: Optional[dict] = None):
        self.name = name
        self.start = start
        self.end = end
        self.attrs = attrs or {}

    def duration(self) -> float:
        return max(0.0, self.end - self.start)

    def to_dict(self, origin: float) -> dict:
        d = {
            "name": self.name,
            "at": round(self.start - origin, 6),
            "dur": round(self.duration(), 6),
        }
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class Trace:
    """One work item's trace: controller + key + ordered spans + final
    attributes (result, error, requeue decision).  Only sampled items
    get a Trace at all — the unsampled path carries None."""

    __slots__ = ("controller", "key", "start", "end", "spans", "attrs", "_clock", "_lock")

    def __init__(self, controller: str, key: str, clock: Callable[[], float]):
        self.controller = controller
        self.key = key
        self._clock = clock
        self.start = clock()
        self.end = 0.0
        self.spans: list[Span] = []
        self.attrs: dict = {}
        self._lock = threading.Lock()

    def add_span(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)

    def annotate(self, **attrs) -> None:
        with self._lock:
            self.attrs.update(attrs)

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "controller": self.controller,
                "key": self.key,
                "dur": round(max(0.0, self.end - self.start), 6),
                "spans": [s.to_dict(self.start) for s in self.spans],
                **self.attrs,
            }


_active = threading.local()


def current() -> Optional[Trace]:
    """The thread's active trace, or None (unsampled / outside a
    reconcile) — the seam the driver hooks read."""
    return getattr(_active, "trace", None)


class _Activation:
    """Context manager installing a trace as the thread's current one.
    A None trace is a clean no-op, so call sites never branch."""

    __slots__ = ("_trace", "_prev")

    def __init__(self, trace: Optional[Trace]):
        self._trace = trace

    def __enter__(self):
        self._prev = getattr(_active, "trace", None)
        if self._trace is not None:
            _active.trace = self._trace
        return self._trace

    def __exit__(self, *exc):
        if self._trace is not None:
            _active.trace = self._prev
        return False


def activate(trace: Optional[Trace]) -> _Activation:
    return _Activation(trace)


class _SpanContext:
    """``with span("settle-poll", arn=...):`` — attaches a timed span
    to the current trace; no-op (zero allocation beyond self) when no
    trace is active."""

    __slots__ = ("_name", "_attrs", "_trace", "_start")

    def __init__(self, name: str, attrs: dict):
        self._name = name
        self._attrs = attrs
        self._trace = current()

    def __enter__(self):
        if self._trace is not None:
            self._start = self._trace._clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        trace = self._trace
        if trace is not None:
            attrs = dict(self._attrs)
            if exc is not None:
                attrs["error"] = repr(exc)
            trace.add_span(Span(self._name, self._start, trace._clock(), attrs))
        return False


def span(name: str, **attrs) -> _SpanContext:
    return _SpanContext(name, attrs)


def record_call(service: str, op: str, start: float, end: float, outcome: str) -> None:
    """Attach a completed AWS-call span to the current trace (the
    driver's instrumented handles call this with the same timestamps
    they feed the call-latency histogram)."""
    trace = current()
    if trace is None:
        return
    trace.add_span(Span(f"aws:{service}.{op}", start, end, {"outcome": outcome}))


def _default_emit(payload: dict) -> None:
    klog.infof("trace %s", json.dumps(payload, separators=(",", ":"), sort_keys=True))


class Tracer:
    """Sampling trace factory.  ``sample_rate`` in [0, 1]: 0 disables
    tracing entirely, 1 traces everything, anything between samples
    deterministically every ``round(1/rate)``-th started item (no RNG:
    reproducible in tests, exact volume in production)."""

    def __init__(
        self,
        sample_rate: float = 0.0,
        clock: Callable[[], float] = clockseam.monotonic,
        emit: Callable[[dict], None] = _default_emit,
    ):
        self._clock = clock
        self._emit = emit
        self._lock = threading.Lock()
        self._count = 0
        self.emitted_total = 0
        self.set_sample_rate(sample_rate)

    def set_sample_rate(self, rate: float) -> None:
        with self._lock:
            if rate <= 0:
                self._stride = 0
            else:
                self._stride = max(1, round(1.0 / min(rate, 1.0)))

    def sample_rate(self) -> float:
        with self._lock:
            return 0.0 if self._stride == 0 else 1.0 / self._stride

    def _should_sample(self) -> bool:
        with self._lock:
            if self._stride == 0:
                return False
            self._count += 1
            return self._count % self._stride == 0

    def start(self, controller: str, key: str, queue_wait: Optional[float] = None
              ) -> Optional[Trace]:
        """A Trace for a sampled work item, None otherwise.  The queue
        wait (known only to the workqueue) arrives as a pre-measured
        span so the trace covers the item's full queued lifetime."""
        if not self._should_sample():
            return None
        trace = Trace(controller, key, self._clock)
        if queue_wait is not None and queue_wait >= 0:
            trace.add_span(
                Span("queue-wait", trace.start - queue_wait, trace.start)
            )
        return trace

    def finish(self, trace: Optional[Trace]) -> None:
        """Close and emit a sampled trace; no-op on None.  Emission
        failures are contained — telemetry must never fail a
        reconcile."""
        if trace is None:
            return
        trace.end = trace._clock()
        try:
            self._emit(trace.to_dict())
        except Exception as err:
            klog.errorf("trace emission failed for %r: %s", trace.key, err)
        with self._lock:
            self.emitted_total += 1


# ---------------------------------------------------------------------------
# the process-global tracer, configured by --trace-sample (cmd/root.py);
# default rate 0 = tracing off (reference parity: no tracing existed)
# ---------------------------------------------------------------------------

_tracer = Tracer()


def tracer() -> Tracer:
    return _tracer


def configure(sample_rate: float) -> None:
    _tracer.set_sample_rate(sample_rate)
