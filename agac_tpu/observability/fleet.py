"""Fleet-merged metrics: one view over every shard replica's scrape.

A sharded fleet (ISSUE 8) has no single endpoint that answers "what is
the fleet's convergence p99" — each replica's ``/metrics`` carries only
its own slice of the keyspace.  This module merges N expositions into
one fleet view:

- **counters and histograms are summed** sample-by-sample (histogram
  ``_bucket``/``_sum``/``_count`` series sum like any counter, which
  is exactly how journey latency histograms aggregate across shards);
- **gauges are labeled by shard** (``shard="<identity>"`` appended) —
  summing a depth or an age across replicas would manufacture numbers
  nobody measured;
- a source that fails to scrape is skipped and NAMED in the view's
  meta (``# fleet-source-failed``) — a partial fleet view must say it
  is partial, never silently shrink.

``FleetView`` is the serving form: sources are (identity → fetcher)
callables so the same class merges live registries in-process (the
sim harness's replicas), HTTP scrapes of peer replicas
(``--fleet-peers`` → ``/metrics/fleet`` on any replica), and captured
exposition texts (the bench's sharding phase, the process drill).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from .. import klog

# metric types whose samples sum across sources; everything else
# (gauges, unknown) is labeled per shard instead
_SUMMED_TYPES = frozenset({"counter", "histogram"})


@dataclass
class Family:
    name: str
    type: str = "untyped"
    help: str = ""
    # sample name (with labels) -> value, insertion-ordered
    samples: dict[str, float] = field(default_factory=dict)


def parse_exposition(text: str) -> dict[str, Family]:
    """Prometheus text format → {family name: Family}.  Strict enough
    to catch a malformed render; sample lines before any TYPE header
    land in an untyped family."""
    families: dict[str, Family] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            families.setdefault(name, Family(name)).help = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, type_name = rest.partition(" ")
            families.setdefault(name, Family(name)).type = type_name
            continue
        if line.startswith("#"):
            continue
        sample, _, value = line.rpartition(" ")
        if not sample:
            raise ValueError(f"unparseable sample line: {line!r}")
        family_name = sample.split("{", 1)[0]
        # histogram samples (_bucket/_sum/_count) belong to the base
        # family whose TYPE header declared them
        for suffix in ("_bucket", "_sum", "_count"):
            base = family_name[: -len(suffix)] if family_name.endswith(suffix) else None
            if base and base in families and families[base].type == "histogram":
                family_name = base
                break
        family = families.setdefault(family_name, Family(family_name))
        family.samples[sample] = float(value)
    return families


def _label_sample(sample: str, extra_key: str, extra_value: str) -> str:
    """Append one label to a sample name (creating the braces when the
    sample is unlabeled)."""
    escaped = extra_value.replace("\\", "\\\\").replace('"', '\\"')
    if sample.endswith("}"):
        return f'{sample[:-1]},{extra_key}="{escaped}"}}'
    return f'{sample}{{{extra_key}="{escaped}"}}'


def merge_expositions(
    sources: dict[str, str], shard_label: str = "shard"
) -> tuple[dict[str, Family], list[str]]:
    """Merge {identity: exposition text}: counters/histograms summed,
    gauges labeled ``shard_label=identity``.  Returns (families,
    notes) where notes name type conflicts between sources."""
    merged: dict[str, Family] = {}
    notes: list[str] = []
    for identity in sorted(sources):
        for name, family in parse_exposition(sources[identity]).items():
            target = merged.get(name)
            if target is None:
                target = merged[name] = Family(name, family.type, family.help)
            elif target.type != family.type:
                notes.append(
                    f"type conflict on {name}: {target.type} vs "
                    f"{family.type} from {identity}"
                )
                continue
            if family.type in _SUMMED_TYPES:
                for sample, value in family.samples.items():
                    target.samples[sample] = target.samples.get(sample, 0.0) + value
            else:
                for sample, value in family.samples.items():
                    target.samples[
                        _label_sample(sample, shard_label, identity)
                    ] = value
    return merged, notes


def render_families(families: dict[str, Family], meta: Optional[list[str]] = None) -> str:
    """Families → exposition text (sorted, deterministic), with meta
    lines as leading comments."""
    lines = [f"# {note}" for note in (meta or [])]
    for name in sorted(families):
        family = families[name]
        lines.append(f"# HELP {family.name} {family.help}")
        lines.append(f"# TYPE {family.name} {family.type}")
        for sample in sorted(family.samples):
            value = family.samples[sample]
            if value != value:  # NaN
                rendered = "NaN"
            elif float(value).is_integer() and abs(value) < 1e15:
                rendered = str(int(value))
            else:
                rendered = repr(float(value))
            lines.append(f"{sample} {rendered}")
    return "\n".join(lines) + "\n"


class FleetView:
    """The serving form: named fetchers in, one merged exposition out.
    A fetcher raising is a partial view, named in the output meta —
    the contract every consumer (the ``/metrics/fleet`` endpoint, the
    bench, the drills) relies on during failover."""

    def __init__(self, sources: dict[str, Callable[[], str]]):
        self._sources = dict(sources)

    def add_source(self, identity: str, fetch: Callable[[], str]) -> None:
        self._sources[identity] = fetch

    def collect(self) -> tuple[dict[str, str], list[str]]:
        texts: dict[str, str] = {}
        failed: list[str] = []
        for identity, fetch in self._sources.items():
            try:
                texts[identity] = fetch()
            except Exception as err:
                failed.append(identity)
                klog.v(2).infof(
                    "fleet view: source %s failed to scrape: %s", identity, err
                )
        return texts, failed

    def render(self) -> str:
        texts, failed = self.collect()
        families, notes = merge_expositions(texts)
        meta = [f"fleet-sources: {','.join(sorted(texts)) or 'none'}"]
        for identity in failed:
            meta.append(f"fleet-source-failed: {identity}")
        meta += notes
        return render_families(families, meta=meta)


def http_fetcher(url: str, timeout: float = 5.0) -> Callable[[], str]:
    """A fetcher over a peer replica's /metrics (the --fleet-peers
    wiring)."""
    import urllib.request

    def fetch() -> str:
        with urllib.request.urlopen(url, timeout=timeout) as response:
            return response.read().decode()

    return fetch


def converge_percentiles(
    families: dict[str, Family], quantiles: tuple[float, ...] = (0.5, 0.99)
) -> dict[str, dict]:
    """Per-controller-group convergence percentiles off a (merged)
    exposition's journey histogram — the bench's ``convergence`` block
    and the fleet SLO view share this read."""
    from .slo import (
        BINDING_CONTROLLERS,
        GA_CONTROLLERS,
        RECORD_CONTROLLERS,
        estimate_quantile,
    )

    groups = {
        "ga": GA_CONTROLLERS,
        "record": RECORD_CONTROLLERS,
        "binding": BINDING_CONTROLLERS,
    }
    family = families.get("agac_journey_converge_seconds")
    out: dict[str, dict] = {}
    for group, controllers in groups.items():
        # gather cumulative buckets across the group's spec-trigger
        # series: {le: count}
        bucket_counts: dict[float, float] = {}
        total = 0.0
        if family is not None:
            for sample, value in family.samples.items():
                if 'trigger="spec"' not in sample:
                    continue
                if not any(f'controller="{c}"' in sample for c in controllers):
                    continue
                if "_bucket{" in sample:
                    le = sample.split('le="', 1)[1].split('"', 1)[0]
                    if le == "+Inf":
                        continue
                    bound = float(le)
                    bucket_counts[bound] = bucket_counts.get(bound, 0.0) + value
                elif "_count{" in sample:
                    total += value
        buckets = sorted(bucket_counts.items())
        entry = {"count": int(total)}
        for q in quantiles:
            entry[f"p{int(q * 100)}_s"] = round(
                estimate_quantile(buckets, total, q), 4
            )
        out[group] = entry
    return out
