"""The EndpointGroupBinding custom resource, v1alpha1.

Capability parity with the reference's CRD types
(``pkg/apis/endpointgroupbinding/v1alpha1/types.go:16-70``): spec binds
the load balancers of a referenced Service or Ingress into an existing
Global Accelerator endpoint group (by ARN, immutable via the
validating webhook), with optional weight and client-IP preservation;
status tracks the endpoint ids added plus ObservedGeneration.

Group/version/kind and the finalizer string are identical to the
reference (group ``operator.h3poteto.dev``, ``registry.go:22-33``;
finalizer at ``pkg/controller/endpointgroupbinding/reconcile.go:18``),
so existing manifests and stored objects are compatible.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ...cluster.objects import ObjectMeta

GROUP = "operator.h3poteto.dev"
VERSION = "v1alpha1"
KIND = "EndpointGroupBinding"
PLURAL = "endpointgroupbindings"
FINALIZER = "operator.h3poteto.dev/endpointgroupbindings"


@dataclass
class ServiceReference:
    name: str = ""


@dataclass
class IngressReference:
    name: str = ""


@dataclass
class EndpointGroupBindingSpec:
    endpoint_group_arn: str = ""
    client_ip_preservation: bool = field(
        default=False, metadata={"wire": "clientIPPreservation"}
    )
    weight: Optional[int] = None
    service_ref: Optional[ServiceReference] = None
    ingress_ref: Optional[IngressReference] = None


@dataclass
class EndpointGroupBindingStatus:
    endpoint_ids: list[str] = field(default_factory=list)
    observed_generation: int = 0


@dataclass
class EndpointGroupBinding:
    KIND = KIND
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: EndpointGroupBindingSpec = field(default_factory=EndpointGroupBindingSpec)
    status: EndpointGroupBindingStatus = field(
        default_factory=EndpointGroupBindingStatus
    )
