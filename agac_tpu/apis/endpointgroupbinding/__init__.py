from .v1alpha1 import (
    FINALIZER,
    GROUP,
    VERSION,
    EndpointGroupBinding,
    EndpointGroupBindingSpec,
    EndpointGroupBindingStatus,
    IngressReference,
    ServiceReference,
)

__all__ = [
    "GROUP",
    "VERSION",
    "FINALIZER",
    "EndpointGroupBinding",
    "EndpointGroupBindingSpec",
    "EndpointGroupBindingStatus",
    "ServiceReference",
    "IngressReference",
]
