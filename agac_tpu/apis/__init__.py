"""The annotation contract — the framework's user-facing API.

Capability parity with the reference's ``pkg/apis/type.go:3-12`` and
the annotation table in its ``README.md:232-241``: five controller
annotations plus two foreign annotations the predicates recognize.
The annotation domain is kept identical so manifests written for the
reference work unchanged against this framework.
"""

# Controller annotations (the user API)
AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/global-accelerator-managed"
)
ROUTE53_HOSTNAME_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/route53-hostname"
)
CLIENT_IP_PRESERVATION_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/client-ip-preservation"
)
AWS_GLOBAL_ACCELERATOR_NAME_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/global-accelerator-name"
)
AWS_GLOBAL_ACCELERATOR_TAGS_ANNOTATION = (
    "aws-global-accelerator-controller.h3poteto.dev/global-accelerator-tags"
)

# Foreign annotations recognized by the predicates
AWS_LOAD_BALANCER_TYPE_ANNOTATION = "service.beta.kubernetes.io/aws-load-balancer-type"
INGRESS_CLASS_ANNOTATION = "kubernetes.io/ingress.class"

# ALB listen-ports annotation consumed for listener derivation
# (reference ``pkg/cloudprovider/aws/global_accelerator.go:521``)
ALB_LISTEN_PORTS_ANNOTATION = "alb.ingress.kubernetes.io/listen-ports"
