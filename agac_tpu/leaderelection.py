"""Lease-based leader election — single-active-controller HA.

Capability parity with the reference's ``pkg/leaderelection/`` (85
LoC), which wraps client-go's LeaseLock elector: a coordination Lease
object named after the controller, uuid identity, LeaseDuration 60 s /
RenewDeadline 15 s / RetryPeriod 5 s (``leaderelection.go:61-63``),
the run callback invoked only once leadership is acquired, and
``on_stopped_leading`` fired when the lease cannot be renewed within
the renew deadline — the reference exits the process there
(``leaderelection.go:70-73``) and the CLI layer here does the same.

The elector speaks to the apiserver only through ``ClusterClient``
(Lease get/create/update with optimistic concurrency), so it runs
against both the fake and the REST client.

Clock-skew independence (client-go semantics): the holder's
``renewTime`` written by the *other* replica is never compared against
this process's wall clock.  Instead the elector records, on the local
monotonic clock, when it last *observed the lease record change*
(holder/renewTime/acquireTime/transitions tuple).  The lease is
considered live until ``observed_time + lease_duration`` on the local
clock — so a holder whose wall clock is minutes ahead or behind still
keeps its lease as long as it keeps writing, and a crashed holder is
superseded one full lease_duration after its last observed write.
This mirrors the ``observedRecord``/``observedTime`` pair in client-go's
``leaderelection.go`` (as wrapped by the reference's
``pkg/leaderelection/leaderelection.go:47-73``).
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass
from typing import Callable, Optional

from . import clockseam, klog
from .analysis import racecheck
from .cluster import ClusterClient, Lease
from .cluster.objects import LeaseSpec, ObjectMeta
from .errors import AlreadyExistsError, ConflictError, NotFoundError
from .observability import instruments


def _now_rfc3339() -> str:
    import datetime

    # through the wall-clock seam so lease timestamps are virtual
    # (and deterministic) under the sim runtime; freshness decisions
    # never read these — they use the local monotonic clock below
    return datetime.datetime.fromtimestamp(
        clockseam.time(), datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


@dataclass
class LeaderElectionConfig:
    lease_duration: float = 60.0
    renew_deadline: float = 15.0
    retry_period: float = 5.0


class LeaderElection:
    def __init__(
        self,
        name: str,
        namespace: str,
        config: Optional[LeaderElectionConfig] = None,
        identity: Optional[str] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.name = name
        self.namespace = namespace
        self.config = config or LeaderElectionConfig()
        self.identity = identity or str(uuid.uuid4())
        # optional annotations folded into every lease record this
        # elector writes (shard membership publishes its measured
        # keys-owned here for load-aware placement, ISSUE 10); None
        # (default) leaves lease metadata untouched
        self.annotation_provider: Optional[Callable[[], dict]] = None
        # the local monotonic clock all freshness decisions run on —
        # virtual under the sim runtime (ISSUE 7), where lease churn
        # plays out in virtual seconds
        self._clock = clock or clockseam.monotonic
        self._leading = threading.Event()
        # Observed-record tracking (client-go's observedRecord /
        # observedTime): the lease's last-seen content and the local
        # monotonic time at which it was first seen in that state.
        # The pair is touched from both the acquire loop and the renew
        # thread, so it gets its own (racecheck-instrumented) lock.
        self._observed_lock = racecheck.make_lock(f"leaderelection.{name}")
        self._observed_record: Optional[tuple] = None
        self._observed_time: float = 0.0
        # observability (ISSUE 5): the held gauge is a live view over
        # the leading event; takeovers count when this elector bumps
        # lease_transitions
        election_metrics = instruments.leaderelection_instruments()
        election_metrics.is_leader.labels(name=name).set_function(
            lambda: 1.0 if self._leading.is_set() else 0.0
        )
        self._m_transitions = election_metrics.transitions.labels(name=name)

    def is_leader(self) -> bool:
        return self._leading.is_set()

    def observed_holder(self) -> str:
        """The holder identity of the last lease record this elector
        observed ("" before any observation) — shard membership uses
        it to distinguish a steal from a first claim."""
        with self._observed_lock:
            if self._observed_record is None:
                return ""
            return self._observed_record[0] or ""

    def set_leading(self, leading: bool) -> None:
        """Flip the leading flag from a cooperative driver (sim
        elector actors own the acquire/renew state machine themselves;
        the threaded ``run`` path manages this flag internally)."""
        if leading:
            self._leading.set()
        else:
            self._leading.clear()

    # ------------------------------------------------------------------
    def run(
        self,
        client: ClusterClient,
        run_fn: Callable[[threading.Event], None],
        stop: threading.Event,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        on_new_leader: Optional[Callable[[str], None]] = None,
    ) -> None:
        """Block until leadership, run ``run_fn(stop)``, and keep the
        lease renewed in the background; if renewal fails past the
        renew deadline, fire ``on_stopped_leading`` (process exit in
        the CLI) and set ``stop``."""
        if not clockseam.threads_enabled():
            raise RuntimeError(
                "LeaderElection.run spawns the lease-renew thread; under "
                "the sim's cooperative executor use a _SimElector actor "
                "with set_leading() instead"
            )
        klog.infof("leader election id: %s", self.identity)
        last_reported_leader = ""
        while not stop.is_set():
            acquired, holder = self._try_acquire_or_renew(client)
            if acquired:
                break
            if holder and holder != last_reported_leader:
                last_reported_leader = holder
                klog.infof("new leader elected: %s", holder)
                if on_new_leader:
                    on_new_leader(holder)
            stop.wait(self.config.retry_period)
        if stop.is_set():
            return

        self._leading.set()
        klog.infof("successfully acquired lease %s/%s", self.namespace, self.name)

        renew_failed = threading.Event()

        def renew_loop():
            deadline = self._clock() + self.config.renew_deadline
            while not stop.is_set():
                acquired, _ = self._try_acquire_or_renew(client)
                if acquired:
                    deadline = self._clock() + self.config.renew_deadline
                elif self._clock() >= deadline:
                    klog.infof("leader lost: %s", self.identity)
                    self._leading.clear()
                    renew_failed.set()
                    stop.set()
                    if on_stopped_leading:
                        on_stopped_leading()
                    return

                stop.wait(self.config.retry_period)

        renewer = threading.Thread(target=renew_loop, daemon=True, name="lease-renew")
        renewer.start()
        try:
            run_fn(stop)
        finally:
            stop.set()
            renewer.join(timeout=self.config.retry_period + 1)
            # ReleaseOnCancel, but only AFTER the run callback has fully
            # returned: releasing earlier would let a standby start
            # reconciling while this process's workers are still
            # draining (split-brain).  No release when the lease was
            # lost — someone else already holds it.
            if not renew_failed.is_set():
                self._release(client)
            self._leading.clear()

    # ------------------------------------------------------------------
    def try_acquire_or_renew(self, client: ClusterClient) -> tuple[bool, str]:
        """One acquire-or-renew attempt, public for cooperative
        drivers (the sim runtime's elector actors step this explicitly
        instead of running the threaded loops above)."""
        acquired, holder = self._try_acquire_or_renew(client)
        try:
            from .sim import capture as capture_mod

            tap = capture_mod.active()
            if tap is not None:
                tap.record_lease_observation(
                    f"{self.namespace}/{self.name}", self.identity,
                    acquired, holder,
                )
        except Exception:
            pass  # the capture tap must never fail an election tick
        return acquired, holder

    def _try_acquire_or_renew(self, client: ClusterClient) -> tuple[bool, str]:
        """Returns (we_are_leader, current_holder)."""
        now = _now_rfc3339()
        try:
            lease = client.get("Lease", self.namespace, self.name)
        except NotFoundError:
            lease = Lease(
                metadata=ObjectMeta(
                    name=self.name, namespace=self.namespace,
                    annotations=self._annotations(),
                ),
                spec=LeaseSpec(
                    holder_identity=self.identity,
                    lease_duration_seconds=int(self.config.lease_duration),
                    acquire_time=now,
                    renew_time=now,
                    lease_transitions=0,
                ),
            )
            try:
                client.create("Lease", lease)
                return True, self.identity
            except AlreadyExistsError:
                return False, ""
        except Exception as err:
            klog.errorf("error retrieving lease %s/%s: %s", self.namespace, self.name, err)
            return False, ""

        record = (
            lease.spec.holder_identity,
            lease.spec.renew_time,
            lease.spec.acquire_time,
            lease.spec.lease_transitions,
        )
        with self._observed_lock:
            if record != self._observed_record:
                self._observed_record = record
                self._observed_time = self._clock()
            observed_time = self._observed_time

        holder = lease.spec.holder_identity or ""
        took_over = False
        if holder != self.identity:
            if holder:
                # Freshness on the LOCAL monotonic clock only: the lease
                # is live until one lease_duration after we last saw its
                # record change.  The holder's own renewTime timestamp is
                # deliberately ignored here — comparing a remote wall
                # clock to ours can elect two leaders under skew.
                duration = (
                    lease.spec.lease_duration_seconds or self.config.lease_duration
                )
                if observed_time + duration > self._clock():
                    return False, holder  # lease is held and fresh
            lease.spec.lease_transitions += 1
            lease.spec.acquire_time = now
            took_over = True
        lease.spec.holder_identity = self.identity
        lease.spec.renew_time = now
        lease.spec.lease_duration_seconds = int(self.config.lease_duration)
        annotations = self._annotations()
        if annotations:
            if lease.metadata.annotations is None:
                lease.metadata.annotations = {}
            lease.metadata.annotations.update(annotations)
        try:
            client.update("Lease", lease)
            if took_over:
                self._m_transitions.inc()
            return True, self.identity
        except (ConflictError, NotFoundError):
            return False, holder
        except Exception as err:
            klog.errorf("error updating lease: %s", err)
            return False, holder

    def _annotations(self) -> dict:
        if self.annotation_provider is None:
            return {}
        try:
            return dict(self.annotation_provider())
        except Exception:
            return {}

    def release(self, client: ClusterClient) -> None:
        """Public release for cooperative drivers (shard membership,
        the sim electors): clear the holder on clean shutdown."""
        self._release(client)

    def _release(self, client: ClusterClient) -> None:
        """ReleaseOnCancel analog: clear the holder on clean shutdown."""
        try:
            lease = client.get("Lease", self.namespace, self.name)
            if lease.spec.holder_identity == self.identity:
                lease.spec.holder_identity = None
                client.update("Lease", lease)
        except Exception:
            pass
