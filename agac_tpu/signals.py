"""Signal handling.

Capability parity with the reference's ``pkg/signals/signals.go:16-30``:
SIGINT/SIGTERM set the returned stop event; a second signal hard-exits
with code 1; installing the handler twice raises.
"""

from __future__ import annotations

import os
import signal
import threading

_installed = False


def setup_signal_handler() -> threading.Event:
    global _installed
    if _installed:
        raise RuntimeError("signal handler already installed")  # panics when called twice
    _installed = True

    stop = threading.Event()

    def handler(signum, frame):
        if stop.is_set():
            os._exit(1)  # second signal: exit directly
        # incident capture (ISSUE 19): the delivered signal is itself
        # an external input — it lands on the capture chain before the
        # post-mortems run, so a replay re-raises it at the same slot.
        # Strictly contained, like every tap.
        try:
            from .sim.capture import active

            tap = active()
            if tap is not None:
                tap.record_signal(signum)
        except Exception:
            pass
        # flight-recorder post-mortem (ISSUE 5): a terminating pod's
        # log is the one artifact the kubelet keeps, so the last
        # reconcile outcomes go there before shutdown begins.  Strictly
        # contained — telemetry must never block the stop signal.
        try:
            from .observability.recorder import flight_recorder

            flight_recorder().log_dump()
        except Exception:
            pass
        # continuous-profiling tail (ISSUE 14): whatever the sampler
        # accumulated rides out with the post-mortem — same containment.
        try:
            from .observability.stackprof import profiler

            profiler().log_top()
        except Exception:
            pass
        # blocked-on table (ISSUE 15): what the fleet was stuck on at
        # the moment of death — same containment.
        try:
            from .observability.explain import engine

            engine().log_top_blocked()
        except Exception:
            pass
        stop.set()

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)
    return stop
