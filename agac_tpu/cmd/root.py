"""CLI entry: ``controller``, ``webhook``, ``version``, ``manifests``.

Capability parity with the reference's cobra CLI (``cmd/``, 199 LoC +
``main.go``): subcommand structure, klog-style ``-v`` verbosity on the
root, kubeconfig resolution order flag → ``$KUBECONFIG`` →
``~/.kube/config`` → in-cluster (``cmd/controller/controller.go:84-98``),
``POD_NAMESPACE`` for the leader-election lease namespace
(``controller.go:55-58``), and version stamping.  ``manifests`` is the
``make manifests`` analog (the reference generates its config/ tree
with controller-gen).
"""

from __future__ import annotations

import argparse
import os
import sys

from .. import VERSION, klog

REVISION = os.environ.get("AGAC_BUILD_REVISION", "dev")
BUILD = os.environ.get("AGAC_BUILD_DATE", "unknown")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="aws-global-accelerator-controller",
        description="Manage AWS Global Accelerator and Route53 from Kubernetes",
    )
    parser.add_argument(
        "-v", "--verbosity", type=int, default=0, help="klog-style log verbosity"
    )
    sub = parser.add_subparsers(dest="command")

    controller = sub.add_parser("controller", help="Start controller")
    controller.add_argument(
        # 8, not the reference's 1: measured at N=1000 under realistic
        # AWS latency/quota shaping, 1 -> 8 workers buys ~10x
        # convergence throughput and further workers only inflate p99
        # (docs/operations.md "Sizing the worker pool")
        "-w", "--workers", type=int, default=8,
        help="Concurrent workers number for controller (reference default: 1).",
    )
    controller.add_argument(
        "-c", "--cluster-name", default="default",
        help="Owner cluster name which is used in resource tags.",
    )
    controller.add_argument(
        "--kubeconfig", default="",
        help="Path to a kubeconfig. Only required if out-of-cluster.",
    )
    controller.add_argument(
        "--master", default="",
        help="The address of the Kubernetes API server. Overrides any value in kubeconfig.",
    )
    controller.add_argument(
        "--disable-leader-election", action="store_true",
        help="Run without acquiring the leader lease (single-replica setups).",
    )
    controller.add_argument(
        "--shard-count", type=int, default=1,
        help="Horizontal sharding (ISSUE 8): partition the reconcile "
        "keyspace over N shard leases (consistent hashing on "
        "namespace/name) and run every replica concurrently — each "
        "reconciles only the keys its held shards own, with the AWS "
        "quota divided per shard. Replaces classic single-leader "
        "election. 1 (default) disables: one active leader owns "
        "everything. This is the BOOT count; the live count follows "
        "the ring lease — change it at runtime with the "
        "`resize-shards` subcommand (drain/handoff-mediated, no "
        "restart).",
    )
    controller.add_argument(
        "--shards-per-replica", type=int, default=0,
        help="Most shard leases one replica may hold (0 = no cap). "
        "Failover coverage requires (replicas-1) x shards-per-replica "
        ">= shard-count; see docs/operations.md 'Horizontal sharding' "
        "for the sizing math.",
    )
    controller.add_argument(
        "--queue-qps", type=float, default=10.0,
        help="Overall enqueue rate limit per workqueue (token bucket qps).",
    )
    controller.add_argument(
        "--queue-burst", type=int, default=100,
        help="Enqueue burst size per workqueue (token bucket capacity).",
    )
    controller.add_argument(
        "--drift-resync-period", type=float, default=0.0,
        help="Re-enqueue every managed object each N seconds so AWS-side "
        "drift (out-of-band disable/delete/record edits) is repaired "
        "without a Kubernetes object change. 0 (default) matches the "
        "reference: drift waits for an object edit.",
    )
    controller.add_argument(
        "--queue-max-backoff", type=float, default=1000.0,
        help="Cap on the per-item exponential retry backoff in seconds "
        "(client-go's default 1000 is far past useful for external-API "
        "retries; lower it to bound worst-case repair latency).",
    )
    controller.add_argument(
        "--reconcile-deadline", type=float, default=300.0,
        help="Per-item reconcile deadline in seconds: settle polls and "
        "backend retry backoffs check it and requeue with a retryable "
        "deadline error instead of wedging a worker. 0 disables "
        "(reference parity: a poll can hold a worker its full timeout).",
    )
    controller.add_argument(
        "--health-port", type=int, default=8081,
        help="Port for the manager /healthz+/readyz endpoint (circuit "
        "state + worker liveness, for deployment probes). 0 disables.",
    )
    controller.add_argument(
        "--api-health-window", type=float, default=None,
        help="Rolling classification window (seconds) of the per-service "
        "API health tracker; 0 disables the whole health plane "
        "(circuit breakers + AIMD pacing). Default 30 "
        "(env AGAC_API_HEALTH_WINDOW).",
    )
    controller.add_argument(
        "--api-health-failure-ratio", type=float, default=None,
        help="Failure ratio over the window that opens a service "
        "circuit. Default 0.5 (env AGAC_API_HEALTH_FAILURE_RATIO).",
    )
    controller.add_argument(
        "--api-health-min-calls", type=int, default=None,
        help="Minimum calls in the window before the ratio is "
        "evaluated. Default 10 (env AGAC_API_HEALTH_MIN_CALLS).",
    )
    controller.add_argument(
        "--api-health-open-duration", type=float, default=None,
        help="Seconds an open circuit rejects calls before admitting "
        "probe calls. Default 15 (env AGAC_API_HEALTH_OPEN_DURATION).",
    )
    controller.add_argument(
        "--api-health-probe-budget", type=int, default=None,
        help="Probe calls allowed per open-duration interval while "
        "half-open. Default 1 (env AGAC_API_HEALTH_PROBE_BUDGET).",
    )
    controller.add_argument(
        "--api-health-aimd-qps", type=float, default=None,
        help="Ceiling of the per-service AIMD adaptive call rate; "
        "throttle responses cut the live rate multiplicatively, "
        "successes restore it additively. 0 disables pacing (circuit "
        "breaking only). Default 20 (env AGAC_API_HEALTH_AIMD_QPS).",
    )
    controller.add_argument(
        "--gc-interval", type=float, default=0.0,
        help="Seconds between orphan-GC sweeps: cross-check every "
        "cluster-tagged accelerator and owner-TXT'd Route53 record "
        "against the apiserver and tear down confirmed orphans (a "
        "Service deleted during a controller outage is otherwise a "
        "permanent leak). 0 (default) disables — reference parity.",
    )
    controller.add_argument(
        "--gc-grace-sweeps", type=int, default=2,
        help="Consecutive sweeps an orphan must be observed before "
        "deletion; disappearing from one sweep resets the counter.",
    )
    controller.add_argument(
        "--gc-max-deletes", type=int, default=10,
        help="Per-sweep deletion budget (accelerators + record owners "
        "combined) — bounds blast radius of a mass-orphan event.",
    )
    controller.add_argument(
        "--gc-dry-run", action="store_true",
        help="GC observes and logs would-be deletions without touching "
        "AWS — the recommended first rollout step (watch the gc "
        "counters on /healthz).",
    )
    controller.add_argument(
        "--metrics-port", type=int, default=0,
        help="Serve the Prometheus /metrics exposition on a dedicated "
        "port in addition to the health server (which always carries "
        "/metrics). 0 (default) disables the dedicated listener.",
    )
    controller.add_argument(
        "--trace-sample", type=float, default=0.0,
        help="Fraction of reconciles to trace (0..1): a sampled item "
        "emits one structured JSON log line with queue-wait, sync, "
        "per-AWS-call and settle-poll spans plus the requeue decision. "
        "0 (default) disables tracing.",
    )
    controller.add_argument(
        "--profile-hz", type=float, default=0.0,
        help="Continuous sampling-profiler rate (samples/second): a "
        "daemon thread walks every thread's stack at this rate and "
        "folds the samples; the top table goes to the log on SIGTERM "
        "and /debug/profile?seconds=N serves on-demand captures. "
        "0 (default) disables the continuous sampler (on-demand "
        "captures still work).",
    )
    controller.add_argument(
        "--profile-stages", dest="profile_stages", action="store_true",
        default=True,
        help="Per-stage CPU/wall attribution for the reconcile hot "
        "path (queue-pop, shard-filter, informer-lookup, serialize, "
        "driver-mutate, settle-park, self-tax, ...), exported as "
        "agac_profile_stage_* histograms. On by default.",
    )
    controller.add_argument(
        "--no-profile-stages", dest="profile_stages",
        action="store_false",
        help="Disable the stage accountant (drops the "
        "agac_profile_stage_* attribution).",
    )
    controller.add_argument(
        "--slo-eval-interval", type=float, default=15.0,
        help="Seconds between convergence-SLO engine evaluations "
        "(journey-latency burn rates over the 5m/1h windows; sustained "
        "burn sheds GC sweeps and drift pacing before user-facing "
        "convergence degrades further). The objectives and shed "
        "doctrine are documented in docs/operations.md 'Convergence "
        "SLOs'; /slo serves the live view. 0 disables the engine.",
    )
    controller.add_argument(
        "--autoscale", action="store_true",
        help="SLO-driven shard autoscaler (ISSUE 13): close the loop "
        "from burn rate to live resize. Scales out on sustained "
        "both-window budget burn or growing oldest-unconverged-age, "
        "scales in only on sustained headroom, always through the "
        "drain/handoff resize path — railed by min/max shards, one "
        "doubling per step, per-direction cooldowns, and never while "
        "a transition is in flight. Requires --shard-count > 1 and "
        "the SLO engine (--slo-eval-interval > 0). Every decision is "
        "flight-recorded; /debug/autoscaler serves the history.",
    )
    controller.add_argument(
        "--autoscale-min-shards", type=int, default=2,
        help="Floor the autoscaler may never scale below.",
    )
    controller.add_argument(
        "--autoscale-max-shards", type=int, default=8,
        help="Ceiling the autoscaler may never scale above.",
    )
    controller.add_argument(
        "--autoscale-cooldown-out", type=float, default=120.0,
        help="Seconds after any executed resize before the next "
        "scale-OUT may fire (sized to outlast placement hysteresis "
        "and the transition itself).",
    )
    controller.add_argument(
        "--autoscale-cooldown-in", type=float, default=600.0,
        help="Seconds after any executed resize before the next "
        "scale-IN may fire (longer than scale-out: shrinking is the "
        "cheaper mistake to delay).",
    )
    controller.add_argument(
        "--autoscale-interval", type=float, default=30.0,
        help="Seconds between autoscaler evaluations.",
    )
    controller.add_argument(
        "--autoscale-observe-only", action="store_true",
        help="Evaluate and flight-record scale recommendations "
        "WITHOUT acting — the recommended first rollout step (watch "
        "/debug/autoscaler before arming).",
    )
    controller.add_argument(
        "--fleet-peers", default="",
        help="Comma-separated host:port list of the OTHER shard "
        "replicas' health endpoints. /metrics/fleet on this replica "
        "then serves the fleet-merged view (counters and journey "
        "histograms summed across replicas, gauges labeled by shard) "
        "— the one scrape that answers fleet-wide convergence SLOs "
        "under --shard-count > 1. Empty (default): the fleet view "
        "carries only this replica.",
    )
    controller.add_argument(
        "--read-plane-ttl", type=float, default=None,
        help="Tick scope (seconds) of the coalesced verification read "
        "plane: accelerator-topology, record-set and load-balancer "
        "reads are shared within one window of this length and re-read "
        "after it. Default 15; 0 disables coalescing (reference-parity "
        "per-object reads). Fine-grained knobs: AGAC_TOPOLOGY_VERIFY_TTL, "
        "AGAC_TOPOLOGY_FULL_TTL, AGAC_RECORDSET_CACHE_TTL, "
        "AGAC_LB_CACHE_TTL, AGAC_LB_BATCH_WINDOW.",
    )

    controller.add_argument(
        "--settle-poll-interval", type=float, default=None,
        help="Tick period (seconds) of the pending-settle scheduler: "
        "reconcile items parked on AWS wait states (accelerator "
        "disable→DEPLOYED settles, Route53 change-batch commits, the "
        "Route53 wait for the accelerator to exist) are re-checked in "
        "coalesced reads and requeued when resolved, instead of each "
        "holding a worker in a poll loop. Default 1 "
        "(env AGAC_SETTLE_POLL_INTERVAL); 0 disables — reference-parity "
        "blocking settle.",
    )
    controller.add_argument(
        "--r53-batch-max", type=int, default=None,
        help="Maximum changes per batched ChangeResourceRecordSets call "
        "(the API accepts up to 1,000). Default 100 "
        "(env AGAC_R53_BATCH_MAX).",
    )
    controller.add_argument(
        "--r53-batch-linger", type=float, default=None,
        help="Seconds the Route53 change batcher gathers co-submitted "
        "record mutations for the same hosted zone into one multi-change "
        "wire call. Default 0 = batching disabled (one call per "
        "mutation, reference parity); 0.1-2 s recommended at fleet "
        "scale (env AGAC_R53_BATCH_LINGER). See docs/operations.md "
        "'Async mutation pipeline'.",
    )

    controller.add_argument(
        "--capture-path", default="",
        help="Arm the incident capture (ISSUE 19): record every "
        "external input — informer deliveries, AWS call outcomes, "
        "lease observations, signals — to this bounded JSONL ring for "
        "deterministic replay (agac explain --capture / "
        "sim.replay.ReplayHarness). '%%p' expands to the PID. Default "
        "off (env AGAC_CAPTURE_PATH).",
    )
    controller.add_argument(
        "--capture-max-bytes", type=int, default=0,
        help="Incident-capture ring size: the active segment rotates "
        "to <path>.1 past this many bytes (at most two segments kept). "
        "Default 16MiB (env AGAC_CAPTURE_MAX_BYTES).",
    )

    webhook = sub.add_parser("webhook", help="Start webhook server")
    webhook.add_argument(
        "--tls-cert-file", default="",
        help="File containing the x509 Certificate for HTTPS.",
    )
    webhook.add_argument(
        "--tls-private-key-file", default="",
        help="File containing the x509 private key to --tls-cert-file.",
    )
    webhook.add_argument("--port", type=int, default=8443, help="Webhook server port.")
    webhook.add_argument(
        "--ssl", default="true", choices=["true", "false"],
        help="Webhook server use SSL.",
    )

    resize = sub.add_parser(
        "resize-shards",
        help="Live-resize a sharded fleet (ISSUE 10): CAS the new "
        "shard-count target onto the ring lease; every replica's next "
        "membership tick starts the drain/handoff transition — no "
        "restarts, no unowned keys beyond one handoff window.",
    )
    resize.add_argument(
        "-n", "--shard-count", type=int, required=True,
        help="Target shard count (the live hash ring resizes to it).",
    )
    resize.add_argument(
        "--kubeconfig", default="",
        help="Path to a kubeconfig. Only required if out-of-cluster.",
    )
    resize.add_argument(
        "--master", default="",
        help="The address of the Kubernetes API server. Overrides any "
        "value in kubeconfig.",
    )
    resize.add_argument(
        "--force", action="store_true",
        help="Supersede an in-flight transition (only when the fleet "
        "is wedged — a forced restart recomputes every replica's plan).",
    )
    resize.add_argument(
        "--dry-run", action="store_true",
        help="Print the computed transition plan (donor/gainer arcs, "
        "moved keyspace fraction) without writing the ring lease.",
    )

    explain = sub.add_parser(
        "explain",
        help="Explain why an object has not converged (ISSUE 15): query "
        "every replica's /debug/explain, let the owning shard answer, "
        "and merge — non-owners report not-owner with their ring epoch.",
    )
    explain.add_argument(
        "key",
        help="Object key as namespace/name (e.g. default/my-service).",
    )
    explain.add_argument(
        "--controller", default="",
        help="Restrict the verdict to one controller worker (e.g. "
        "'service'); default merges across all controllers.",
    )
    explain.add_argument(
        "--fleet-peers", default="127.0.0.1:8080",
        help="Comma-separated host:port health endpoints of every "
        "replica (same value as the controller's --fleet-peers). A "
        "single peer queries just that replica.",
    )
    explain.add_argument(
        "--timeout", type=float, default=3.0,
        help="Per-peer HTTP timeout in seconds.",
    )
    explain.add_argument(
        "--capture", default="",
        help="Time-machine mode (ISSUE 19): instead of querying live "
        "peers, replay this incident capture in the deterministic sim "
        "and answer from the replayed world — the verdict as of "
        "--at seconds of virtual time.",
    )
    explain.add_argument(
        "--at", type=float, default=-1.0,
        help="With --capture: the past virtual instant (seconds) to "
        "stop the replay at before asking. Default: the capture's end.",
    )

    sub.add_parser("version", help="Print the version number")

    manifests = sub.add_parser(
        "manifests", help="Generate CRD/webhook/RBAC/sample manifests"
    )
    manifests.add_argument("-o", "--output", default="config", help="Output directory.")

    return parser


def resolve_kubeconfig(flag_value: str) -> str:
    """flag → $KUBECONFIG → ~/.kube/config → "" (in-cluster)."""
    if flag_value:
        return flag_value
    env = os.environ.get("KUBECONFIG", "")
    if env:
        return env
    default = os.path.expanduser("~/.kube/config")
    if os.path.exists(default):
        return default
    return ""


def run_controller(args) -> int:
    from .. import clockseam

    if not clockseam.threads_enabled():
        # the CLI lifecycle spawns slo/autoscale/health-server threads;
        # it is the production entry point and has no sim analogue
        raise RuntimeError(
            "run_controller requires a threaded runtime "
            "(clockseam.threads_enabled() is false)"
        )
    from ..cluster.rest import build_client
    from ..controllers import (
        EndpointGroupBindingConfig,
        GarbageCollectorConfig,
        GlobalAcceleratorConfig,
        Route53Config,
    )
    from ..leaderelection import LeaderElection, LeaderElectionConfig
    from ..manager import ControllerConfig, Manager
    from ..sharding import ShardingConfig
    from ..signals import setup_signal_handler

    kubeconfig = resolve_kubeconfig(args.kubeconfig)
    if kubeconfig:
        klog.infof("Using kubeconfig: %s", kubeconfig)
    else:
        klog.info("Using in-cluster config")
    try:
        client = build_client(kubeconfig, args.master)
    except Exception as err:
        klog.errorf("Error building rest config: %s", err)
        return 1

    namespace = os.environ.get("POD_NAMESPACE") or "default"
    # lease timing env overrides: the kill-recovery / leader-failover
    # drills need sub-second takeover, production keeps the reference's
    # 60/15/5 defaults.  Shared by the single-leader lease AND the
    # per-shard leases.
    lease_defaults = LeaderElectionConfig()
    lease_config = LeaderElectionConfig(
        lease_duration=float(
            os.environ.get("AGAC_LEASE_DURATION", lease_defaults.lease_duration)
        ),
        renew_deadline=float(
            os.environ.get("AGAC_LEASE_RENEW_DEADLINE", lease_defaults.renew_deadline)
        ),
        retry_period=float(
            os.environ.get("AGAC_LEASE_RETRY_PERIOD", lease_defaults.retry_period)
        ),
    )
    queue_limits = {
        "queue_qps": args.queue_qps,
        "queue_burst": args.queue_burst,
        "queue_max_backoff": args.queue_max_backoff,
        "drift_resync_period": args.drift_resync_period,
        "reconcile_deadline": args.reconcile_deadline,
    }
    config = ControllerConfig(
        global_accelerator=GlobalAcceleratorConfig(
            workers=args.workers, cluster_name=args.cluster_name, **queue_limits
        ),
        route53=Route53Config(
            workers=args.workers, cluster_name=args.cluster_name, **queue_limits
        ),
        endpoint_group_binding=EndpointGroupBindingConfig(
            workers=args.workers, **queue_limits
        ),
        garbage_collector=GarbageCollectorConfig(
            interval=args.gc_interval,
            grace_sweeps=args.gc_grace_sweeps,
            max_deletes=args.gc_max_deletes,
            dry_run=args.gc_dry_run,
            cluster_name=args.cluster_name,
        ),
        sharding=ShardingConfig(
            shard_count=args.shard_count,
            shards_per_replica=args.shards_per_replica,
            namespace=namespace,
            lease=lease_config,
        ),
    )
    stop = setup_signal_handler()

    # the incident capture (ISSUE 19): a wall-clock tap over this
    # controller's whole external-input stream.  Armed before any
    # informer or AWS traffic so the recording starts at genesis;
    # closed at exit (the per-record flush makes a SIGKILL'd tail a
    # tolerated torn record, not a lost capture).
    capture_path = args.capture_path or os.environ.get("AGAC_CAPTURE_PATH", "")
    if capture_path:
        import atexit

        from ..sim import capture as capture_mod

        capture_path = capture_path.replace("%p", str(os.getpid()))
        max_bytes = (
            args.capture_max_bytes
            or int(os.environ.get("AGAC_CAPTURE_MAX_BYTES", "0"))
            or capture_mod.DEFAULT_MAX_BYTES
        )
        tap = capture_mod.IncidentCapture(
            capture_path, max_bytes=max_bytes,
            clock_mode="real", source="controller",
        )
        capture_mod.install(tap)
        tap.record_clock("start")
        klog.infof("incident capture armed: %s (max %d bytes)",
                   capture_path, max_bytes)

        def _close_capture():
            tap.record_clock("stop")
            capture_mod.install(None)
            tap.close()

        atexit.register(_close_capture)

    from ..cloudprovider.aws.factory import (
        configure_api_health,
        configure_pipeline,
        configure_read_plane,
        invalidate_read_plane,
        real_cloud_factory,
        settle_poll_interval,
        shared_health_tracker,
        shared_settle_table,
    )

    configure_read_plane(args.read_plane_ttl)
    configure_pipeline(
        settle_poll_interval=args.settle_poll_interval,
        r53_batch_max=args.r53_batch_max,
        r53_batch_linger=args.r53_batch_linger,
    )
    config.settle_poll_interval = settle_poll_interval()
    configure_api_health(
        window=args.api_health_window,
        failure_ratio=args.api_health_failure_ratio,
        min_calls=args.api_health_min_calls,
        open_duration=args.api_health_open_duration,
        probe_budget=args.api_health_probe_budget,
        aimd_qps=args.api_health_aimd_qps,
    )
    from ..observability import metrics as obs_metrics
    from ..observability import profile as obs_profile
    from ..observability import stackprof as obs_stackprof
    from ..observability import trace as obs_trace

    obs_trace.configure(args.trace_sample)
    obs_profile.configure(stages=args.profile_stages)
    if args.profile_hz > 0:
        # continuous sampling profiler (ISSUE 14): folds stacks in the
        # background; SIGTERM dumps the top table with the post-mortem
        obs_stackprof.configure(args.profile_hz)
        obs_stackprof.profiler().start(stop)
    tracker = shared_health_tracker()
    manager = Manager(health=tracker, metrics_registry=obs_metrics.registry())
    # reshard adoptions re-read AWS through fresh snapshots (ISSUE 8)
    manager.on_reshard = invalidate_read_plane

    import threading

    from ..manager import make_health_server
    from ..observability import fleet as obs_fleet
    from ..observability import journey as obs_journey
    from ..observability import slo as obs_slo

    if args.slo_eval_interval > 0:
        # the convergence SLO engine (ISSUE 9) over the process-global
        # journey histograms; installing it globally arms the
        # deferrable-load gates in the GC sweeper and drift tickers
        slo_engine = obs_slo.SLOEngine(
            registry=obs_metrics.registry(),
            journey_tracker=obs_journey.tracker(),
        )
        obs_slo.install_engine(slo_engine)

        def slo_loop():
            while not stop.wait(args.slo_eval_interval):
                try:
                    slo_engine.tick()
                except Exception as err:  # a bad tick must not kill the loop
                    klog.errorf("slo engine tick failed: %s", err)

        threading.Thread(target=slo_loop, daemon=True, name="slo-engine").start()

    # the fleet-merged scrape (ISSUE 9): this replica's registry plus
    # every --fleet-peers replica's /metrics
    fleet_view = obs_fleet.FleetView({"self": obs_metrics.registry().render})
    for peer in filter(None, (p.strip() for p in args.fleet_peers.split(","))):
        url = peer if peer.startswith("http") else f"http://{peer}"
        fleet_view.add_source(
            peer, obs_fleet.http_fetcher(url.rstrip("/") + "/metrics")
        )

    autoscaler = None
    if args.autoscale:
        # the shard autoscaler (ISSUE 13): burn rates + journey ages +
        # the ring-lease load board in, railed resize decisions out
        # through the same CAS path the resize-shards CLI uses
        if args.shard_count <= 1:
            klog.warning(
                "--autoscale requires --shard-count > 1; autoscaler disabled"
            )
        elif args.slo_eval_interval <= 0:
            klog.warning(
                "--autoscale requires the SLO engine "
                "(--slo-eval-interval > 0); autoscaler disabled"
            )
        else:
            from ..autoscaler import (
                AutoscalerLoop,
                ScalePolicy,
                ScalePolicyConfig,
                ScaleSignals,
            )

            def _resize_status():
                membership = manager.shard_membership
                return (
                    membership.resize_status() if membership is not None else {}
                )

            def _replica_count():
                membership = manager.shard_membership
                if membership is None:
                    return 0
                holders = membership.shard_map().get("holders", {})
                return len(set(holders.values()))

            autoscaler = AutoscalerLoop(
                ScaleSignals(
                    slo_engine=obs_slo.engine(),
                    journey_tracker=obs_journey.tracker(),
                    resize_status=_resize_status,
                    keys_by_shard=manager.keys_by_shard,
                    replica_count=_replica_count,
                    open_circuits=(
                        tracker.open_services if tracker is not None else None
                    ),
                ),
                ScalePolicy(
                    ScalePolicyConfig(
                        min_shards=args.autoscale_min_shards,
                        max_shards=args.autoscale_max_shards,
                        cooldown_out_seconds=args.autoscale_cooldown_out,
                        cooldown_in_seconds=args.autoscale_cooldown_in,
                        observe_only=args.autoscale_observe_only,
                    )
                ),
                execute=lambda target: manager.request_resize(client, target),
                registry=obs_metrics.registry(),
            )

            def autoscale_loop():
                autoscaler.run(stop, args.autoscale_interval)

            threading.Thread(
                target=autoscale_loop, daemon=True, name="autoscaler"
            ).start()

    if args.health_port > 0:
        health_server = make_health_server(
            args.health_port, health=tracker, gc_status=manager.gc_status,
            shard_status=manager.shard_status, fleet_view=fleet_view,
            queue_status=manager.queue_status,
            autoscaler_status=(
                autoscaler.status if autoscaler is not None else None
            ),
            autoscaler_history=(
                autoscaler.history if autoscaler is not None else None
            ),
        )
        threading.Thread(
            target=health_server.serve_forever, daemon=True, name="health-server"
        ).start()
    if args.metrics_port > 0 and args.metrics_port != args.health_port:
        # a dedicated scrape listener for deployments that separate
        # probe and metrics networks; same handler, same registry
        metrics_server = make_health_server(
            args.metrics_port, health=tracker, gc_status=manager.gc_status,
            shard_status=manager.shard_status, fleet_view=fleet_view,
        )
        threading.Thread(
            target=metrics_server.serve_forever, daemon=True, name="metrics-server"
        ).start()

    def run_manager(stop_event):
        manager.run(
            client, config, stop_event, cloud_factory=real_cloud_factory,
            block=True, settle_table=shared_settle_table(),
        )

    if args.shard_count > 1:
        # sharded mode (ISSUE 8): every replica runs concurrently —
        # the per-shard leases (manager's membership loop) decide who
        # works which keys, so the single-leader lease would only
        # serialize the fleet back down to one active process
        klog.infof(
            "sharded mode: %d shards, capacity %d/replica — classic "
            "leader election disabled",
            args.shard_count, args.shards_per_replica or args.shard_count,
        )
        run_manager(stop)
        return 0

    if args.disable_leader_election:
        run_manager(stop)
        return 0

    election = LeaderElection(
        "aws-global-accelerator-controller", namespace, config=lease_config
    )
    election.run(
        client,
        run_manager,
        stop,
        # lease lost: exit so the kubelet restarts us as a follower
        # (reference ``leaderelection.go:70-73``)
        on_stopped_leading=lambda: os._exit(0),
    )
    return 0


def run_resize_shards(args) -> int:
    from ..cluster.rest import build_client
    from ..sharding import HashRing, request_resize, ring_status, transition_plan

    kubeconfig = resolve_kubeconfig(args.kubeconfig)
    try:
        client = build_client(kubeconfig, args.master)
    except Exception as err:
        klog.errorf("Error building rest config: %s", err)
        return 1
    namespace = os.environ.get("POD_NAMESPACE") or "kube-system"
    try:
        status = ring_status(client, namespace=namespace)
    except Exception as err:
        print(f"resize refused: {err}", file=sys.stderr)
        return 1
    current = status["shard_count"]
    if args.shard_count == current:
        print(
            f"resize refused: the fleet is already at {current} shards "
            f"(epoch {status['epoch']}) — nothing to do",
            file=sys.stderr,
        )
        return 1
    # show the operator exactly what will move before anything acts
    if current >= 1:
        plan = transition_plan(HashRing(current), HashRing(args.shard_count))
        print(
            f"transition plan {current} -> {args.shard_count} shards: "
            f"{plan.moved_fraction:.1%} of the keyspace moves"
        )
        for donor in sorted(plan.gainers_of):
            gainers = ", ".join(
                str(gainer) for gainer in sorted(plan.gainers_of[donor])
            )
            print(f"  shard {donor} drains to shard(s) {gainers}")
    if status["in_flight"] and not args.force:
        print(
            "note: a resize transition is still in flight — the request "
            "will be refused unless --force",
        )
    if args.dry_run:
        print("dry run: ring lease not written")
        return 0
    try:
        epoch = request_resize(
            client, args.shard_count, namespace=namespace, force=args.force
        )
    except Exception as err:
        print(f"resize refused: {err}", file=sys.stderr)
        return 1
    print(
        f"resize to {args.shard_count} shards requested (epoch {epoch}); "
        "watch /healthz sharding.resize until state returns to 'stable'"
    )
    return 0


def run_explain(args) -> int:
    """Query /debug/explain across the fleet and print the merged verdict.

    Every peer is asked; the owning shard's answer wins (see
    observability.explain.merge_fleet_explains). Peers that cannot be
    reached are reported in the ``peers`` map rather than dropped, so a
    partial fleet still yields the most-blocking view of what answered.
    """
    import json
    import urllib.error
    import urllib.parse
    import urllib.request

    from ..observability import explain as obs_explain

    if getattr(args, "capture", ""):
        # time-machine mode (ISSUE 19): replay the capture to --at
        # virtual seconds and answer from the replayed world
        from ..sim.replay import ReplayHarness
        from ..sim.capture import load_capture

        capture = load_capture(args.capture)
        with ReplayHarness(capture) as rh:
            if args.at >= 0:
                rh.run_to(args.at)
            else:
                rh.run_to(float("inf"))
            answer = rh.explain(args.key, args.controller or None)
        print(json.dumps(answer, indent=2, sort_keys=True))
        return 0 if answer.get("verdict") not in ("", "no-live-stack") else 1

    peers = [p.strip() for p in args.fleet_peers.split(",") if p.strip()]
    if not peers:
        print("no --fleet-peers given", file=sys.stderr)
        return 2
    params = {"key": args.key}
    if args.controller:
        params["controller"] = args.controller
    query = urllib.parse.urlencode(params)

    answers = {}
    for peer in peers:
        url = peer if peer.startswith("http") else f"http://{peer}"
        url = url.rstrip("/") + "/debug/explain?" + query
        try:
            with urllib.request.urlopen(url, timeout=args.timeout) as resp:
                answers[peer] = json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as err:
            # 4xx still carries the JSON error contract; surface it
            try:
                answers[peer] = json.loads(err.read().decode("utf-8"))
            except Exception:
                answers[peer] = {"error": f"HTTP {err.code}"}
        except Exception as err:
            answers[peer] = {"error": str(err)}

    merged = obs_explain.merge_fleet_explains(answers)
    print(json.dumps(merged, indent=2, sort_keys=True))
    return 0 if merged.get("owner") else 1


def run_webhook(args) -> int:
    from ..webhook import Server

    use_ssl = args.ssl == "true"
    if use_ssl and (not args.tls_cert_file or not args.tls_private_key_file):
        print(
            "You must set --tls-cert-file and --tls-private-key-file when you use SSL",
            file=sys.stderr,
        )
        return 2
    Server(
        args.port,
        args.tls_cert_file if use_ssl else "",
        args.tls_private_key_file if use_ssl else "",
    )
    return 0


def run_version(_args) -> int:
    print(f"Version : {VERSION}")
    print(f"Revision: {REVISION}")
    print(f"Build   : {BUILD}")
    return 0


def run_manifests(args) -> int:
    from ..manifests import write_manifests

    for path in write_manifests(args.output):
        print(os.path.join(args.output, path))
    return 0


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    klog.init(verbosity=args.verbosity)
    if args.command == "controller":
        return run_controller(args)
    if args.command == "resize-shards":
        return run_resize_shards(args)
    if args.command == "explain":
        return run_explain(args)
    if args.command == "webhook":
        return run_webhook(args)
    if args.command == "version":
        return run_version(args)
    if args.command == "manifests":
        return run_manifests(args)
    parser.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
