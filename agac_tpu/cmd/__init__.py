from .root import main

__all__ = ["main"]
