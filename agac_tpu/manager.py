"""Controller manager: registry + lifecycle.

Capability parity with the reference's ``pkg/manager/`` (136 LoC): a
named registry of controller initializers, one shared informer factory
with a 30 s resync (``manager.go:52-53``), controllers launched in
their own threads, informers started after registration, and a join
that returns when the stop event fires.

One difference by design: a single ``ClusterClient`` serves both the
built-in kinds and the CRD (the reference needs two generated
clientsets + two informer factories; the generic cluster layer makes
that split unnecessary).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from . import klog
from .cluster import ClusterClient, SharedInformerFactory
from .controllers import (
    EndpointGroupBindingConfig,
    EndpointGroupBindingController,
    GlobalAcceleratorConfig,
    GlobalAcceleratorController,
    Route53Config,
    Route53Controller,
)
from .controllers.common import CloudFactory

INFORMER_RESYNC_PERIOD = 30.0


@dataclass
class ControllerConfig:
    global_accelerator: GlobalAcceleratorConfig = field(
        default_factory=GlobalAcceleratorConfig
    )
    route53: Route53Config = field(default_factory=Route53Config)
    endpoint_group_binding: EndpointGroupBindingConfig = field(
        default_factory=EndpointGroupBindingConfig
    )


InitFunc = Callable[
    [ClusterClient, SharedInformerFactory, ControllerConfig, Optional[CloudFactory]],
    object,
]


def new_controller_initializers() -> dict[str, InitFunc]:
    """The controller registry (reference ``manager.go:34-40``)."""
    return {
        "global-accelerator-controller": lambda client, informers, config, cloud: GlobalAcceleratorController(
            client, informers, config.global_accelerator, cloud
        ),
        "route53-controller": lambda client, informers, config, cloud: Route53Controller(
            client, informers, config.route53, cloud
        ),
        "endpoint-group-binding-controller": lambda client, informers, config, cloud: EndpointGroupBindingController(
            client, informers, config.endpoint_group_binding, cloud
        ),
    }


class Manager:
    def __init__(self, resync_period: float = INFORMER_RESYNC_PERIOD):
        self._resync_period = resync_period
        self.controllers: dict[str, object] = {}

    def run(
        self,
        client: ClusterClient,
        config: ControllerConfig,
        stop: threading.Event,
        cloud_factory: Optional[CloudFactory] = None,
        block: bool = True,
    ) -> list[threading.Thread]:
        """Start every registered controller plus the shared informers;
        with ``block=True`` (the reference's ``wg.Wait()``) returns only
        after ``stop`` fires and all controller threads exit."""
        informer_factory = SharedInformerFactory(client, self._resync_period)
        threads = []
        for name, init in new_controller_initializers().items():
            klog.infof("Starting %s", name)
            controller = init(client, informer_factory, config, cloud_factory)
            self.controllers[name] = controller
            thread = threading.Thread(
                target=controller.run, args=(stop,), daemon=True, name=name
            )
            thread.start()
            threads.append(thread)
            klog.infof("Started %s", name)

        informer_factory.start(stop)
        if block:
            stop.wait()
            for thread in threads:
                thread.join(timeout=5)
        return threads

    def drift_tick(self) -> int:
        """Drive ONE drift-resync round explicitly: walk every
        registered controller's own ``drift_resync_sources()`` — the
        same lister/predicate/enqueue triples the in-process ticker
        consumes, so an external tick can never diverge from a real
        one.  Returns the number of enqueued objects.  Used by the
        bench's drift-tick phase and the call-budget regression tier
        to bracket exactly one round."""
        enqueued = 0
        for controller in self.controllers.values():
            for lister, predicate, enqueue in controller.drift_resync_sources():
                for obj in lister.list():
                    if predicate(obj):
                        enqueue(obj)
                        enqueued += 1
        return enqueued
