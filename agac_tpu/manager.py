"""Controller manager: registry + lifecycle + the health endpoint.

Capability parity with the reference's ``pkg/manager/`` (136 LoC): a
named registry of controller initializers, one shared informer factory
with a 30 s resync (``manager.go:52-53``), controllers launched in
their own threads, informers started after registration, and a join
that returns when the stop event fires.

One difference by design: a single ``ClusterClient`` serves both the
built-in kinds and the CRD (the reference needs two generated
clientsets + two informer factories; the generic cluster layer makes
that split unnecessary).

Beyond the reference: the API health plane (ISSUE 3).  The manager
optionally carries a ``HealthTracker``; ``drift_tick`` skips
controllers whose backing service circuits are open (marking the tick
partial instead of issuing verify reads into an outage), shutdown
names the reconcile key any straggler thread is wedged on, a watchdog
surfaces stuck workers, and ``make_health_server`` serves
``/healthz`` + ``/readyz`` (stdlib server, same pattern as
``webhook/server.py``) reporting per-circuit state and worker
liveness for deployment probes.

And the crash-recovery plane (ISSUE 4): when
``ControllerConfig.garbage_collector.interval > 0`` the manager runs
the orphan GC sweeper (``controllers/garbagecollector.py``) on its own
daemon thread, sharing the controllers' informer caches and cloud
factory; ``gc_sweep()`` drives one sweep explicitly (bench/tests, the
``drift_tick`` pattern) and ``/healthz`` carries ``gc_status()``.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from . import clockseam, klog
from .cloudprovider.aws import health as api_health
from .cluster import ClusterClient, SharedInformerFactory
from .observability import fleet as obs_fleet
from .observability import journey as obs_journey
from .observability import metrics as obs_metrics
from .observability import profile as obs_profile
from .observability import recorder as obs_recorder
from .observability import stackprof as obs_stackprof
from .observability import slo as obs_slo
from .observability import explain as obs_explain
from .controllers import (
    EndpointGroupBindingConfig,
    EndpointGroupBindingController,
    GarbageCollector,
    GarbageCollectorConfig,
    GlobalAcceleratorConfig,
    GlobalAcceleratorController,
    Route53Config,
    Route53Controller,
)
from .controllers.common import CloudFactory
from .observability import instruments as obs_instruments
from .sharding import OWNS_ALL, ShardMembership, ShardingConfig
from .sharding.reports import merge_shard_reports

INFORMER_RESYNC_PERIOD = 30.0

# a worker on one reconcile key longer than this is "stuck" for the
# watchdog and /healthz (a healthy reconcile is seconds; the longest
# legitimate hold is the 180 s settle poll)
WORKER_STUCK_THRESHOLD = 300.0


@dataclass
class ControllerConfig:
    global_accelerator: GlobalAcceleratorConfig = field(
        default_factory=GlobalAcceleratorConfig
    )
    route53: Route53Config = field(default_factory=Route53Config)
    endpoint_group_binding: EndpointGroupBindingConfig = field(
        default_factory=EndpointGroupBindingConfig
    )
    # the orphan GC sweeper (ISSUE 4); interval 0 (default) disables
    garbage_collector: GarbageCollectorConfig = field(
        default_factory=GarbageCollectorConfig
    )
    # poll-tick period of the pending-settle scheduler (ISSUE 6): how
    # often parked reconcile items (accelerator settles, change-batch
    # commits, cross-controller waits) are re-checked in coalesced
    # reads and requeued.  Only takes effect when a settle table is
    # passed to Manager.run; the checks are cheap (one coalesced list
    # + in-memory peeks), so 1 s keeps resolve latency ~1 tick.
    settle_poll_interval: float = 1.0
    # the horizontal sharding plane (ISSUE 8): shard_count > 1 runs
    # this replica as one of several concurrently-live controllers,
    # each owning the keys its shard leases cover
    sharding: ShardingConfig = field(default_factory=ShardingConfig)


InitFunc = Callable[
    [
        ClusterClient,
        SharedInformerFactory,
        ControllerConfig,
        Optional[CloudFactory],
        object,  # shard filter (sharding.ShardFilter)
    ],
    object,
]


def new_controller_initializers() -> dict[str, InitFunc]:
    """The controller registry (reference ``manager.go:34-40``)."""
    return {
        "global-accelerator-controller": lambda client, informers, config, cloud, shards: GlobalAcceleratorController(
            client, informers, config.global_accelerator, cloud, shard_filter=shards
        ),
        "route53-controller": lambda client, informers, config, cloud, shards: Route53Controller(
            client, informers, config.route53, cloud, shard_filter=shards
        ),
        "endpoint-group-binding-controller": lambda client, informers, config, cloud, shards: EndpointGroupBindingController(
            client, informers, config.endpoint_group_binding, cloud, shard_filter=shards
        ),
    }


class Manager:
    def __init__(
        self,
        resync_period: float = INFORMER_RESYNC_PERIOD,
        health: Optional["api_health.HealthTracker"] = None,
        heartbeats: Optional["api_health.WorkerHeartbeats"] = None,
        metrics_registry: Optional["obs_metrics.MetricsRegistry"] = None,
    ):
        self._resync_period = resync_period
        self._health = health
        self.heartbeats = heartbeats or api_health.worker_heartbeats()
        # the registry the GC sweeper's counters land in (ISSUE 5);
        # None keeps a private one per manager (unit tiers build many
        # managers per process), cmd/root and the bench pass the
        # process-global registry so /metrics carries the gc series
        self.metrics_registry = (
            metrics_registry
            if metrics_registry is not None
            else obs_metrics.MetricsRegistry()
        )
        self.controllers: dict[str, object] = {}
        # the shared informer factory build() wired (None until then)
        self.informer_factory: Optional[SharedInformerFactory] = None
        # per-shard drift reports keyed by ownership token ("all" in
        # single-shard mode); the legacy ``last_drift_report`` view
        # merges them additively so a second shard's tick can never
        # silently overwrite the first (the single-owner-merge fix,
        # ISSUE 8)
        self.last_drift_reports: dict[str, dict] = {}
        # the sharding plane (ISSUE 8), built by build() when
        # config.sharding.shard_count > 1; the filter defaults to
        # owns-everything single-shard semantics
        self.shard_membership: Optional[ShardMembership] = None
        self.shard_filter = OWNS_ALL
        # set by the membership on-change hook; the shard loop performs
        # the adopted-key resync once informers are synced
        self._reshard_pending = False
        # read-plane invalidation hook, called before every reshard
        # resync: the adopted keyspace was written by ANOTHER process,
        # so every local snapshot (discovery, topology, record sets,
        # zones) is suspect — reconciling adopted keys through a stale
        # cache creates DUPLICATE accelerators.  Wired by cmd/root
        # (factory caches) and the sim harness (per-replica world).
        self.on_reshard: Optional[Callable[[], None]] = None
        # the orphan GC sweeper (ISSUE 4), built by run() when its
        # interval is > 0; None = disabled (reference parity)
        self.gc: Optional[GarbageCollector] = None
        # the pending-settle table (ISSUE 6) the run() caller wired;
        # None = blocking-settle parity.  settle_tick() drives one
        # scheduler round explicitly (tests/bench, the drift_tick
        # pattern).
        self.settle_table = None
        # the explain plane (ISSUE 15), built by build()
        self.explain_engine: Optional[obs_explain.ExplainEngine] = None

    def build(
        self,
        client: ClusterClient,
        config: ControllerConfig,
        cloud_factory: Optional[CloudFactory] = None,
        informer_factory: Optional[SharedInformerFactory] = None,
    ) -> SharedInformerFactory:
        """Construct every registered controller (and the GC sweeper
        when enabled) WITHOUT starting any thread.  ``run`` wraps this
        with the threaded lifecycle; the deterministic sim harness
        (``agac_tpu/sim/``) calls it directly and steps the same
        controller objects cooperatively on virtual time — the two
        runtimes can never drift apart on what a manager contains."""
        informer_factory = informer_factory or SharedInformerFactory(
            client, self._resync_period
        )
        self.informer_factory = informer_factory
        if config.sharding.enabled:
            # the membership must exist BEFORE the controllers: their
            # informer handlers consult the filter from the first
            # delivered event
            self.shard_membership = ShardMembership(
                config.sharding,
                identity=config.sharding.identity or None,
                registry=self.metrics_registry,
                on_change=self._on_shard_change,
            )
            # entering a resize transition re-divides quota (the
            # denominator grew to max(from, to)) without the full
            # handoff resync an ownership change triggers
            self.shard_membership.on_quota_change = self._on_shard_quota_change
            # load-aware placement input (ISSUE 10): measured managed
            # keys per shard under the live ring
            self.shard_membership.fleet_key_counts = self._count_keys_by_shard
            self.shard_filter = self.shard_membership.filter
            obs_instruments.sharding_instruments(
                self.metrics_registry
            ).keys_owned.set_function(self._count_owned_keys)
            if self._health is not None:
                # budget follows ownership from the very start: a
                # replica that has not acquired any shard yet paces at
                # the floor, not the whole global budget
                self._health.set_quota_fraction(0.0)
        for name, init in new_controller_initializers().items():
            self.controllers[name] = init(
                client, informer_factory, config, cloud_factory,
                self.shard_filter,
            )
        # the explain plane (ISSUE 15): one engine per manager, wired
        # to every plane the blocked-on classification consults; the
        # settle table is late-bound (run()/the sim harness attach it
        # after build), hence the lambda
        self.explain_engine = obs_explain.ExplainEngine(
            identity=(
                self.shard_membership.identity
                if self.shard_membership is not None
                else (config.sharding.identity or "")
            ),
            settle_table=lambda: self.settle_table,
            health=self._health,
            shard_filter=lambda: self.shard_filter,
            resize_status=self._resize_status,
            informers_synced=self._informers_synced,
            slo_shedding=self._slo_shedding,
        )
        self.explain_engine.bind_metrics(self.metrics_registry)
        for controller in self.controllers.values():
            for spec in controller.worker_specs():
                self.explain_engine.register_worker(
                    spec["name"], spec["queue"], spec["key_to_obj"],
                    managed=spec.get("managed"),
                )
        gc_config = config.garbage_collector
        if gc_config.interval > 0 and cloud_factory is not None:
            # the sweeper shares the controllers' informer caches (its
            # owner cross-checks must see the same world the reconciles
            # do) and the same cloud factory (deletes flow through the
            # shaped drivers); it never sweeps before those caches sync
            self.gc = GarbageCollector(
                informer_factory, gc_config, cloud_factory, health=self._health,
                registry=self.metrics_registry,
                shard_filter=self.shard_filter,
            )
        return informer_factory

    def run(
        self,
        client: ClusterClient,
        config: ControllerConfig,
        stop: threading.Event,
        cloud_factory: Optional[CloudFactory] = None,
        block: bool = True,
        settle_table=None,
    ) -> list[threading.Thread]:
        """Start every registered controller plus the shared informers;
        with ``block=True`` (the reference's ``wg.Wait()``) returns only
        after ``stop`` fires and all controller threads exit."""
        if not clockseam.threads_enabled():
            raise RuntimeError(
                "Manager.run spawns controller/gc/shard threads; under "
                "the sim's cooperative executor call build() and step "
                "the worker specs explicitly"
            )
        informer_factory = self.build(client, config, cloud_factory)
        # the threaded (production) lifecycle owns the process: its
        # engine becomes the global one the reconcile loop's recorder
        # stamps and the default /debug/explain lookup resolve.  The
        # sim harness calls build() directly and reads each replica's
        # own engine instead.
        obs_explain.install(self.explain_engine)
        threads = []
        for name, controller in self.controllers.items():
            klog.infof("Starting %s", name)
            thread = threading.Thread(
                target=controller.run, args=(stop,), daemon=True, name=name
            )
            thread.start()
            threads.append(thread)
            klog.infof("Started %s", name)

        if self.gc is not None:
            threading.Thread(
                target=self.gc.run, args=(stop,), daemon=True,
                name="garbage-collector",
            ).start()

        if self.shard_membership is not None:
            # the sharding plane's lease loop (ISSUE 8): every replica
            # runs it concurrently — shard leases, not the single
            # leader lease, decide who works which keys
            threading.Thread(
                target=self._shard_loop, args=(client, stop), daemon=True,
                name="shard-membership",
            ).start()

        if settle_table is not None and config.settle_poll_interval > 0:
            # the async mutation pipeline's poll tick (ISSUE 6):
            # re-checks every parked reconcile item in coalesced reads
            # and requeues resolved/expired waits
            from .reconcile.pending import SettleScheduler

            self.settle_table = settle_table
            SettleScheduler(
                settle_table, interval=config.settle_poll_interval
            ).start(stop)

        informer_factory.start(stop)
        api_health.start_worker_watchdog(stop, self.heartbeats)
        if block:
            stop.wait()
            for thread in threads:
                thread.join(timeout=5)
            self._log_stragglers(threads)
        return threads

    def _log_stragglers(self, threads: list[threading.Thread]) -> None:
        """Name every controller thread that failed to join, plus the
        reconcile key any of its workers is wedged on (heartbeat
        table) — a silently leaked straggler made wedged shutdowns
        undiagnosable."""
        for thread in threads:
            if not thread.is_alive():
                continue
            wedged = [
                f"{worker} on {info['key']!r} for {info['age']:.0f}s"
                for worker, info in self.heartbeats.snapshot().items()
                if worker.startswith(thread.name)
            ]
            klog.errorf(
                "controller thread %s failed to join within 5s%s",
                thread.name,
                f"; busy workers: {', '.join(wedged)}" if wedged else "",
            )

    # ------------------------------------------------------------------
    # sharding plane (ISSUE 8)
    # ------------------------------------------------------------------
    def _on_shard_change(self, membership: ShardMembership) -> None:
        """Membership hook: quota follows ownership immediately; the
        adopted-key resync is deferred to the shard loop (it needs
        synced informer caches to enumerate)."""
        if self._health is not None:
            self._health.set_quota_fraction(membership.quota_fraction())
        self._reshard_pending = True
        obs_recorder.flight_recorder().record(
            "shard-rebalance",
            owned=sorted(membership.owned_shards()),
            quota_fraction=round(membership.quota_fraction(), 4),
        )

    def _on_shard_quota_change(self, membership: ShardMembership) -> None:
        """A resize transition began: the quota denominator moved but
        no shard changed hands — re-divide without the full handoff
        resync."""
        if self._health is not None:
            self._health.set_quota_fraction(membership.quota_fraction())
        obs_recorder.flight_recorder().record(
            "shard-resize",
            state=membership.resize_status().get("state"),
            epoch=membership.resize_epoch,
            quota_fraction=round(membership.quota_fraction(), 4),
        )

    def shard_tick(self, client: ClusterClient) -> bool:
        """One membership round plus (when ownership changed and the
        informer caches are synced) the adopted-keyspace resync — the
        cooperative entry point the threaded loop AND the sim harness
        both drive, so the two runtimes cannot diverge on failover
        semantics.  Returns True when the owned-shard set changed.

        During a live resize (ISSUE 10) the tick also drives this
        replica's side of the drain/handoff protocol: shards adopted
        this round get their moved keys resynced (journeys stamped
        ``trigger=resize``) and the handoff ack is written only AFTER
        that resync ran — the marker in the lease record is the
        protocol's statement that the new owner is actually serving."""
        if self.shard_membership is None:
            return False
        changed = self.shard_membership.tick(client)
        if self.shard_membership.resync_pending() and self._informers_synced():
            moved = self.shard_membership.moved_key_predicate()
            if self.on_reshard is not None:
                # the gained keys were written by other processes:
                # every local snapshot is suspect (duplicate-accelerator
                # hazard, same as a failover adoption)
                self.on_reshard()
            enqueued = self._resync_sources(
                trigger=obs_journey.TRIGGER_RESIZE,
                key_predicate=moved,
            )
            klog.infof(
                "resize resync: re-enqueued %d re-homed keys for shards %s",
                enqueued, self.shard_filter.token(),
            )
            self.shard_membership.ack_adoptions(client)
        if self._reshard_pending and self._informers_synced():
            self._reshard_pending = False
            self.reshard_resync()
        return changed

    def request_resize(self, client: ClusterClient, target_count: int) -> int:
        """CAS the fleet's live shard-count target onto the ring lease
        (the ``resize-shards`` CLI calls the module function directly;
        this is the embedded/test entry point)."""
        from .sharding import request_resize as _request_resize

        membership = self.shard_membership
        if membership is None:
            raise RuntimeError("sharding is not enabled on this manager")
        return _request_resize(
            client, target_count,
            namespace=membership.config.namespace,
            lease_prefix=membership.config.lease_prefix,
            vnodes=membership.config.vnodes,
        )

    def _resize_status(self) -> dict:
        """The live resize view the explain engine stamps verdicts
        with ({} in single-shard mode)."""
        if self.shard_membership is None:
            return {}
        return self.shard_membership.resize_status()

    @staticmethod
    def _slo_shedding() -> bool:
        """True while the SLO engine is actively shedding deferrable
        load — read from the attribute, NOT ``should_shed`` (that gate
        counts a shed action; an explain lookup must not)."""
        slo_engine = obs_slo.engine()
        return bool(slo_engine is not None and slo_engine.shedding)

    def _informers_synced(self) -> bool:
        if self.informer_factory is None:
            return False
        return all(
            informer.has_synced()
            for informer in self.informer_factory.informers()
        )

    def reshard_resync(self) -> int:
        """Re-enqueue every managed object this replica's shards now
        own — the level-triggered adoption path after a lease steal or
        first acquisition (informer events never replay for keys whose
        events were consumed by a dead replica).  The controllers' own
        drift sources carry the shard predicate, so this can never
        enqueue foreign keys."""
        if self.on_reshard is not None:
            # fresh reads for an adopted keyspace: another process
            # wrote it, local snapshots would ensure duplicates
            self.on_reshard()
        # journeys opened by this resync are HANDOFF-triggered: the
        # adopted keys' convergence latency is failover cost, not a
        # spec edit's, and the SLO plane separates the two
        enqueued = self._resync_sources(trigger=obs_journey.TRIGGER_HANDOFF)
        klog.infof(
            "shard resync: re-enqueued %d keys for shards %s",
            enqueued, self.shard_filter.token(),
        )
        return enqueued

    def _resync_sources(
        self, trigger: str, key_predicate=None
    ) -> int:
        """Walk every controller's canonical drift sources, enqueueing
        owned objects (optionally narrowed by ``key_predicate`` over
        the ``namespace/name`` key — the resize resync only re-homes
        MOVED keys)."""
        from .cluster.objects import meta_namespace_key

        enqueued = 0
        for controller in self.controllers.values():
            for lister, predicate, enqueue in controller.drift_resync_sources(
                trigger=trigger
            ):
                for obj in lister.list():
                    if not predicate(obj):
                        continue
                    if key_predicate is not None and not key_predicate(
                        meta_namespace_key(obj)
                    ):
                        continue
                    enqueue(obj)
                    enqueued += 1
        return enqueued

    def _shard_loop(self, client: ClusterClient, stop: threading.Event) -> None:
        membership = self.shard_membership
        klog.infof(
            "Starting shard membership (identity %s, %d shards, capacity %d)",
            membership.identity, membership.shard_count,
            membership.capacity(),
        )
        while not stop.is_set():
            try:
                self.shard_tick(client)
            except Exception as err:  # a bad tick must not kill the loop
                klog.errorf("shard tick failed: %s", err)
            stop.wait(membership.config.lease.retry_period)
        membership.release_all(client)
        klog.info("Shutting down shard membership")

    def shard_status(self) -> dict:
        """Shard assignment for ``/healthz``: which leases this replica
        holds, the observed map, and its quota slice."""
        if self.shard_membership is None:
            return {"enabled": False}
        status = {"enabled": True}
        status.update(self.shard_membership.shard_map())
        status["quota_fraction"] = round(
            self.shard_membership.quota_fraction(), 4
        )
        status["keys_owned"] = self._count_owned_keys()
        # elastic resharding (ISSUE 10): ring version, resize state
        # (stable/draining/adopting) and per-shard handoff progress
        status["resize"] = self.shard_membership.resize_status()
        return status

    def _count_owned_keys(self) -> int:
        """Managed Services + Ingresses owned by this replica's shards
        (the ``agac_shard_keys_owned`` gauge's collection-time view)."""
        if self.informer_factory is None:
            return 0
        from .controllers.globalaccelerator import (
            is_managed_ingress,
            is_managed_service,
        )

        count = 0
        try:
            for obj in self.informer_factory.informer("Service").lister().list():
                if is_managed_service(obj) and self.shard_filter.owns_obj(obj):
                    count += 1
            for obj in self.informer_factory.informer("Ingress").lister().list():
                if is_managed_ingress(obj) and self.shard_filter.owns_obj(obj):
                    count += 1
        except Exception:
            return count
        return count

    def _count_keys_by_shard(self) -> dict[int, int]:
        """Managed keys per shard under the LIVE ring — the measured
        load the membership's preferred-owner placement scores claims
        and sheds by (ISSUE 10).  Counts the whole fleet (not only
        owned shards): a claim decision needs the weight of shards
        this replica does NOT hold yet."""
        if self.informer_factory is None or self.shard_membership is None:
            return {}
        from .cluster.objects import meta_namespace_key
        from .controllers.globalaccelerator import (
            is_managed_ingress,
            is_managed_service,
        )

        ring = self.shard_membership.ring
        counts: dict[int, int] = {}
        try:
            for obj in self.informer_factory.informer("Service").lister().list():
                if is_managed_service(obj):
                    shard = ring.shard_for_key(meta_namespace_key(obj))
                    counts[shard] = counts.get(shard, 0) + 1
            for obj in self.informer_factory.informer("Ingress").lister().list():
                if is_managed_ingress(obj):
                    shard = ring.shard_for_key(meta_namespace_key(obj))
                    counts[shard] = counts.get(shard, 0) + 1
        except Exception:
            return counts
        return counts

    def keys_by_shard(self) -> dict[int, int]:
        """The per-shard managed-key census under the live ring, as a
        documented public accessor — the autoscaler's load-board
        signal (ISSUE 13) reads this instead of reaching into the
        placement internals.  Empty when sharding is disabled."""
        return self._count_keys_by_shard()

    def drift_tick(self) -> int:
        """Drive ONE drift-resync round explicitly: walk every
        registered controller's own ``drift_resync_sources()`` — the
        same lister/predicate/enqueue triples the in-process ticker
        consumes, so an external tick can never diverge from a real
        one.  Returns the number of enqueued objects.  Used by the
        bench's drift-tick phase and the call-budget regression tier
        to bracket exactly one round.

        Degraded mode (health plane): a controller whose
        ``DRIFT_SERVICES`` include an open circuit is skipped — its
        verify reads would only feed the outage — and the tick is
        marked partial in ``last_drift_report`` (exported into
        bench_detail.json), so a stale verify round is visibly stale
        rather than silently incomplete."""
        report: dict = {
            # the shard-ownership token this (possibly partial) tick
            # covered — "all" in single-shard mode
            "shards": self.shard_filter.token(),
            "enqueued": {},
            "skipped": {},
            "partial": False,
        }
        if obs_slo.should_shed("drift-tick"):
            # burn-rate shedding (ISSUE 9): drift verification is
            # deferrable — while the convergence budget burns, the
            # tick is skipped and says so instead of adding load
            report["shed"] = True
            report["partial"] = True
            self.last_drift_reports[report["shards"]] = report
            obs_recorder.flight_recorder().record(
                "drift-tick", shards=report["shards"], shed=True
            )
            klog.warningf("drift tick: shed under SLO budget burn")
            return 0
        enqueued = 0
        # the fleet-enumeration cost of a verify round, attributed as
        # its own stage (ISSUE 14) — the tick runs outside any
        # reconcile scope, so it flushes immediately under "manager"
        with obs_profile.stage("drift-tick"):
            for name, controller in self.controllers.items():
                open_services = (
                    [
                        service
                        for service in getattr(controller, "DRIFT_SERVICES", ())
                        if self._health.is_open(service)
                    ]
                    if self._health is not None
                    else []
                )
                if open_services:
                    report["skipped"][name] = open_services
                    report["partial"] = True
                    klog.warningf(
                        "drift tick: skipping %s (open circuits: %s)",
                        name, ", ".join(open_services),
                    )
                    continue
                count = 0
                for lister, predicate, enqueue in controller.drift_resync_sources():
                    for obj in lister.list():
                        if predicate(obj):
                            enqueue(obj)
                            count += 1
                report["enqueued"][name] = count
                enqueued += count
        self.last_drift_reports[report["shards"]] = report
        obs_recorder.flight_recorder().record(
            "drift-tick",
            shards=report["shards"],
            enqueued=dict(report["enqueued"]),
            skipped=dict(report["skipped"]),
            partial=report["partial"],
        )
        return enqueued

    @property
    def last_drift_report(self) -> dict:
        """The legacy single-report view: an additive merge over the
        per-shard partials stored in ``last_drift_reports`` (identical
        to the raw report while one replica covers the whole
        keyspace)."""
        return merge_shard_reports(self.last_drift_reports)

    def settle_tick(self) -> dict:
        """Drive ONE pending-settle poll round explicitly (tests and
        the bench; same pattern as ``drift_tick``).  No-op when no
        settle table is wired."""
        if self.settle_table is None:
            return {}
        return self.settle_table.poll_once()

    def settle_status(self) -> dict:
        """Pending-settle depth/age counters for ``/healthz`` and
        bench_detail."""
        if self.settle_table is None:
            return {"enabled": False}
        return self.settle_table.stats()

    def gc_sweep(self) -> dict:
        """Drive ONE orphan-GC sweep explicitly (tests and the bench's
        gc-sweep phase; same pattern as ``drift_tick``).  No-op when
        the sweeper is disabled."""
        if self.gc is None:
            return {}
        with obs_profile.stage("gc-sweep"):
            return self.gc.sweep_once()

    def gc_status(self) -> dict:
        """The sweeper's counters for ``/healthz`` and bench_detail:
        cumulative totals, pending (grace-held) depths, and the last
        sweep's full report."""
        if self.gc is None:
            return {"enabled": False}
        return self.gc.status()

    def queue_status(self) -> dict:
        """Every controller queue's live internals (ready depth, items
        being processed, parked delays and the next delay's maturity)
        — the ``/debug/queues`` view that makes a wedged or
        delay-parked queue diagnosable from the outside."""
        status: dict = {}
        for controller in self.controllers.values():
            for spec in controller.worker_specs():
                queue = spec["queue"]
                status[spec["name"]] = queue.debug_status()
        return status


# ---------------------------------------------------------------------------
# /healthz + /readyz (stdlib server, the webhook/server.py pattern)
# ---------------------------------------------------------------------------


class _HealthHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # probes arrive every few seconds from the kubelet: verbose level
    # from day one (the webhook's healthz flooded logs at info)
    def log_message(self, fmt, *args):
        klog.v(4).infof("health http: " + fmt, *args)

    def do_GET(self):
        # every endpoint dispatches on the bare path through one route
        # table (ISSUE 15 satellite) with one shared query parser and a
        # uniform JSON error contract: unknown path → 404 JSON naming
        # the known endpoints, bad query → 400 JSON with "error"
        path, _, raw_query = self.path.partition("?")
        handler = self._ROUTES.get(path)
        if handler is None:
            self._respond(404, {
                "error": f"no such endpoint: {path}",
                "endpoints": sorted(self._ROUTES),
            })
            return
        handler(self, _parse_query(raw_query))

    def _healthz(self, query=None):
        """Process liveness: 200 unless a worker is stuck past the
        threshold (a wedged worker pool deserves a kubelet restart —
        state is all external, restart-resume is proven by the
        resilience tier)."""
        klog.v(4).infof("healthz")
        stuck = self.server.heartbeats.stuck(self.server.stuck_threshold)
        body = {
            "workers": self.server.heartbeats.snapshot(),
            "stuck": [
                {"worker": worker, "key": key, "age": round(age, 1)}
                for worker, key, age in stuck
            ],
            # orphan-GC sweep status (ISSUE 4): operators watching a
            # dry-run rollout read would-delete counts here instead of
            # grepping logs
            "gc": self.server.gc_status(),
            # shard assignment (ISSUE 8): which shard leases this
            # replica holds, the observed map, and its quota slice
            "sharding": self.server.shard_status(),
            # convergence SLO summary (ISSUE 9): burn rates + shed
            # state — the block the rollout/federation gates read;
            # the full view (objectives, slowest journeys) is /slo
            "slo": self.server.slo_status(),
            # shard autoscaler (ISSUE 13): rail/knob settings and the
            # last decision; full history is /debug/autoscaler
            "autoscaler": self.server.autoscaler_status(),
        }
        self._respond(500 if stuck else 200, body)

    def _readyz(self, query=None):
        """Readiness: 503 while any API circuit is open — the pod is
        alive but degraded, and deployment probes/rollouts should see
        that without scraping logs."""
        klog.v(4).infof("readyz")
        tracker = self.server.health_tracker
        open_services = tracker.open_services() if tracker is not None else []
        body = {
            "open_circuits": open_services,
            "services": tracker.snapshot() if tracker is not None else {},
        }
        self._respond(503 if open_services else 200, body)

    def _metrics(self, query=None):
        """Prometheus text exposition of the wired registry (ISSUE 5):
        the scrape endpoint operators point their Prometheus at."""
        payload = self.server.metrics_registry.render().encode()
        self.send_response(200)
        self.send_header("Content-Type", obs_metrics.CONTENT_TYPE)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _slo(self, query=None):
        """The convergence SLO plane in full (ISSUE 9): declared
        objectives with burn rates and quantile estimates, shed state,
        and the slowest unconverged journeys (each id greps straight
        into /debug/flightrecorder)."""
        self._respond(200, self.server.slo_status())

    def _fleet_metrics(self, query=None):
        """The fleet-merged exposition (ISSUE 9): this replica's
        registry plus every configured peer's /metrics — counters and
        journey histograms summed, gauges labeled by shard.  A peer
        that fails to scrape is named in the leading meta comments,
        never silently dropped."""
        payload = self.server.fleet_view.render().encode()
        self.send_response(200)
        self.send_header("Content-Type", obs_metrics.CONTENT_TYPE)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _flightrecorder(self, query=None):
        """The flight recorder's ring buffer, oldest → newest — the
        live post-mortem of the last few hundred reconcile outcomes.
        The active incident capture's cursor (ISSUE 19) rides along,
        naming the replayable artifact this window corresponds to."""
        recorder = self.server.flight_recorder
        body = {
            "capacity": recorder.capacity,
            "recorded_total": recorder.recorded_total,
            "entries": recorder.dump(),
        }
        try:
            from .sim.capture import active as _capture_active

            tap = _capture_active()
            if tap is not None:
                body["capture_cursor"] = tap.cursor()
        except Exception:
            pass
        self._respond(200, body)

    def _queues(self, query=None):
        self._respond(200, self.server.queue_status())

    def _autoscaler(self, query=None):
        """The autoscaler's bounded decision history, oldest → newest,
        each entry carrying the full evidence snapshot the policy saw
        — suppressed decisions included (a quiet autoscaler should be
        explainably quiet)."""
        self._respond(
            200,
            {
                "status": self.server.autoscaler_status(),
                "decisions": self.server.autoscaler_history(),
            },
        )

    def _profile(self, query):
        """On-demand sampling-profiler capture (ISSUE 14):
        ``?seconds=N`` samples the live process for N seconds (bounded
        by the profiler) and returns the folded stacks plus the ranked
        top table; ``&format=folded`` returns the flamegraph-ready
        text instead of JSON.  The stage accountant's cumulative
        attribution table rides along so one curl answers both "where
        is wall time going right now" and "where has CPU gone since
        start"."""
        try:
            seconds = float(query.get("seconds", "1"))
            hz = float(query.get("hz", "0")) or None
        except ValueError:
            self._respond(400, {"error": "seconds/hz must be numbers"})
            return
        capture = self.server.profile_capture(seconds, hz)
        if query.get("format", "") == "folded":
            payload = (capture["folded"] + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
            return
        capture["stages"] = obs_profile.attribution_table()
        self._respond(200, capture)

    def _explain(self, query):
        """The explain plane's single-key probe (ISSUE 15):
        ``?key=ns/name[&controller=worker-label]`` returns the
        blocked-on verdict + causal timeline per controller — every
        lookup O(1) per key, no fleet enumeration.  Unknown controller
        → 404; missing/malformed key → 400."""
        key = query.get("key", "")
        if not key:
            self._respond(400, {"error": "missing required query param: key"})
            return
        if "/" not in key:
            self._respond(400, {
                "error": f"key must be namespace/name, got {key!r}"
            })
            return
        controller = query.get("controller") or None
        try:
            answer = self.server.explain_lookup(key, controller)
        except KeyError:
            self._respond(404, {
                "error": f"no such controller: {controller!r}",
            })
            return
        self._respond(200, answer)

    def _respond(self, code: int, body: dict):
        payload = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    # bare path → handler(self, query): the single dispatch surface —
    # a new endpoint is one row here (plus its handler), and the 404
    # body enumerates exactly this table
    _ROUTES = {
        "/healthz": _healthz,
        "/readyz": _readyz,
        "/metrics": _metrics,
        "/metrics/fleet": _fleet_metrics,
        "/slo": _slo,
        "/debug/flightrecorder": _flightrecorder,
        "/debug/queues": _queues,
        "/debug/autoscaler": _autoscaler,
        "/debug/profile": _profile,
        "/debug/explain": _explain,
    }


def _parse_query(raw_query: str) -> dict:
    """The shared query-string parser: first value per param (no
    endpoint takes repeated params)."""
    return {
        name: values[0]
        for name, values in urllib.parse.parse_qs(raw_query).items()
        if values
    }


def make_health_server(
    port: int,
    health: Optional["api_health.HealthTracker"] = None,
    heartbeats: Optional["api_health.WorkerHeartbeats"] = None,
    stuck_threshold: float = WORKER_STUCK_THRESHOLD,
    host: str = "",
    gc_status: Optional[Callable[[], dict]] = None,
    metrics_registry: Optional["obs_metrics.MetricsRegistry"] = None,
    flight_recorder: Optional["obs_recorder.FlightRecorder"] = None,
    shard_status: Optional[Callable[[], dict]] = None,
    slo_status: Optional[Callable[[], dict]] = None,
    fleet_view: Optional["obs_fleet.FleetView"] = None,
    queue_status: Optional[Callable[[], dict]] = None,
    autoscaler_status: Optional[Callable[[], dict]] = None,
    autoscaler_history: Optional[Callable[[], list]] = None,
    profile_capture: Optional[Callable[..., dict]] = None,
    explain_lookup: Optional[Callable[..., dict]] = None,
) -> ThreadingHTTPServer:
    """Build the manager's health endpoint (bind port 0 in tests);
    call ``serve_forever`` on a daemon thread to serve.  ``gc_status``
    is the manager's ``gc_status`` hook (defaults to disabled).
    ``/metrics`` renders ``metrics_registry`` (default: the
    process-global registry, where the hot-path instruments land),
    ``/debug/flightrecorder`` dumps ``flight_recorder`` (default: the
    process-global ring), ``/slo`` serves ``slo_status`` (default: the
    installed global SLO engine, or a disabled stub), and
    ``/metrics/fleet`` serves ``fleet_view`` (default: a one-source
    view over this replica's own registry — ``--fleet-peers`` adds
    the rest of the fleet)."""
    server = ThreadingHTTPServer((host, port), _HealthHandler)
    server.health_tracker = health
    server.heartbeats = heartbeats or api_health.worker_heartbeats()
    server.stuck_threshold = stuck_threshold
    server.gc_status = gc_status or (lambda: {"enabled": False})
    server.shard_status = shard_status or (lambda: {"enabled": False})
    server.queue_status = queue_status or (lambda: {})
    server.slo_status = slo_status or obs_slo.status_or_disabled
    server.autoscaler_status = autoscaler_status or (lambda: {"enabled": False})
    server.autoscaler_history = autoscaler_history or (lambda: [])
    server.profile_capture = profile_capture or obs_stackprof.capture
    # /debug/explain (ISSUE 15): default to the installed process
    # engine (an unwired default engine knows no workers and answers
    # not-managed — graceful, never a 500)
    server.explain_lookup = explain_lookup or (
        lambda key, controller=None: obs_explain.engine().explain(key, controller)
    )
    server.metrics_registry = (
        metrics_registry if metrics_registry is not None else obs_metrics.registry()
    )
    server.fleet_view = fleet_view or obs_fleet.FleetView(
        {"self": server.metrics_registry.render}
    )
    server.flight_recorder = (
        flight_recorder
        if flight_recorder is not None
        else obs_recorder.flight_recorder()
    )
    klog.infof("Health endpoint listening on :%d", server.server_address[1])
    return server
