"""The process-wide clock/sleep seam (ISSUE 7).

Every subsystem that measures or spends time — workqueue delays,
settle polls, drift/resync tickers, health-plane windows, informer
resync ages, leader-election freshness, the Route53 batcher linger —
must read time through this seam (or an explicitly injected clock)
instead of calling ``time.time()`` / ``time.monotonic()`` /
``time.sleep()`` directly.  Under production the seam is a
zero-indirection passthrough to the real clock; under the
deterministic simulation runtime (``agac_tpu/sim/``) the sim installs
its virtual clock here and the ENTIRE manager runs on virtual time —
an N=50k fleet converges and a 7-virtual-day soak finishes in minutes
of wall clock, with every run byte-replayable from its seed.

The ``unseamed-clock`` lint rule (``analysis/rules.py``) pins the
invariant statically: a direct wall-clock call outside this module,
``agac_tpu/sim/`` and the sanctioned real-I/O modules fails CI.

Four installable pieces:

- ``monotonic()`` — the interval clock (durations, deadlines, TTLs);
- ``time()`` — the wall clock (timestamps in persisted objects);
- ``sleep(d)`` — blocking delay; in the sim this ADVANCES virtual
  time instead of blocking a thread;
- ``thread_cpu()`` — per-thread CPU time (``time.thread_time``), the
  stage accountant's cost clock (ISSUE 14).  The sim installs its
  virtual monotonic here too, so under simulation CPU == wall and the
  profiling plane stays byte-replayable.

Plus one capability flag: ``threads_enabled()``.  The sim runtime is
a single-threaded cooperative executor — components that would
normally spawn helper threads (the workqueue's delay waker, the event
recorder's persistence worker) consult this flag at construction time
and fall back to synchronous, explicitly-pumped operation so every
interleaving decision belongs to the deterministic scheduler.

``install``/``reset`` are NOT thread-safe against concurrent
construction on purpose: the seam is flipped once, before a sim world
is built, and flipped back after — never mid-flight.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Optional

_real_monotonic = _time.monotonic
_real_time = _time.time
_real_sleep = _time.sleep
_real_thread_cpu = _time.thread_time

_monotonic: Callable[[], float] = _real_monotonic
_wall: Callable[[], float] = _real_time
_sleep: Callable[[float], None] = _real_sleep
_thread_cpu: Callable[[], float] = _real_thread_cpu
_threads_enabled: bool = True


def monotonic() -> float:
    """Interval clock — the seam-routed ``time.monotonic()``."""
    return _monotonic()


def time() -> float:
    """Wall clock — the seam-routed ``time.time()``."""
    return _wall()


def sleep(seconds: float) -> None:
    """Seam-routed ``time.sleep()``; virtual-time advance in the sim."""
    _sleep(seconds)


def thread_cpu() -> float:
    """Per-thread CPU seconds — the seam-routed ``time.thread_time()``.
    The stage accountant (``observability/profile.py``) charges every
    stage's CPU through this; under the sim it reads virtual monotonic
    time so replay hashes never depend on host scheduling."""
    return _thread_cpu()


def monotonic_fn() -> Callable[[], float]:
    """The CURRENT monotonic callable, for components that capture a
    ``clock`` attribute at construction (``clock or
    clockseam.monotonic_fn()``).  Capturing the module function
    ``monotonic`` works too and additionally follows later installs;
    this accessor exists for call sites that want construction-time
    binding semantics made explicit."""
    return _monotonic


def sleep_fn() -> Callable[[float], None]:
    return _sleep


def threads_enabled() -> bool:
    """False while a simulation runtime is installed: helper threads
    (queue delay wakers, recorder persistence workers) must not be
    spawned — the sim's cooperative scheduler pumps their work
    explicitly so interleaving stays deterministic."""
    return _threads_enabled


def install(
    monotonic: Optional[Callable[[], float]] = None,
    wall: Optional[Callable[[], float]] = None,
    sleep: Optional[Callable[[float], None]] = None,
    threads: bool = True,
    thread_cpu: Optional[Callable[[], float]] = None,
) -> None:
    """Install a replacement clock (the sim runtime's entry point).
    Omitted pieces keep the real implementation — EXCEPT ``thread_cpu``,
    which defaults to the installed ``monotonic`` whenever that is
    replaced: a virtual world has no meaningful host-CPU counter, and
    CPU == wall keeps stage accounting deterministic under replay."""
    global _monotonic, _wall, _sleep, _thread_cpu, _threads_enabled
    _monotonic = monotonic if monotonic is not None else _real_monotonic
    _wall = wall if wall is not None else _real_time
    _sleep = sleep if sleep is not None else _real_sleep
    if thread_cpu is not None:
        _thread_cpu = thread_cpu
    elif monotonic is not None:
        _thread_cpu = monotonic
    else:
        _thread_cpu = _real_thread_cpu
    _threads_enabled = threads


def reset() -> None:
    """Restore the real clock (sim teardown; exception-safe via
    ``sim.runtime.installed`` context manager)."""
    install()
