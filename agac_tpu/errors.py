"""Error taxonomy for the reconcile pipeline.

Capability parity with the reference's ``pkg/errors/errors.go:1-40``:
a ``NoRetryError`` marker suppresses the rate-limited requeue that the
reconcile kernel otherwise performs on any processing error, and
``is_no_retry`` walks the exception chain the way Go's ``errors.As``
unwraps wrapped errors (``errors.go:33-39``).

``NotFoundError`` is the analog of apimachinery's IsNotFound: the
reconcile kernel dispatches to the delete path when an object lookup
raises it (reference ``pkg/reconcile/reconcile.go:62-63``).
"""

from __future__ import annotations


class NoRetryError(Exception):
    """An error that must not trigger a rate-limited requeue."""


def no_retry_errorf(fmt: str, *args) -> NoRetryError:
    """Build a NoRetryError from a printf-style format.

    Mirrors ``NewNoRetryErrorf`` (reference ``pkg/errors/errors.go:19-23``).
    """
    return NoRetryError(fmt % args if args else fmt)


def is_no_retry(err: BaseException | None) -> bool:
    """True if ``err`` or any exception in its explicit cause chain
    (``raise ... from inner``) is a NoRetryError.

    Follows only ``__cause__`` — the analog of Go's ``errors.As``
    unwrapping explicit wrapping (reference ``pkg/errors/errors.go:33-39``).
    Implicit ``__context__`` is deliberately ignored: an exception that
    merely *occurred inside* an ``except NoRetryError`` block was not
    wrapped by the raiser and must keep its own retry semantics.
    """
    seen = set()
    while err is not None and id(err) not in seen:
        if isinstance(err, NoRetryError):
            return True
        seen.add(id(err))
        err = err.__cause__
    return False


class NotFoundError(Exception):
    """Raised by cluster/cloud lookups when an object does not exist."""

    def __init__(self, kind: str = "", name: str = ""):
        self.kind = kind
        self.name = name
        super().__init__(f"{kind} {name!r} not found" if kind or name else "not found")


def is_not_found(err: BaseException | None) -> bool:
    return isinstance(err, NotFoundError)


class ConflictError(Exception):
    """Optimistic-concurrency conflict: the object's resourceVersion is
    stale (the apiserver's 409).  Leader election retries on it."""


class AlreadyExistsError(Exception):
    """Create of an object that already exists (the apiserver's 409)."""

