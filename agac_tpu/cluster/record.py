"""Event recorder — user-facing observability.

The analog of client-go's ``record.EventRecorder`` that every
controller in the reference constructs (e.g.
``pkg/controller/globalaccelerator/controller.go:55-58``) and emits
through (``GlobalAcceleratorCreated``/``GlobalAcceleratorDeleted``
events, ``service.go:82,117``).  Events are both logged and persisted
as ``Event`` objects through the cluster client, so tests and
operators can list them.
"""

from __future__ import annotations

import time
from typing import Any

from .. import klog
from .client import ClusterClient
from .objects import Event, EventSource, ObjectMeta, ObjectReference


class EventRecorder:
    def __init__(self, client: ClusterClient, component: str):
        self._client = client
        self._component = component

    def event(self, obj: Any, event_type: str, reason: str, message: str) -> None:
        meta = obj.metadata
        # unique across recorder instances and process restarts, like
        # client-go's UnixNano suffix
        ev = Event(
            metadata=ObjectMeta(
                name=f"{meta.name}.{time.time_ns():x}",
                namespace=meta.namespace or "default",
            ),
            involved_object=ObjectReference(
                kind=getattr(obj, "KIND", type(obj).__name__),
                namespace=meta.namespace,
                name=meta.name,
                uid=meta.uid,
            ),
            reason=reason,
            message=message,
            type=event_type,
            source=EventSource(component=self._component),
        )
        klog.infof(
            'Event(%s/%s %s): type=%r reason=%r %s',
            meta.namespace,
            meta.name,
            ev.involved_object.kind,
            event_type,
            reason,
            message,
        )
        try:
            self._client.create("Event", ev)
        except Exception as err:
            klog.errorf("failed to record event %s: %s", reason, err)

    def eventf(self, obj: Any, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)
