"""Event recorder — user-facing observability.

The analog of client-go's ``record.EventRecorder`` that every
controller in the reference constructs (e.g.
``pkg/controller/globalaccelerator/controller.go:55-58``) and emits
through (``GlobalAcceleratorCreated``/``GlobalAcceleratorDeleted``
events, ``service.go:82,117``).  Events are both logged and persisted
as ``Event`` objects through the cluster client, so tests and
operators can list them.

Shaped like client-go's correlator + broadcaster stack:

- **aggregation** — a repeat of the same (object, type, reason,
  message) within the aggregation window bumps ``count`` and
  ``lastTimestamp`` on the existing Event instead of creating a new
  object, so a requeue-repair loop shows as one Event with count=N;
- **spam filter** — a token bucket per involved object (25 burst, one
  refill per 5 minutes, client-go's defaults) drops pathological
  floods before they are logged or persisted;
- **async persistence** — apiserver writes happen on the recorder's
  own worker thread behind a bounded queue (the broadcaster analog:
  buffered channel, drop-if-full), so an apiserver stall never blocks
  the reconcile workers emitting events.

Correlation state lives under a lock that is never held across I/O.
"""

from __future__ import annotations

import copy
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable

from .. import clockseam, klog
from .client import ClusterClient
from .objects import Event, EventSource, ObjectMeta, ObjectReference

AGGREGATION_WINDOW = 600.0  # seconds; client-go's 10-minute window
SPAM_BURST = 25.0
SPAM_REFILL_PER_SECOND = 1.0 / 300.0  # one event per object per 5 min sustained
MAX_CACHE_ENTRIES = 4096  # client-go's LRU cache size
QUEUE_CAPACITY = 1000  # pending persistence actions (broadcaster buffer)


def _iso(now: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(now))


class _Series:
    __slots__ = ("event", "created", "last_seen", "dirty")

    def __init__(self, event: Event, last_seen: float):
        self.event = event
        self.created = False  # persisted at least once
        self.last_seen = last_seen
        self.dirty = False  # queued for persistence


class EventRecorder:
    def __init__(
        self,
        client: ClusterClient,
        component: str,
        clock: Callable[[], float] | None = None,
        monotonic: Callable[[], float] | None = None,
        sleep: Callable[[float], None] | None = None,
        synchronous: bool | None = None,
    ):
        self._client = client
        self._component = component
        # clock seam (ISSUE 7): wall clock stamps the events, the
        # monotonic/sleep pair bounds flush() — all virtual under sim
        self._clock = clock or clockseam.time
        self._monotonic = monotonic or clockseam.monotonic
        self._sleep = sleep or clockseam.sleep
        # threadless mode (sim runtime): persist inline on the emitting
        # thread instead of a worker thread, so apiserver writes land
        # at deterministic points in the cooperative schedule
        self._synchronous = (
            synchronous
            if synchronous is not None
            else not clockseam.threads_enabled()
        )
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        # true LRU: touched entries move to the end, eviction pops the
        # front — an actively flooding object is never evicted into a
        # fresh full-burst bucket
        self._series: OrderedDict[tuple, _Series] = OrderedDict()
        self._buckets: OrderedDict[tuple, tuple[float, float]] = OrderedDict()
        self._queue: deque[tuple] = deque()
        self._worker: threading.Thread | None = None
        self._stopped = False
        self._inflight = 0
        self._last_name_suffix = 0

    # ------------------------------------------------------------------
    # correlation (fast, lock-held, no I/O)
    # ------------------------------------------------------------------
    def _spam_filtered(self, obj_key: tuple, now: float) -> bool:
        tokens, last = self._buckets.get(obj_key, (SPAM_BURST, now))
        tokens = min(SPAM_BURST, tokens + (now - last) * SPAM_REFILL_PER_SECOND)
        filtered = tokens < 1.0
        self._buckets[obj_key] = (tokens if filtered else tokens - 1.0, now)
        self._buckets.move_to_end(obj_key)
        while len(self._buckets) > MAX_CACHE_ENTRIES:
            self._buckets.popitem(last=False)
        return filtered

    def _next_name_suffix(self, now: float) -> int:
        """Nanosecond-scale name suffix derived from the seamed wall
        clock, bumped past the previous one so two events in the same
        (virtual) instant still get distinct names."""
        self._last_name_suffix = max(int(now * 1e9), self._last_name_suffix + 1)
        return self._last_name_suffix

    def event(self, obj: Any, event_type: str, reason: str, message: str) -> None:
        meta = obj.metadata
        kind = getattr(obj, "KIND", type(obj).__name__)
        now = self._clock()
        obj_key = (kind, meta.namespace, meta.name)
        series_key = obj_key + (event_type, reason, message)
        with self._lock:
            if self._spam_filtered(obj_key, now):
                klog.v(2).infof(
                    "event for %s/%s dropped by spam filter", meta.namespace, meta.name
                )
                return
            series = self._series.get(series_key)
            if series is not None and now - series.last_seen < AGGREGATION_WINDOW:
                series.event.count += 1
                series.event.last_timestamp = _iso(now)
                series.last_seen = now
            else:
                ev = Event(
                    metadata=ObjectMeta(
                        # unique across recorder instances and process
                        # restarts, like client-go's UnixNano suffix —
                        # read through the clock seam (plus a strictly
                        # increasing floor for same-instant events) so
                        # sim replays mint identical names
                        name=f"{meta.name}.{self._next_name_suffix(now):x}",
                        namespace=meta.namespace or "default",
                    ),
                    involved_object=ObjectReference(
                        kind=kind,
                        namespace=meta.namespace,
                        name=meta.name,
                        uid=meta.uid,
                    ),
                    reason=reason,
                    message=message,
                    type=event_type,
                    source=EventSource(component=self._component),
                    first_timestamp=_iso(now),
                    last_timestamp=_iso(now),
                )
                series = _Series(ev, now)
                self._series[series_key] = series
            self._series.move_to_end(series_key)
            while len(self._series) > MAX_CACHE_ENTRIES:
                self._series.popitem(last=False)
            if not series.dirty:
                if len(self._queue) >= QUEUE_CAPACITY:
                    klog.errorf(
                        "event queue full; dropping event %s for %s/%s",
                        reason, meta.namespace, meta.name,
                    )
                    return
                series.dirty = True
                self._queue.append(series_key)
            if not self._synchronous:
                self._ensure_worker()
                self._wake.notify()
        if self._synchronous:
            while self._drain_step():
                pass
        klog.infof(
            'Event(%s/%s %s): type=%r reason=%r %s',
            meta.namespace, meta.name, kind, event_type, reason, message,
        )

    def eventf(self, obj: Any, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)

    # ------------------------------------------------------------------
    # persistence worker (all I/O happens here, never under the lock)
    # ------------------------------------------------------------------
    def _ensure_worker(self) -> None:
        # under a disabled seam the recorder must never spawn, even if
        # it was constructed synchronous=False before a sim flipped the
        # seam: events stay queued for the next explicit flush().  (No
        # inline drain HERE — this runs under self._lock and
        # _drain_step re-acquires it.)
        if not clockseam.threads_enabled():
            return
        if self._worker is None or not self._worker.is_alive():
            self._stopped = False
            self._worker = threading.Thread(
                target=self._drain_loop, daemon=True, name=f"event-recorder-{self._component}"
            )
            self._worker.start()

    def _drain_loop(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._stopped:
                    self._wake.wait()
                if not self._queue and self._stopped:
                    return
            self._drain_step()

    def _drain_step(self) -> bool:
        """Persist at most one queued series; False when the queue is
        empty.  Shared by the worker thread and synchronous mode."""
        with self._lock:
            if not self._queue:
                return False
            series_key = self._queue.popleft()
            series = self._series.get(series_key)
            if series is None:
                return True
            series.dirty = False
            self._inflight += 1
            # snapshot what we persist — a COPY taken under the
            # lock, because event() keeps mutating count and
            # last_timestamp on the live object; serializing the
            # live reference outside the lock could persist a torn
            # view (new count, stale lastTimestamp).  Later bumps
            # re-queue via the dirty flag.
            snapshot = copy.deepcopy(series.event)
            created = series.created
        try:
            if created:
                stored = self._client.update("Event", snapshot)
            else:
                stored = self._client.create("Event", snapshot)
        except Exception as err:
            klog.errorf("failed to record event %s: %s", snapshot.reason, err)
            with self._lock:
                self._inflight -= 1
                # stale/lost: the next occurrence starts fresh
                if self._series.get(series_key) is series:
                    del self._series[series_key]
            return True
        with self._lock:
            self._inflight -= 1
            if self._series.get(series_key) is series:
                series.created = True
                series.event.metadata.resource_version = (
                    stored.metadata.resource_version
                )
        return True

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every queued event has been persisted (tests
        and shutdown use this; reconcile paths never need to)."""
        deadline = self._monotonic() + timeout
        while self._monotonic() < deadline:
            with self._lock:
                if not self._queue and self._inflight == 0:
                    return True
            if self._synchronous:
                while self._drain_step():
                    pass
                continue
            self._sleep(0.002)
        with self._lock:
            return not self._queue and self._inflight == 0

    def shutdown(self, timeout: float = 2.0) -> None:
        """Drain pending events and stop the worker (controllers call
        this on their way out, like broadcaster.Shutdown())."""
        self.flush(timeout)
        with self._lock:
            self._stopped = True
            worker = self._worker
            self._wake.notify_all()
        if worker is not None:
            worker.join(timeout)
