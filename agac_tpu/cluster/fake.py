"""In-memory fake apiserver.

The test double the reference gets from its generated fake clientset
(``pkg/client/clientset/versioned/fake/clientset_generated.go:37``) and
kind clusters, but covering the full surface the framework needs:
typed CRUD with optimistic concurrency, finalizer-aware deletion,
generation/resourceVersion bookkeeping, and replayable watch streams —
enough to run whole controllers against it (SURVEY.md §7 stage 4's
"fake apiserver").

Apiserver behaviors reproduced because controllers depend on them:

- ``delete`` of an object with finalizers only sets
  ``metadata.deletionTimestamp`` (MODIFIED event); the object is
  removed once an ``update`` clears the last finalizer — the
  EndpointGroupBinding lifecycle (reference
  ``pkg/controller/endpointgroupbinding/reconcile.go:36-64``).
- ``metadata.generation`` increments only on spec changes;
  ``update_status`` never bumps it (ObservedGeneration bookkeeping,
  reference ``reconcile.go:89,157,208``).
- updates with a stale ``resourceVersion`` raise ``ConflictError``
  (leader-election lease races).
- ``watch`` replays history after the given resourceVersion, then
  streams live events.
"""

from __future__ import annotations

import bisect
import copy
import datetime
import queue as queue_mod
import threading
import uuid
from typing import Any, Callable, Iterator, Optional

from ..errors import AlreadyExistsError, ConflictError, NotFoundError
from .client import ClusterClient, WatchEvent
from .objects import meta_namespace_key

_HISTORY_LIMIT = 4096


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


class FakeCluster(ClusterClient):
    def __init__(self):
        self._lock = threading.Lock()
        self._store: dict[str, dict[str, Any]] = {}
        self._rv = 0
        self._history: dict[str, list[tuple[int, WatchEvent]]] = {}
        self._watchers: dict[str, list[queue_mod.Queue]] = {}
        # per-kind high-water mark of trimmed history: events at or
        # below this rv are no longer replayable (the "410 Gone" line
        # events_since reports so pollers know to relist)
        self._trimmed_rv: dict[str, int] = {}

    # ---- internals ----------------------------------------------------
    def _bump(self) -> int:
        self._rv += 1
        return self._rv

    def _kind_store(self, kind: str) -> dict[str, Any]:
        return self._store.setdefault(kind, {})

    def _broadcast(self, kind: str, event_type: str, obj: Any, rv: int) -> None:
        event = WatchEvent(event_type, copy.deepcopy(obj))
        history = self._history.setdefault(kind, [])
        history.append((rv, event))
        if len(history) > _HISTORY_LIMIT:
            trim = len(history) - _HISTORY_LIMIT
            self._trimmed_rv[kind] = history[trim - 1][0]
            del history[:trim]
        for q in self._watchers.get(kind, []):
            q.put((rv, event))

    # ---- ClusterClient -------------------------------------------------
    def get(self, kind: str, namespace: str, name: str) -> Any:
        key = f"{namespace}/{name}" if namespace else name
        with self._lock:
            obj = self._kind_store(kind).get(key)
            if obj is None:
                raise NotFoundError(kind, key)
            return copy.deepcopy(obj)

    def list(self, kind: str, namespace: Optional[str] = None) -> tuple[list[Any], str]:
        with self._lock:
            objs = [
                copy.deepcopy(o)
                for o in self._kind_store(kind).values()
                if namespace is None or o.metadata.namespace == namespace
            ]
            return objs, str(self._rv)

    def create(self, kind: str, obj: Any) -> Any:
        obj = copy.deepcopy(obj)
        key = meta_namespace_key(obj)
        with self._lock:
            store = self._kind_store(kind)
            if key in store:
                raise AlreadyExistsError(f"{kind} {key!r} already exists")
            rv = self._bump()
            obj.metadata.uid = obj.metadata.uid or str(uuid.uuid4())
            obj.metadata.resource_version = str(rv)
            obj.metadata.creation_timestamp = obj.metadata.creation_timestamp or _now()
            if hasattr(obj, "spec"):
                obj.metadata.generation = 1
            store[key] = obj
            self._broadcast(kind, "ADDED", obj, rv)
            return copy.deepcopy(obj)

    def update(self, kind: str, obj: Any) -> Any:
        obj = copy.deepcopy(obj)
        key = meta_namespace_key(obj)
        with self._lock:
            store = self._kind_store(kind)
            current = store.get(key)
            if current is None:
                raise NotFoundError(kind, key)
            if (
                obj.metadata.resource_version
                and obj.metadata.resource_version != current.metadata.resource_version
            ):
                raise ConflictError(
                    f"{kind} {key!r}: resourceVersion {obj.metadata.resource_version} "
                    f"is stale (current {current.metadata.resource_version})"
                )
            # status is a subresource: a plain update cannot change it
            if hasattr(current, "status"):
                obj.status = copy.deepcopy(current.status)
            rv = self._bump()
            if hasattr(obj, "spec") and obj.spec != current.spec:
                obj.metadata.generation = current.metadata.generation + 1
            else:
                obj.metadata.generation = current.metadata.generation
            obj.metadata.resource_version = str(rv)
            obj.metadata.uid = current.metadata.uid
            obj.metadata.creation_timestamp = current.metadata.creation_timestamp
            if current.metadata.deletion_timestamp:
                obj.metadata.deletion_timestamp = current.metadata.deletion_timestamp
            if obj.metadata.deletion_timestamp and not obj.metadata.finalizers:
                del store[key]
                self._broadcast(kind, "DELETED", obj, rv)
            else:
                store[key] = obj
                self._broadcast(kind, "MODIFIED", obj, rv)
            return copy.deepcopy(obj)

    def update_status(self, kind: str, obj: Any) -> Any:
        key = meta_namespace_key(obj)
        with self._lock:
            store = self._kind_store(kind)
            current = store.get(key)
            if current is None:
                raise NotFoundError(kind, key)
            if (
                obj.metadata.resource_version
                and obj.metadata.resource_version != current.metadata.resource_version
            ):
                raise ConflictError(f"{kind} {key!r}: resourceVersion is stale")
            updated = copy.deepcopy(current)
            updated.status = copy.deepcopy(obj.status)
            rv = self._bump()
            updated.metadata.resource_version = str(rv)
            store[key] = updated
            self._broadcast(kind, "MODIFIED", updated, rv)
            return copy.deepcopy(updated)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}" if namespace else name
        with self._lock:
            store = self._kind_store(kind)
            obj = store.get(key)
            if obj is None:
                raise NotFoundError(kind, key)
            rv = self._bump()
            obj.metadata.resource_version = str(rv)
            if obj.metadata.finalizers:
                obj.metadata.deletion_timestamp = _now()
                self._broadcast(kind, "MODIFIED", obj, rv)
            else:
                del store[key]
                self._broadcast(kind, "DELETED", obj, rv)

    # ---- incident capture & replay (ISSUE 19) -------------------------
    def snapshot(self) -> tuple[list[tuple[str, Any]], int]:
        """Point-in-time copy of the store for a capture header:
        ``(kind, object)`` pairs ordered by resourceVersion (so a
        restore re-seeds in creation order) plus the rv counter."""
        with self._lock:
            objects = [
                (kind, copy.deepcopy(obj))
                for kind, store in self._store.items()
                for obj in store.values()
            ]
            objects.sort(key=lambda item: int(item[1].metadata.resource_version or 0))
            return objects, self._rv

    def restore(self, objects: list[tuple[str, Any]], resource_version: int) -> None:
        """Seed this (fresh) cluster from a capture-header snapshot:
        objects land verbatim — same uid/rv/generation, NO watch events
        — and the rv counter resumes where the recording's stood, so a
        replayed run mints the same resourceVersion stream the live
        run did."""
        with self._lock:
            for kind, obj in objects:
                key = meta_namespace_key(obj)
                self._kind_store(kind)[key] = copy.deepcopy(obj)
            self._rv = max(self._rv, int(resource_version))

    def events_since(
        self, kind: str, resource_version: str
    ) -> tuple[Optional[list[WatchEvent]], str]:
        """Non-blocking watch cursor (the sim runtime's pump, ISSUE 7):
        every event of ``kind`` after ``resource_version``, plus the
        new cursor to resume from.  Returns ``(None, cursor)`` when the
        requested window has been trimmed out of history — the
        apiserver's "410 Gone": the caller must relist (the sim pump
        calls the informer's ``sync_once``) instead of silently missing
        deltas."""
        with self._lock:
            since = int(resource_version or 0)
            if since < self._trimmed_rv.get(kind, 0):
                return None, str(self._rv)
            history = self._history.get(kind, [])
            # rvs are strictly increasing, so the cursor seek is a
            # bisect, not a scan — the pump calls this per informer per
            # round, and an O(history) scan each time was a measurable
            # slice of the 7-day sim soak's wall clock
            start = bisect.bisect_right(history, since, key=lambda item: item[0])
            events = [ev for _, ev in history[start:]]
            return events, str(self._rv)

    def watch(
        self, kind: str, resource_version: str, stop: Callable[[], bool]
    ) -> Iterator[WatchEvent]:
        q: queue_mod.Queue = queue_mod.Queue()
        with self._lock:
            since = int(resource_version or 0)
            backlog = [
                (rv, ev) for rv, ev in self._history.get(kind, []) if rv > since
            ]
            self._watchers.setdefault(kind, []).append(q)
        delivered = since
        try:
            for rv, ev in backlog:
                if stop():
                    return
                delivered = rv
                yield ev
            while not stop():
                try:
                    rv, ev = q.get(timeout=0.05)
                except queue_mod.Empty:
                    continue
                if rv <= delivered:  # already replayed from backlog
                    continue
                delivered = rv
                yield ev
        finally:
            with self._lock:
                watchers = self._watchers.get(kind, [])
                if q in watchers:
                    watchers.remove(q)
