"""An embeddable kube-apiserver speaking the Kubernetes REST protocol
over real HTTP, backed by ``FakeCluster``.

The envtest/kind analog for this framework (the reference's tier-2
test strategy runs a kind cluster, SURVEY.md §4): full controller
processes — REST client, informers with streaming watches, leader
election leases, CRD finalizer flows — run against it without a real
control plane.  Endpoints implemented (for every kind in
``KIND_REGISTRY``):

- ``GET    /{prefix}/{plural}``                       list (all namespaces)
- ``GET    /{prefix}/{plural}?watch=true&...``        streaming watch
- ``GET    /{prefix}/namespaces/{ns}/{plural}``       namespaced list
- ``GET    /{prefix}/namespaces/{ns}/{plural}/{name}``
- ``POST   /{prefix}/namespaces/{ns}/{plural}``       create
- ``PUT    .../{name}``                               update
- ``PUT    .../{name}/status``                        status subresource
- ``PATCH  .../{name}`` (``application/apply-patch+yaml``) server-side apply
- ``DELETE .../{name}``                               delete (finalizer-aware)

Errors are k8s ``Status`` JSON with the proper HTTP codes so the REST
client's error mapping round-trips (404 NotFound, 409 Conflict /
AlreadyExists).

Validating admission webhooks can be registered per kind
(``register_validating_webhook``): CREATE/UPDATE requests are wrapped
in an AdmissionReview, POSTed to the webhook URL, and rejected with
403 when not allowed — the flow the reference's kind e2e exercises
against the real apiserver (``e2e/e2e_test.go:78-98``).
"""

from __future__ import annotations

import itertools
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..errors import AlreadyExistsError, ConflictError, NotFoundError
from .fake import FakeCluster
from .rest import KIND_REGISTRY
from .serde import from_wire, to_wire

# path prefix -> kind, e.g. ("api/v1", "services") -> "Service"
_PATH_TO_KIND = {
    (prefix, plural): kind
    for kind, (prefix, plural, _, _) in KIND_REGISTRY.items()
}


def _deep_merge(base: dict, overlay: dict) -> dict:
    """Recursive map merge for the apply route: nested dicts merge
    key-by-key, everything else (scalars, lists) is replaced by the
    overlay — the approximation of SSA the fallback-equivalence tests
    rely on."""
    merged = dict(base)
    for key, value in overlay.items():
        if isinstance(value, dict) and isinstance(merged.get(key), dict):
            merged[key] = _deep_merge(merged[key], value)
        else:
            merged[key] = value
    return merged


# identity fields every applier co-owns without conflict (the real
# apiserver's managedFields never attribute these to one manager)
_APPLY_IDENTITY_FIELDS = frozenset(
    {"apiVersion", "kind", "metadata.name", "metadata.namespace",
     "metadata.resourceVersion"}
)


def _apply_leaf_paths(manifest: dict, prefix: tuple = ()) -> list[str]:
    """Dot-joined leaf field paths an apply of ``manifest`` claims:
    maps recurse, scalars/lists/empty-maps are leaves (the granularity
    real SSA tracks atomic fields at — list-item-level ownership is
    beyond this server's charter).  Identity fields are excluded."""
    paths = []
    if isinstance(manifest, dict) and manifest:
        for key, value in manifest.items():
            paths.extend(_apply_leaf_paths(value, prefix + (str(key),)))
    else:
        path = ".".join(prefix)
        if path and path not in _APPLY_IDENTITY_FIELDS:
            paths.append(path)
    return paths


def _full_wire(kind: str, obj) -> dict:
    """Wire envelope: serde dict stamped with apiVersion + kind."""
    _, _, _, api_version = KIND_REGISTRY[kind]
    wire = to_wire(obj)
    wire["apiVersion"] = api_version
    wire["kind"] = kind
    return wire


def _status_body(code: int, reason: str, message: str) -> bytes:
    return json.dumps(
        {
            "kind": "Status",
            "apiVersion": "v1",
            "status": "Failure",
            "message": message,
            "reason": reason,
            "code": code,
        }
    ).encode()


class _Route:
    def __init__(self, kind: str, namespace: str, name: str, subresource: str):
        self.kind = kind
        self.namespace = namespace
        self.name = name
        self.subresource = subresource


def _parse_path(path: str) -> _Route | None:
    """Resolve a request path to (kind, namespace, name, subresource)."""
    parts = [p for p in path.split("/") if p]
    # prefixes are 2 ("api/v1") or 3 ("apis/group/version") segments
    for prefix_len in (2, 3):
        if len(parts) < prefix_len + 1:
            continue
        prefix = "/".join(parts[:prefix_len])
        rest = parts[prefix_len:]
        namespace = ""
        if rest and rest[0] == "namespaces" and len(rest) >= 2:
            namespace = rest[1]
            rest = rest[2:]
        if not rest:
            continue
        plural = rest[0]
        kind = _PATH_TO_KIND.get((prefix, plural))
        if kind is None:
            continue
        name = rest[1] if len(rest) > 1 else ""
        subresource = rest[2] if len(rest) > 2 else ""
        return _Route(kind, namespace, name, subresource)
    return None


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "agac-testserver/0.1"

    def log_message(self, fmt, *args):
        pass  # quiet

    @property
    def cluster(self) -> FakeCluster:
        return self.server.cluster  # type: ignore[attr-defined]

    # ------------------------------------------------------------------
    def _send(self, code: int, body: bytes, content_type="application/json", chunked=False):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        if chunked:
            self.send_header("Transfer-Encoding", "chunked")
        else:
            self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if not chunked and body:
            self.wfile.write(body)

    def _send_obj(self, code: int, kind: str, obj) -> None:
        self._send(code, json.dumps(_full_wire(kind, obj)).encode())

    def _send_error_status(self, err: Exception, context: str) -> None:
        if isinstance(err, NotFoundError):
            self._send(404, _status_body(404, "NotFound", f"{context} not found"))
        elif isinstance(err, AlreadyExistsError):
            self._send(409, _status_body(409, "AlreadyExists", f"{context} already exists"))
        elif isinstance(err, ConflictError):
            self._send(409, _status_body(409, "Conflict", str(err)))
        else:
            self._send(500, _status_body(500, "InternalError", str(err)))

    def _read_object(self, kind: str):
        length = int(self.headers.get("Content-Length") or 0)
        payload = json.loads(self.rfile.read(length)) if length else {}
        _, _, cls, _ = KIND_REGISTRY[kind]
        return from_wire(cls, payload)

    def _admit(self, kind: str, operation: str, obj, old_obj) -> str | None:
        """Run registered validating webhooks; returns a denial message
        or None if allowed (failurePolicy=Fail semantics: webhook
        errors reject the request, like the reference's configuration,
        ``config/webhook/manifests.yaml`` failurePolicy: Fail)."""
        webhook_url = self.server.webhooks.get(kind)  # type: ignore[attr-defined]
        if webhook_url is None:
            return None
        import urllib.request
        import uuid

        def wrap(o):
            return None if o is None else _full_wire(kind, o)

        review = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": str(uuid.uuid4()),
                "kind": {"kind": kind},
                "operation": operation,
                "object": wrap(obj),
                "oldObject": wrap(old_obj),
            },
        }
        request = urllib.request.Request(
            webhook_url,
            data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(request, timeout=10) as response:
                result = json.loads(response.read())
        except Exception as err:
            return f"admission webhook call failed: {err}"
        resp = result.get("response") or {}
        if resp.get("allowed"):
            return None
        # or-fallback, not get-default: an explicit null message must
        # still read as a denial
        return (resp.get("status") or {}).get("message") or "denied by admission webhook"

    # ------------------------------------------------------------------
    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        route = _parse_path(parsed.path)
        if route is None:
            self._send(404, _status_body(404, "NotFound", f"unknown path {parsed.path}"))
            return
        query = dict(urllib.parse.parse_qsl(parsed.query))
        if route.name:
            try:
                obj = self.cluster.get(route.kind, route.namespace, route.name)
            except Exception as err:
                self._send_error_status(err, f"{route.kind} {route.name}")
                return
            self._send_obj(200, route.kind, obj)
            return
        if query.get("watch") == "true":
            self._serve_watch(route.kind, query)
            return
        # chunked listing: honor limit/continue the way a real
        # apiserver does — continue pages are served from a PINNED
        # snapshot (never a fresh re-list, which would skip objects
        # deleted between pages), and an expired/unknown token gets a
        # 410 so clients restart the list
        try:
            limit = int(query.get("limit") or 0)
        except ValueError:
            self._send(400, _status_body(400, "BadRequest", "invalid limit"))
            return
        token = query.get("continue") or ""
        snapshots = self.server.list_snapshots  # type: ignore[attr-defined]
        snapshots_lock = self.server.snapshots_lock  # type: ignore[attr-defined]
        if token:
            try:
                snap_id, offset_str = token.split(":", 1)
                offset = int(offset_str)
            except ValueError:
                self._send(400, _status_body(400, "BadRequest", "invalid continue token"))
                return
            with snapshots_lock:
                snapshot = snapshots.get(snap_id)
            if snapshot is None:
                self._send(
                    410, _status_body(410, "Expired", "continue token expired")
                )
                return
            objs, rv = snapshot
        else:
            objs, rv = self.cluster.list(route.kind, route.namespace or None)
            offset = 0
        _, _, _, api_version = KIND_REGISTRY[route.kind]
        metadata: dict = {"resourceVersion": rv}
        page = objs[offset:]
        if limit and len(page) > limit:
            page = page[:limit]
            snap_id = (
                token.split(":", 1)[0]
                if token
                else f"s{next(self.server.snapshot_counter)}"  # type: ignore[attr-defined]
            )
            with snapshots_lock:
                # LRU: move-to-end on every touch so an ACTIVE
                # pagination outlives younger abandoned ones, then
                # evict oldest (clients holding an evicted token get
                # the 410 above)
                snapshots.pop(snap_id, None)
                snapshots[snap_id] = (objs, rv)
                while len(snapshots) > 32:
                    snapshots.pop(next(iter(snapshots)))
            metadata["continue"] = f"{snap_id}:{offset + limit}"
        elif token:
            with snapshots_lock:
                snapshots.pop(token.split(":", 1)[0], None)  # fully consumed
        items = [_full_wire(route.kind, obj) for obj in page]
        body = json.dumps(
            {
                "apiVersion": api_version,
                "kind": f"{route.kind}List",
                "metadata": metadata,
                "items": items,
            }
        ).encode()
        self._send(200, body)

    def _serve_watch(self, kind: str, query: dict) -> None:
        import time

        timeout_seconds = float(query.get("timeoutSeconds", 240))
        deadline = time.monotonic() + timeout_seconds
        stopped = threading.Event()
        start_generation = getattr(self.server, "watch_generation", 0)

        def broken() -> bool:
            return getattr(self.server, "watch_generation", 0) != start_generation

        def stop() -> bool:
            return (
                stopped.is_set() or time.monotonic() >= deadline or broken()
            )

        self._send(200, b"", chunked=True)
        try:
            for event in self.cluster.watch(kind, query.get("resourceVersion", "0"), stop):
                line = (
                    json.dumps(
                        {"type": event.type, "object": _full_wire(kind, event.obj)}
                    ).encode()
                    + b"\n"
                )
                self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
                self.wfile.flush()
            if broken():
                # the apiserver expired this watch: emit the 410 ERROR
                # event clients must answer with a fresh list+watch
                line = (
                    json.dumps(
                        {"type": "ERROR", "object": {"code": 410, "reason": "Gone"}}
                    ).encode()
                    + b"\n"
                )
                self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            stopped.set()
            return
        try:
            self.wfile.write(b"0\r\n\r\n")  # chunked terminator
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_POST(self):
        route = _parse_path(urllib.parse.urlsplit(self.path).path)
        if route is None:
            self._send(404, _status_body(404, "NotFound", "unknown path"))
            return
        try:
            obj = self._read_object(route.kind)
            denial = self._admit(route.kind, "CREATE", obj, None)
            if denial is not None:
                self._send(403, _status_body(403, "Forbidden", denial))
                return
            created = self.cluster.create(route.kind, obj)
        except Exception as err:
            self._send_error_status(err, route.kind)
            return
        self._send_obj(201, route.kind, created)

    def do_PUT(self):
        route = _parse_path(urllib.parse.urlsplit(self.path).path)
        if route is None or not route.name:
            self._send(404, _status_body(404, "NotFound", "unknown path"))
            return
        try:
            obj = self._read_object(route.kind)
            if route.subresource == "status":
                updated = self.cluster.update_status(route.kind, obj)
            else:
                old_obj = None
                try:
                    old_obj = self.cluster.get(route.kind, route.namespace, route.name)
                except NotFoundError:
                    pass
                denial = self._admit(route.kind, "UPDATE", obj, old_obj)
                if denial is not None:
                    self._send(403, _status_body(403, "Forbidden", denial))
                    return
                updated = self.cluster.update(route.kind, obj)
        except Exception as err:
            self._send_error_status(err, f"{route.kind} {route.name}")
            return
        self._send_obj(200, route.kind, updated)

    def do_PATCH(self):
        """Server-side apply (``application/apply-patch+yaml``), the
        route ``DynamicClient.apply`` hits first — create-or-merge with
        the fieldManager recorded in ``server.apply_managers`` so tests
        can assert WHICH branch ran (reference analog: SSA through the
        dynamic client, ``e2e/pkg/util/manifests.go:83-141``).  Field
        ownership is tracked per leaf path in ``server.field_owners``:
        a second manager applying an owned field gets 409 Conflict
        unless ``force=true`` takes the field over — so the client's
        force contract is asserted against a server that can say no.

        ``TestApiServer(ssa=False)`` answers 501 instead, standing in
        for pre-SSA servers so the client's create-or-replace fallback
        stays testable."""
        parsed = urllib.parse.urlsplit(self.path)
        route = _parse_path(parsed.path)
        if route is None or not route.name:
            self._send(404, _status_body(404, "NotFound", "unknown path"))
            return
        if route.subresource:
            # the real apiserver supports apply on /status; this server
            # does not emulate field ownership per subresource — be
            # LOUD (400 propagates through DynamicClient, no fallback)
            # rather than silently applying to the whole object
            self._send(
                400,
                _status_body(
                    400,
                    "BadRequest",
                    f"apply to subresource {route.subresource!r} is not "
                    "implemented by the test apiserver",
                ),
            )
            return
        if not getattr(self.server, "ssa_enabled", True):
            self._send(
                501, _status_body(501, "NotImplemented", "SSA disabled")
            )
            return
        content_type = (self.headers.get("Content-Type") or "").split(";")[0]
        if content_type != "application/apply-patch+yaml":
            # merge/json/strategic patch are not implemented here —
            # 415 is what a server without the route family answers
            self._send(
                415,
                _status_body(
                    415, "UnsupportedMediaType", f"unsupported patch {content_type}"
                ),
            )
            return
        query = dict(urllib.parse.parse_qsl(parsed.query))
        field_manager = query.get("fieldManager", "")
        force = query.get("force", "false") == "true"
        if not field_manager:
            # the real apiserver rejects apply without a manager; NOT
            # a fallback trigger (400 must propagate to the client)
            self._send(
                400,
                _status_body(400, "BadRequest", "fieldManager is required for apply"),
            )
            return
        import yaml as _yaml_mod

        length = int(self.headers.get("Content-Length") or 0)
        try:
            manifest = _yaml_mod.safe_load(self.rfile.read(length)) or {}
        except _yaml_mod.YAMLError as err:
            self._send(400, _status_body(400, "BadRequest", f"bad YAML: {err}"))
            return
        metadata = (manifest.get("metadata") or {}) if isinstance(manifest, dict) else {}
        body_name = metadata.get("name")
        body_namespace = metadata.get("namespace")
        if (body_name and body_name != route.name) or (
            body_namespace and route.namespace and body_namespace != route.namespace
        ):
            # the real apiserver 400s on URL/body identity mismatch;
            # silently creating the BODY's name would let smoke-mode
            # tests pass that fail on kind
            self._send(
                400,
                _status_body(
                    400,
                    "BadRequest",
                    f"manifest identity {body_namespace}/{body_name} does not "
                    f"match request path {route.namespace}/{route.name}",
                ),
            )
            return
        _, _, cls, _ = KIND_REGISTRY[route.kind]
        owner_key = (route.kind, route.namespace, route.name)
        claimed = _apply_leaf_paths(manifest)
        # the whole read-adjudicate-write sequence must be atomic under
        # ThreadingHTTPServer: without this, two concurrent non-force
        # applies from different managers both read a not-yet-written
        # owners map, both pass the conflict gate, and the last writer
        # silently takes fields the real apiserver would 409
        with self.server.apply_lock:  # type: ignore[attr-defined]
            self._apply_locked(route, cls, owner_key, claimed, manifest,
                               field_manager, force)

    def _apply_locked(
        self, route, cls, owner_key, claimed, manifest, field_manager, force
    ):
        try:
            current = None
            try:
                current = self.cluster.get(route.kind, route.namespace, route.name)
            except NotFoundError:
                pass
            if current is not None:
                # field-manager conflict semantics (the contract
                # ``DynamicClient.apply(force=...)`` is written
                # against, reference ``e2e/pkg/util/manifests.go:
                # 120-141`` Force: true): a field owned by a DIFFERENT
                # manager conflicts — 409 without force, ownership
                # takeover with it.  Value equality does not matter:
                # real SSA conflicts between appliers regardless of
                # the value being applied.
                owners = self.server.field_owners.get(owner_key, {})  # type: ignore[attr-defined]
                conflicts = sorted(
                    (path, owners[path])
                    for path in claimed
                    if owners.get(path) not in (None, field_manager)
                )
                if conflicts and not force:
                    detail = ", ".join(
                        f'conflict with "{manager}": .{path}'
                        for path, manager in conflicts
                    )
                    plural = "s" if len(conflicts) != 1 else ""
                    self._send(
                        409,
                        _status_body(
                            409,
                            "Conflict",
                            f"Apply failed with {len(conflicts)} "
                            f"conflict{plural}: {detail}",
                        ),
                    )
                    return
            if current is None:
                obj = from_wire(cls, manifest)
                denial = self._admit(route.kind, "CREATE", obj, None)
                if denial is not None:
                    self._send(403, _status_body(403, "Forbidden", denial))
                    return
                result = self.cluster.create(route.kind, obj)
                code = 201
            else:
                # apply over the live object (conflicts already
                # adjudicated above): deep-merge the manifest's fields
                # (maps merge, scalars/lists replace), on the CURRENT
                # resourceVersion so the storage update itself never
                # optimistic-locks
                merged = _deep_merge(_full_wire(route.kind, current), manifest)
                merged.setdefault("metadata", {})["resourceVersion"] = (
                    to_wire(current).get("metadata", {}).get("resourceVersion")
                )
                obj = from_wire(cls, merged)
                denial = self._admit(route.kind, "UPDATE", obj, current)
                if denial is not None:
                    self._send(403, _status_body(403, "Forbidden", denial))
                    return
                result = self.cluster.update(route.kind, obj)
                code = 200
        except Exception as err:
            self._send_error_status(err, f"{route.kind} {route.name}")
            return
        self.server.apply_managers[  # type: ignore[attr-defined]
            (route.kind, route.namespace, route.name)
        ] = field_manager
        # the applier now owns every field it claimed (including any
        # it took over with force)
        owned = self.server.field_owners.setdefault(owner_key, {})  # type: ignore[attr-defined]
        for path in claimed:
            owned[path] = field_manager
        self._send_obj(code, route.kind, result)

    def do_DELETE(self):
        route = _parse_path(urllib.parse.urlsplit(self.path).path)
        if route is None or not route.name:
            self._send(404, _status_body(404, "NotFound", "unknown path"))
            return
        try:
            self.cluster.delete(route.kind, route.namespace, route.name)
        except Exception as err:
            self._send_error_status(err, f"{route.kind} {route.name}")
            return
        # a deleted object's field ownership dies with it: a future
        # namesake starts with a clean managedFields slate
        with self.server.apply_lock:  # type: ignore[attr-defined]
            self.server.field_owners.pop(  # type: ignore[attr-defined]
                (route.kind, route.namespace, route.name), None
            )
            self.server.apply_managers.pop(  # type: ignore[attr-defined]
                (route.kind, route.namespace, route.name), None
            )
        self._send(200, _status_body(200, "Success", "deleted").replace(b"Failure", b"Success"))


class TestApiServer:
    """Lifecycle wrapper: ``with TestApiServer() as server:`` gives
    ``server.url`` for a RestClusterClient and ``server.cluster`` for
    direct state manipulation/assertions."""

    __test__ = False  # not a pytest collection target

    def __init__(
        self, cluster: FakeCluster | None = None, port: int = 0, ssa: bool = True
    ):
        self.cluster = cluster or FakeCluster()
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.cluster = self.cluster  # type: ignore[attr-defined]
        self._httpd.webhooks = {}  # type: ignore[attr-defined]
        # SSA apply support (do_PATCH); ssa=False answers 501 so the
        # DynamicClient's create-or-replace fallback can be exercised
        self._httpd.ssa_enabled = ssa  # type: ignore[attr-defined]
        # (kind, namespace, name) -> last apply fieldManager; only the
        # SSA route writes this, so tests can prove which branch ran
        self.apply_managers: dict[tuple[str, str, str], str] = {}
        self._httpd.apply_managers = self.apply_managers  # type: ignore[attr-defined]
        # (kind, namespace, name) -> {leaf field path -> fieldManager}:
        # enough managed-fields bookkeeping to say NO — overlapping
        # apply from a second manager is 409 without force, takeover
        # with it (the real apiserver's apply conflict contract)
        self.field_owners: dict[tuple[str, str, str], dict[str, str]] = {}
        self._httpd.field_owners = self.field_owners  # type: ignore[attr-defined]
        # serializes apply conflict adjudication (read owners → admit →
        # write → record owners) across handler threads
        self._httpd.apply_lock = threading.Lock()  # type: ignore[attr-defined]
        # pagination snapshots: initialized once here (not lazily per
        # request — the threaded server would race and drop one) and
        # keyed by a monotonic counter, never id(), which CPython can
        # reuse after GC and silently resume a stale token against the
        # wrong snapshot instead of 410ing
        self._httpd.list_snapshots = {}  # type: ignore[attr-defined]
        self._httpd.snapshots_lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd.snapshot_counter = itertools.count(1)  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    def register_validating_webhook(self, kind: str, url: str) -> None:
        """Route CREATE/UPDATE admission for ``kind`` through the
        webhook at ``url`` (the ValidatingWebhookConfiguration analog)."""
        self._httpd.webhooks[kind] = url  # type: ignore[attr-defined]

    def break_watches(self) -> None:
        """Expire every active watch stream with a 410 Gone ERROR
        event — the compaction/timeout fault real apiservers serve,
        which clients must answer with a fresh list+watch."""
        self._httpd.watch_generation = (  # type: ignore[attr-defined]
            getattr(self._httpd, "watch_generation", 0) + 1
        )

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "TestApiServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="test-apiserver"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self) -> "TestApiServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
