"""Shared informers and listers.

The analog of client-go's SharedInformerFactory machinery the
reference builds in its manager (``pkg/manager/manager.go:52-53``,
30 s resync) and consumes in every controller: a local cache kept in
sync by list+watch, event handlers with add/update/delete callbacks,
tombstones for deletions observed only through a relist
(``cache.DeletedFinalStateUnknown`` handling, reference
``pkg/controller/globalaccelerator/controller.go:113-127``), and
lister views for cheap cache reads.

One informer per kind is shared by all controllers (the factory
deduplicates), and all handler callbacks for a kind are delivered from
a single dispatch thread, preserving client-go's ordering guarantee.
The periodic resync re-lists and re-delivers every object as an
update(obj, obj) — the level-trigger safety net (SURVEY.md §5).

Lister reads return the cached objects themselves under the read-only
contract (the reconcile kernel deep-copies before mutation,
``agac_tpu/reconcile/reconcile.py``), matching the reference's
lister semantics.
"""

from __future__ import annotations

import queue as queue_mod
import threading
from dataclasses import dataclass
from typing import Any, Callable, Optional

from .. import clockseam, klog
from ..analysis import racecheck
from ..errors import NotFoundError
from ..observability import instruments
from .client import ClusterClient
from .objects import meta_namespace_key


@dataclass
class Tombstone:
    """Final-state-unknown marker for deletions observed via relist,
    the ``cache.DeletedFinalStateUnknown`` analog: handlers receive
    this instead of the live object and must unwrap ``.obj``."""

    key: str
    obj: Any


@dataclass
class _Handler:
    on_add: Optional[Callable[[Any], None]] = None
    on_update: Optional[Callable[[Any, Any], None]] = None
    on_delete: Optional[Callable[[Any], None]] = None


class SharedInformer:
    def __init__(
        self,
        client: ClusterClient,
        kind: str,
        resync_period: float = 30.0,
        clock: Optional[Callable[[], float]] = None,
    ):
        self._client = client
        self.kind = kind
        self._resync_period = resync_period
        # the clock seam (ISSUE 7): resync deadlines and the
        # resync-age gauge run on virtual time under the sim runtime
        self._clock = clock or clockseam.monotonic
        # racecheck seam: instrumented when the lock-order watchdog is
        # enabled — the store lock is acquired from the watch, dispatch
        # and every controller thread (via lister reads)
        self._lock = racecheck.make_lock(f"informer.{kind}")
        self._store: dict[str, Any] = {}
        self._handlers: list[_Handler] = []
        self._synced = threading.Event()
        # deltas flow through one queue to one dispatch thread so
        # handlers never run concurrently for the same informer
        self._deltas: queue_mod.Queue = queue_mod.Queue()
        self._started = False
        # observability (ISSUE 5): resync lag + store size as
        # collection-time views, list/watch failures as a counter.
        # -1 until the first successful relist — "never synced" must
        # not read as "freshly synced".
        self._last_relist = -1.0
        informer_metrics = instruments.informer_instruments()
        informer_metrics.resync_age.labels(kind=kind).set_function(
            lambda: (
                -1.0
                if self._last_relist < 0
                else max(0.0, self._clock() - self._last_relist)
            )
        )
        informer_metrics.items.labels(kind=kind).set_function(
            lambda: len(self._store)
        )
        self._m_listwatch_errors = informer_metrics.listwatch_errors.labels(kind=kind)

    # ---- registration --------------------------------------------------
    def add_event_handler(
        self,
        on_add: Optional[Callable[[Any], None]] = None,
        on_update: Optional[Callable[[Any, Any], None]] = None,
        on_delete: Optional[Callable[[Any], None]] = None,
    ) -> None:
        handler = _Handler(on_add, on_update, on_delete)
        # enqueue the synthetic adds while still holding the lock:
        # store mutation, handler snapshot and delta enqueue must be
        # atomic or a concurrently applied watch event can reach the
        # new handler before its (staler) synthetic add
        with self._lock:
            self._handlers.append(handler)
            for obj in self._store.values():
                self._deltas.put(("add", None, obj, [handler]))

    def has_synced(self) -> bool:
        return self._synced.is_set()

    # ---- lister reads --------------------------------------------------
    def get_by_key(self, key: str) -> Any:
        with self._lock:
            obj = self._store.get(key)
        if obj is None:
            raise NotFoundError(self.kind, key)
        return obj

    def list_all(self, namespace: Optional[str] = None) -> list[Any]:
        with self._lock:
            return [
                o
                for o in self._store.values()
                if namespace is None or o.metadata.namespace == namespace
            ]

    def lister(self) -> "Lister":
        return Lister(self)

    # ---- run loops -----------------------------------------------------
    def run(self, stop: threading.Event) -> None:
        """Start the watch and dispatch threads; returns immediately."""
        if not clockseam.threads_enabled():
            raise RuntimeError(
                "SharedInformer.run spawns watch/dispatch threads; under "
                "the sim's cooperative executor drive the informer with "
                "explicit relist/dispatch steps instead"
            )
        with self._lock:
            if self._started:
                return
            self._started = True
        threading.Thread(
            target=self._dispatch_loop, args=(stop,), daemon=True, name=f"informer-dispatch-{self.kind}"
        ).start()
        threading.Thread(
            target=self._watch_loop, args=(stop,), daemon=True, name=f"informer-watch-{self.kind}"
        ).start()

    def _watch_loop(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                rv = self._relist()
                self._synced.set()
                self._record_arrival(rv, (), relist=True)
                deadline = self._clock() + self._resync_period
                should_stop = lambda: stop.is_set() or self._clock() >= deadline
                for event in self._client.watch(self.kind, rv, should_stop):
                    self._apply(event.type, event.obj)
                    self._record_arrival(rv, (event,))
            except Exception as err:
                self._m_listwatch_errors.inc()
                klog.errorf("informer %s: list/watch failed: %s", self.kind, err)
                stop.wait(1.0)

    def _record_arrival(self, cursor: str, events: tuple, relist: bool = False) -> None:
        """Incident capture (ISSUE 19): list/watch arrivals are THE
        external input of the live informer plane — record them at the
        wire boundary, before dispatch fans out.  (The sim's
        cooperative pump records its own batches; this path only runs
        in the threaded live loop.)"""
        try:
            from ..sim.capture import active

            tap = active()
            if tap is not None:
                tap.record_informer_batch(
                    "live", self.kind, list(events),
                    cursor=cursor, relist=relist, delivered=len(events),
                )
        except Exception:
            pass  # the tap must never fail the watch loop

    def _relist(self) -> str:
        objs, rv = self._client.list(self.kind)
        fresh = {meta_namespace_key(o): o for o in objs}
        with self._lock:
            old = self._store
            self._store = fresh
            handlers = list(self._handlers)
            for key, obj in fresh.items():
                if key in old:
                    # resync: re-deliver as update(old, new) even if
                    # equal — the level-trigger safety net
                    self._deltas.put(("update", old[key], obj, handlers))
                else:
                    self._deltas.put(("add", None, obj, handlers))
            for key, obj in old.items():
                if key not in fresh:
                    self._deltas.put(("delete", None, Tombstone(key, obj), handlers))
        self._last_relist = self._clock()
        return rv

    def _apply(self, event_type: str, obj: Any) -> None:
        key = meta_namespace_key(obj)
        with self._lock:
            old = self._store.get(key)
            if event_type == "DELETED":
                self._store.pop(key, None)
            else:
                self._store[key] = obj
            handlers = list(self._handlers)
            if event_type == "DELETED":
                self._deltas.put(("delete", None, obj, handlers))
            elif old is None:
                self._deltas.put(("add", None, obj, handlers))
            else:
                self._deltas.put(("update", old, obj, handlers))

    def _dispatch_loop(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                delta = self._deltas.get(timeout=0.05)
            except queue_mod.Empty:
                continue
            self._dispatch_one(delta)

    def _dispatch_one(self, delta) -> None:
        action, old, obj, handlers = delta
        for h in handlers:
            try:
                if action == "add" and h.on_add:
                    h.on_add(obj)
                elif action == "update" and h.on_update:
                    h.on_update(old, obj)
                elif action == "delete" and h.on_delete:
                    h.on_delete(obj)
            except Exception as err:  # handler crash containment
                klog.errorf("informer %s: handler error: %s", self.kind, err)

    # ---- cooperative stepping (the sim runtime's seam, ISSUE 7) --------
    # The threaded run() above is wall-clock plumbing around exactly
    # these three steps; the sim scheduler calls them explicitly so
    # relist timing, event application and handler dispatch all happen
    # at deterministic points in virtual time.

    def sync_once(self) -> str:
        """One relist + synchronous handler dispatch; marks the
        informer synced and returns the list's resourceVersion (the
        watch cursor the sim pump resumes from)."""
        rv = self._relist()
        self._synced.set()
        self.drain_pending_deltas()
        return rv

    def apply_event(self, event) -> None:
        """Apply one watch event to the store and enqueue its handler
        delta (drained by ``drain_pending_deltas``)."""
        self._apply(event.type, event.obj)

    def drain_pending_deltas(self) -> int:
        """Dispatch every queued delta inline on the calling thread;
        returns how many were delivered."""
        delivered = 0
        while True:
            try:
                delta = self._deltas.get_nowait()
            except queue_mod.Empty:
                return delivered
            self._dispatch_one(delta)
            delivered += 1


class Lister:
    """Cache-backed reads, the client-go lister analog:
    ``lister.namespaced(ns).get(name)`` / ``.list()``."""

    def __init__(self, informer: SharedInformer, namespace: Optional[str] = None):
        self._informer = informer
        self._namespace = namespace

    def namespaced(self, namespace: str) -> "Lister":
        return Lister(self._informer, namespace)

    def get(self, name: str) -> Any:
        key = f"{self._namespace}/{name}" if self._namespace else name
        return self._informer.get_by_key(key)

    def list(self) -> list[Any]:
        return self._informer.list_all(self._namespace)


class SharedInformerFactory:
    """Deduplicates informers per kind and starts them together
    (the analog of ``informers.NewSharedInformerFactory`` +
    ``factory.Start``, reference ``pkg/manager/manager.go:52-72``)."""

    def __init__(
        self,
        client: ClusterClient,
        resync_period: float = 30.0,
        clock: Optional[Callable[[], float]] = None,
        sleep: Optional[Callable[[float], None]] = None,
    ):
        self._client = client
        self._resync_period = resync_period
        # clock/sleep seam (ISSUE 7): threaded through to every
        # informer and used by wait_for_cache_sync's poll below — the
        # last hard-coded time.sleep that would stall virtual time
        self._clock = clock or clockseam.monotonic
        self._sleep = sleep or clockseam.sleep
        self._informers: dict[str, SharedInformer] = {}
        self._lock = racecheck.make_lock("informer-factory")

    def informer(self, kind: str) -> SharedInformer:
        with self._lock:
            if kind not in self._informers:
                self._informers[kind] = SharedInformer(
                    self._client, kind, self._resync_period, clock=self._clock
                )
            return self._informers[kind]

    def informers(self) -> list[SharedInformer]:
        """Every informer built so far — the sim harness's pump walks
        them in deterministic (construction) order."""
        with self._lock:
            return list(self._informers.values())

    def start(self, stop: threading.Event) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.run(stop)

    def wait_for_cache_sync(self, stop: threading.Event, timeout: float = 30.0) -> bool:
        """Block until every started informer has synced
        (``cache.WaitForCacheSync`` analog)."""
        deadline = self._clock() + timeout
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            while not inf.has_synced():
                if stop.is_set() or self._clock() > deadline:
                    return False
                self._sleep(0.005)
        return True
