"""Wire serialization for typed objects.

The generic replacement for the reference's ~1,571 LoC of
code-generator output (SURVEY.md §2 row 17): every kind here is a
dataclass whose fields are snake_case in Python and camelCase on the
wire; ``to_wire``/``from_wire`` convert recursively using the
dataclass type hints, so new kinds (including CRDs) need no generated
clients — registering the dataclass is enough.

Conventions:
- ``None`` fields and empty collections are omitted from wire dicts
  (matching ``json:",omitempty"`` in the reference's Go types).
- A field may override its wire name via
  ``field(metadata={"wire": "name"})``.
- Unknown wire keys are ignored on decode (forward compatibility).
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Type, TypeVar, get_args, get_origin, get_type_hints

T = TypeVar("T")

_hints_cache: dict[type, dict[str, Any]] = {}  # agac-lint: ignore[shared-state-census] -- idempotent get_type_hints memo; racing writers store identical values


def _snake_to_camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(part.title() for part in rest)


def _wire_name(f: dataclasses.Field) -> str:
    return f.metadata.get("wire", _snake_to_camel(f.name))


def _type_hints(cls: type) -> dict[str, Any]:
    if cls not in _hints_cache:
        _hints_cache[cls] = get_type_hints(cls)
    return _hints_cache[cls]


def to_wire(obj: Any) -> Any:
    """Recursively convert a dataclass instance to a wire-format dict."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            value = getattr(obj, f.name)
            if value is None:
                continue
            if isinstance(value, (list, dict)) and not value:
                continue
            out[_wire_name(f)] = to_wire(value)
        return out
    if isinstance(obj, list):
        return [to_wire(v) for v in obj]
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    return obj


def _unwrap_optional(hint: Any) -> Any:
    if get_origin(hint) in (typing.Union, getattr(__import__("types"), "UnionType", ())):
        args = [a for a in get_args(hint) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return hint


def _decode(hint: Any, value: Any) -> Any:
    hint = _unwrap_optional(hint)
    origin = get_origin(hint)
    if value is None:
        return None
    if dataclasses.is_dataclass(hint):
        return from_wire(hint, value)
    if origin is list:
        (item_hint,) = get_args(hint) or (Any,)
        return [_decode(item_hint, v) for v in value]
    if origin is dict:
        args = get_args(hint)
        value_hint = args[1] if len(args) == 2 else Any
        return {k: _decode(value_hint, v) for k, v in value.items()}
    return value


def from_wire(cls: Type[T], data: dict | None) -> T:
    """Build a dataclass instance of ``cls`` from a wire-format dict.

    Missing keys fall back to the dataclass defaults; unknown keys are
    ignored.
    """
    data = data or {}
    hints = _type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        wire = _wire_name(f)
        if wire in data:
            kwargs[f.name] = _decode(hints[f.name], data[wire])
    return cls(**kwargs)
