"""The cluster client interface.

The seam between controllers and the apiserver — implemented by the
in-memory ``FakeCluster`` (tests, local e2e) and the REST client
(real clusters).  The reference talks to kube-apiserver through
client-go's clientset + the generated CRD clientset (SURVEY.md §2
rows 4, 17); this interface is the union of the operations the
framework actually uses: typed CRUD, status updates, list+watch for
informers, and event creation for the recorder.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Any, Callable, Iterator, Optional


@dataclass
class WatchEvent:
    """One watch-stream entry: type is ADDED | MODIFIED | DELETED."""

    type: str
    obj: Any


class ClusterClient(abc.ABC):
    """Typed object CRUD + watch against a cluster.

    ``kind`` is the object KIND string (e.g. "Service"); lookups raise
    ``agac_tpu.errors.NotFoundError`` when the object does not exist.
    """

    @abc.abstractmethod
    def get(self, kind: str, namespace: str, name: str) -> Any: ...

    @abc.abstractmethod
    def list(self, kind: str, namespace: Optional[str] = None) -> tuple[list[Any], str]:
        """Returns (objects, resource_version) — the rv anchors a watch."""

    @abc.abstractmethod
    def create(self, kind: str, obj: Any) -> Any: ...

    @abc.abstractmethod
    def update(self, kind: str, obj: Any) -> Any:
        """Update spec/metadata.  Clearing the last finalizer of an
        object already marked for deletion completes the delete, as the
        real apiserver does (the EndpointGroupBinding finalizer flow,
        reference ``pkg/controller/endpointgroupbinding/reconcile.go:36-64``,
        depends on this)."""

    @abc.abstractmethod
    def update_status(self, kind: str, obj: Any) -> Any:
        """Update only the status subresource (spec/metadata unchanged),
        like the CRD's ``UpdateStatus`` (reference ``reconcile.go:207-209``)."""

    @abc.abstractmethod
    def delete(self, kind: str, namespace: str, name: str) -> None:
        """Delete, honoring finalizers: an object with finalizers gets
        ``metadata.deletionTimestamp`` set and is MODIFIED, not removed."""

    @abc.abstractmethod
    def watch(
        self, kind: str, resource_version: str, stop: Callable[[], bool]
    ) -> Iterator[WatchEvent]:
        """Stream events after ``resource_version`` until ``stop()``."""
