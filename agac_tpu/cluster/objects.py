"""Typed Kubernetes object model — the subset of kinds the framework
watches or writes, as plain dataclasses.

The analog of the k8s.io/api types the reference imports (corev1
Service, networkingv1 Ingress, coordination Lease, corev1 Event) plus
object-key helpers mirroring ``cache.MetaNamespaceKeyFunc`` /
``cache.SplitMetaNamespaceKey`` that the reference uses for queue keys
(e.g. ``pkg/controller/globalaccelerator/controller.go:175-191``).

Every kind carries an ``ObjectMeta`` and declares its ``KIND``; deep
copies go through ``copy.deepcopy`` (the DeepCopyObject analog —
plain data, no back references).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..errors import NoRetryError


# ---------------------------------------------------------------------------
# metadata and keys
# ---------------------------------------------------------------------------


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: str = ""
    generation: int = 0
    creation_timestamp: Optional[str] = None
    deletion_timestamp: Optional[str] = None
    annotations: dict[str, str] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    finalizers: list[str] = field(default_factory=list)


def meta_namespace_key(obj) -> str:
    """``<namespace>/<name>`` (or ``<name>`` for cluster-scoped)."""
    meta = obj.metadata if hasattr(obj, "metadata") else obj
    if meta.namespace:
        return f"{meta.namespace}/{meta.name}"
    return meta.name


def split_meta_namespace_key(key: str) -> tuple[str, str]:
    """Split ``ns/name`` → (ns, name); a bare name has empty ns.

    Raises NoRetryError on malformed keys, which the reconcile kernel
    logs without requeueing — the behavior the reference gets from
    ``NewNoRetryErrorf("invalid resource key: ...")``
    (e.g. ``pkg/controller/globalaccelerator/service.go:32-34``).
    """
    parts = key.split("/")
    if len(parts) == 1:
        return "", parts[0]
    if len(parts) == 2:
        return parts[0], parts[1]
    raise NoRetryError(f"invalid resource key: {key}")


# ---------------------------------------------------------------------------
# core/v1 Service
# ---------------------------------------------------------------------------


@dataclass
class ServicePort:
    name: str = ""
    protocol: str = "TCP"
    port: int = 0
    target_port: Optional[int] = None
    node_port: Optional[int] = None


@dataclass
class ServiceSpec:
    type: str = "ClusterIP"
    ports: list[ServicePort] = field(default_factory=list)
    load_balancer_class: Optional[str] = None


@dataclass
class PortStatus:
    port: int = 0
    protocol: str = "TCP"
    error: Optional[str] = None


@dataclass
class LoadBalancerIngress:
    ip: str = ""
    hostname: str = ""
    ports: list[PortStatus] = field(default_factory=list)


@dataclass
class LoadBalancerStatus:
    ingress: list[LoadBalancerIngress] = field(default_factory=list)


@dataclass
class ServiceStatus:
    load_balancer: LoadBalancerStatus = field(default_factory=LoadBalancerStatus)


@dataclass
class Service:
    KIND = "Service"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    status: ServiceStatus = field(default_factory=ServiceStatus)


# ---------------------------------------------------------------------------
# networking/v1 Ingress
# ---------------------------------------------------------------------------


@dataclass
class ServiceBackendPort:
    name: str = ""
    number: int = 0


@dataclass
class IngressServiceBackend:
    name: str = ""
    port: ServiceBackendPort = field(default_factory=ServiceBackendPort)


@dataclass
class IngressBackend:
    service: Optional[IngressServiceBackend] = None


@dataclass
class HTTPIngressPath:
    path: str = ""
    path_type: str = "Prefix"
    backend: IngressBackend = field(default_factory=IngressBackend)


@dataclass
class HTTPIngressRuleValue:
    paths: list[HTTPIngressPath] = field(default_factory=list)


@dataclass
class IngressRule:
    host: str = ""
    http: Optional[HTTPIngressRuleValue] = None


@dataclass
class IngressSpec:
    ingress_class_name: Optional[str] = None
    default_backend: Optional[IngressBackend] = None
    rules: list[IngressRule] = field(default_factory=list)


@dataclass
class IngressLoadBalancerIngress:
    ip: str = ""
    hostname: str = ""
    ports: list[PortStatus] = field(default_factory=list)


@dataclass
class IngressLoadBalancerStatus:
    ingress: list[IngressLoadBalancerIngress] = field(default_factory=list)


@dataclass
class IngressStatus:
    load_balancer: IngressLoadBalancerStatus = field(default_factory=IngressLoadBalancerStatus)


@dataclass
class Ingress:
    KIND = "Ingress"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: IngressSpec = field(default_factory=IngressSpec)
    status: IngressStatus = field(default_factory=IngressStatus)


# ---------------------------------------------------------------------------
# core/v1 Event (the recorder's output; SURVEY.md §5 observability)
# ---------------------------------------------------------------------------


@dataclass
class ObjectReference:
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""


@dataclass
class EventSource:
    component: str = ""


@dataclass
class Event:
    KIND = "Event"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: ObjectReference = field(default_factory=ObjectReference)
    reason: str = ""
    message: str = ""
    type: str = "Normal"
    count: int = 1
    source: EventSource = field(default_factory=EventSource)
    first_timestamp: Optional[str] = None
    last_timestamp: Optional[str] = None


# ---------------------------------------------------------------------------
# coordination/v1 Lease (leader election; SURVEY.md §2 row 2)
# ---------------------------------------------------------------------------


@dataclass
class LeaseSpec:
    holder_identity: Optional[str] = None
    lease_duration_seconds: Optional[int] = None
    acquire_time: Optional[str] = None
    renew_time: Optional[str] = None
    lease_transitions: int = 0


@dataclass
class Lease:
    KIND = "Lease"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaseSpec = field(default_factory=LeaseSpec)


# ---------------------------------------------------------------------------
# shared condition type (used by CRD status)
# ---------------------------------------------------------------------------


@dataclass
class Condition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""
    last_transition_time: Optional[str] = None
