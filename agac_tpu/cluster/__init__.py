"""Cluster I/O layer: typed Kubernetes objects, a client interface
with a fake in-memory apiserver, shared informers with listers, and an
event recorder.

The analog of the reference's use of client-go informers/listers and
its generated CRD clientset (SURVEY.md §2 rows 4, 17), built as one
generic machine: any registered kind gets storage, watches, informers,
and listers for free.
"""

from .objects import (
    Condition,
    Event,
    Ingress,
    IngressBackend,
    IngressLoadBalancerIngress,
    IngressRule,
    IngressServiceBackend,
    HTTPIngressPath,
    HTTPIngressRuleValue,
    Lease,
    LeaseSpec,
    LoadBalancerIngress,
    ObjectMeta,
    PortStatus,
    Service,
    ServiceBackendPort,
    ServicePort,
    meta_namespace_key,
    split_meta_namespace_key,
)
from .client import ClusterClient, WatchEvent
from .fake import FakeCluster
from .informer import Lister, SharedInformer, SharedInformerFactory, Tombstone
from .record import EventRecorder

__all__ = [
    "ObjectMeta",
    "Service",
    "ServicePort",
    "LoadBalancerIngress",
    "PortStatus",
    "Ingress",
    "IngressRule",
    "IngressBackend",
    "IngressServiceBackend",
    "IngressLoadBalancerIngress",
    "HTTPIngressPath",
    "HTTPIngressRuleValue",
    "ServiceBackendPort",
    "Event",
    "Lease",
    "LeaseSpec",
    "Condition",
    "meta_namespace_key",
    "split_meta_namespace_key",
    "ClusterClient",
    "WatchEvent",
    "FakeCluster",
    "SharedInformer",
    "SharedInformerFactory",
    "Lister",
    "Tombstone",
    "EventRecorder",
]
