"""Dynamic (untyped) client + server-side apply.

The analog of the reference's e2e manifest helpers
(``e2e/pkg/util/manifests.go:72-141``, duplicated at
``local_e2e/pkg/fixtures/manifests.go:72-131``): parse arbitrary YAML
manifests and apply them to an apiserver without typed clients — used
by the real-cluster e2e tier (``tests/test_kind_e2e.py``) to install
the CRD, RBAC, and ValidatingWebhookConfiguration exactly the way
``kubectl apply --server-side`` would.

Apply strategy, like the reference's ``Patch(..., types.ApplyPatchType)``:
server-side apply (``PATCH`` with ``application/apply-patch+yaml`` and
a field manager, force=true).  Servers without SSA (the in-repo test
apiserver) get a create-or-replace fallback so the tier's own logic
stays testable offline.
"""

from __future__ import annotations

import json
from typing import Optional

from .rest import RestClusterClient

# (apiVersion, kind) → plural for everything this repo's manifests and
# e2e tiers touch.  A real dynamic client would use API discovery; a
# static table keeps the client dependency-free and is exactly as
# wide as the manifests we ship (config/, charts/).
WELL_KNOWN_PLURALS: dict[tuple[str, str], str] = {
    ("v1", "Service"): "services",
    ("v1", "ServiceAccount"): "serviceaccounts",
    ("v1", "Namespace"): "namespaces",
    ("v1", "ConfigMap"): "configmaps",
    ("v1", "Secret"): "secrets",
    ("v1", "Event"): "events",
    ("v1", "Pod"): "pods",
    ("apps/v1", "Deployment"): "deployments",
    ("networking.k8s.io/v1", "Ingress"): "ingresses",
    ("coordination.k8s.io/v1", "Lease"): "leases",
    ("rbac.authorization.k8s.io/v1", "Role"): "roles",
    ("rbac.authorization.k8s.io/v1", "RoleBinding"): "rolebindings",
    ("rbac.authorization.k8s.io/v1", "ClusterRole"): "clusterroles",
    ("rbac.authorization.k8s.io/v1", "ClusterRoleBinding"): "clusterrolebindings",
    ("apiextensions.k8s.io/v1", "CustomResourceDefinition"): "customresourcedefinitions",
    (
        "admissionregistration.k8s.io/v1",
        "ValidatingWebhookConfiguration",
    ): "validatingwebhookconfigurations",
    ("operator.h3poteto.dev/v1alpha1", "EndpointGroupBinding"): "endpointgroupbindings",
    # shipped by the Helm chart's webhook template (cert-manager path)
    ("cert-manager.io/v1", "Certificate"): "certificates",
}

CLUSTER_SCOPED_KINDS = {
    "Namespace",
    "ClusterRole",
    "ClusterRoleBinding",
    "CustomResourceDefinition",
    "ValidatingWebhookConfiguration",
}

DEFAULT_FIELD_MANAGER = "aws-global-accelerator-controller"


class DynamicApplyError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


def _yaml():
    import yaml

    return yaml


class DynamicClient:
    """Untyped CRUD + apply over a ``RestClusterClient``'s transport
    (shares its base URL, TLS and credentials)."""

    def __init__(self, rest: RestClusterClient):
        self._rest = rest

    # ------------------------------------------------------------------
    # paths
    # ------------------------------------------------------------------
    @staticmethod
    def _collection_path(manifest: dict) -> str:
        api_version = manifest.get("apiVersion", "")
        kind = manifest.get("kind", "")
        plural = WELL_KNOWN_PLURALS.get((api_version, kind))
        if plural is None:
            raise ValueError(f"no known plural for {api_version}/{kind}")
        prefix = "api/v1" if api_version == "v1" else f"apis/{api_version}"
        if kind in CLUSTER_SCOPED_KINDS:
            return f"{prefix}/{plural}"
        namespace = manifest.get("metadata", {}).get("namespace") or "default"
        return f"{prefix}/namespaces/{namespace}/{plural}"

    @classmethod
    def _object_path(cls, manifest: dict) -> str:
        name = manifest.get("metadata", {}).get("name")
        if not name:
            raise ValueError("manifest has no metadata.name")
        return f"{cls._collection_path(manifest)}/{name}"

    # ------------------------------------------------------------------
    # operations
    # ------------------------------------------------------------------
    def get(self, manifest: dict) -> Optional[dict]:
        """Current object for a manifest-shaped reference, or None."""
        status, body = self._rest.raw_request("GET", self._object_path(manifest))
        if status == 404:
            return None
        if status >= 300:
            raise DynamicApplyError(status, body.decode(errors="replace"))
        return json.loads(body)

    def apply(
        self,
        manifest: dict,
        field_manager: str = DEFAULT_FIELD_MANAGER,
        force: bool = True,
    ) -> dict:
        """Server-side apply; create-or-replace fallback on servers
        without SSA support (405/415/501 from the PATCH verb — genuine
        SSA rejections like 400/403/409/422 propagate).

        ``force=True`` (the default, matching the reference's
        ``Force: true``, ``e2e/pkg/util/manifests.go:120-141``) takes
        ownership of fields held by other field managers; with
        ``force=False`` an overlapping apply surfaces the server's
        409 Conflict as ``DynamicApplyError``."""
        path = (
            f"{self._object_path(manifest)}"
            f"?fieldManager={field_manager}&force={'true' if force else 'false'}"
        )
        status, body = self._rest.raw_request(
            "PATCH",
            path,
            _yaml().safe_dump(manifest).encode(),
            content_type="application/apply-patch+yaml",
        )
        if status < 300:
            return json.loads(body)
        if status in (405, 415, 501):
            # server has no SSA PATCH route (the in-repo test
            # apiserver); genuine SSA rejections (400/403/409/422)
            # propagate untouched
            return self._create_or_replace(manifest)
        raise DynamicApplyError(status, body.decode(errors="replace"))

    def _create_or_replace(self, manifest: dict) -> dict:
        current = self.get(manifest)
        if current is None:
            status, body = self._rest.raw_request(
                "POST",
                self._collection_path(manifest),
                json.dumps(manifest).encode(),
            )
        else:
            replacement = dict(manifest)
            metadata = dict(replacement.get("metadata", {}))
            metadata["resourceVersion"] = current["metadata"].get("resourceVersion")
            replacement["metadata"] = metadata
            status, body = self._rest.raw_request(
                "PUT",
                self._object_path(manifest),
                json.dumps(replacement).encode(),
            )
        if status >= 300:
            raise DynamicApplyError(status, body.decode(errors="replace"))
        return json.loads(body)

    def delete(self, manifest: dict) -> None:
        status, body = self._rest.raw_request("DELETE", self._object_path(manifest))
        if status >= 300 and status != 404:
            raise DynamicApplyError(status, body.decode(errors="replace"))

    # ------------------------------------------------------------------
    # YAML entry points (multi-document, like kubectl apply -f)
    # ------------------------------------------------------------------
    def apply_yaml(self, text: str, field_manager: str = DEFAULT_FIELD_MANAGER) -> list[dict]:
        applied = []
        for doc in _yaml().safe_load_all(text):
            if doc:
                applied.append(self.apply(doc, field_manager))
        return applied

    def apply_file(self, path: str, field_manager: str = DEFAULT_FIELD_MANAGER) -> list[dict]:
        with open(path) as fh:
            return self.apply_yaml(fh.read(), field_manager)

    def delete_yaml(self, text: str) -> None:
        for doc in _yaml().safe_load_all(text):
            if doc:
                self.delete(doc)
