"""REST client for a real kube-apiserver.

The production counterpart of ``FakeCluster``: the same
``ClusterClient`` interface implemented over the Kubernetes HTTP API
with nothing but the standard library (urllib + ssl), covering the
operations the framework uses — typed CRUD, status subresource
updates, and streaming watches.  The analog of the reference's
client-go clientset + generated CRD clientset (SURVEY.md §2 rows 4,
17) and of ``clientcmd.BuildConfigFromFlags`` kubeconfig resolution
(``cmd/controller/controller.go:50,84-98``).

Transport is injectable for tests: ``transport(method, url, headers,
body, timeout, stream)`` returns ``(status, body_bytes)`` or, when
``stream=True``, ``(status, line_iterator)``.
"""

from __future__ import annotations

import base64
import json
import os
import socket
import ssl
import tempfile
import urllib.error
import urllib.parse
import threading
import time
import urllib.request
from typing import Any, Callable, Iterator, Optional

from .. import klog
from ..apis.endpointgroupbinding import EndpointGroupBinding
from ..errors import AlreadyExistsError, ConflictError, NotFoundError
from .client import ClusterClient, WatchEvent
from .objects import Event, Ingress, Lease, Service
from .serde import from_wire, to_wire

# client-go reflectors list in pages of 500 (WatchListPageSize default)
LIST_PAGE_SIZE = 500

# kind -> (api prefix, plural, type, apiVersion string)
KIND_REGISTRY: dict[str, tuple[str, str, type, str]] = {
    "Service": ("api/v1", "services", Service, "v1"),
    "Event": ("api/v1", "events", Event, "v1"),
    "Ingress": (
        "apis/networking.k8s.io/v1",
        "ingresses",
        Ingress,
        "networking.k8s.io/v1",
    ),
    "Lease": (
        "apis/coordination.k8s.io/v1",
        "leases",
        Lease,
        "coordination.k8s.io/v1",
    ),
    "EndpointGroupBinding": (
        "apis/operator.h3poteto.dev/v1alpha1",
        "endpointgroupbindings",
        EndpointGroupBinding,
        "operator.h3poteto.dev/v1alpha1",
    ),
}


class ClusterAPIError(Exception):
    def __init__(self, status: int, message: str):
        self.status = status
        super().__init__(f"apiserver returned {status}: {message}")


def _raise_for_status(status: int, body: bytes, context: str) -> None:
    message = ""
    try:
        message = json.loads(body).get("message", "")
    except Exception:
        message = body[:200].decode(errors="replace")
    if status == 404:
        raise NotFoundError("", context)
    if status == 409:
        if "already exists" in message:
            raise AlreadyExistsError(message)
        raise ConflictError(message)
    raise ClusterAPIError(status, message or context)


class RestClusterClient(ClusterClient):
    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        ssl_context: Optional[ssl.SSLContext] = None,
        transport: Optional[Callable] = None,
        token_provider: Optional[Callable[[], Optional[str]]] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self._token = token
        # dynamic credentials (exec plugins, rotated token files)
        # re-resolved per request; wins over the static token
        self._token_provider = token_provider
        self._ssl_context = ssl_context
        self._transport = transport or self._default_transport

    # ------------------------------------------------------------------
    # transport
    # ------------------------------------------------------------------
    def _default_transport(self, method, url, headers, body, timeout, stream):
        request = urllib.request.Request(url, data=body, headers=headers, method=method)
        try:
            response = urllib.request.urlopen(
                request, timeout=timeout, context=self._ssl_context
            )
        except urllib.error.HTTPError as err:
            return err.code, err.read()
        if stream:
            # file-like: the watch loop reads lines itself so it can
            # poll stop() on idle-read timeouts
            return response.status, response
        with response:
            return response.status, response.read()

    def _request(
        self, method: str, path: str, body: Optional[dict] = None, timeout: float = 30.0, stream: bool = False
    ):
        url = f"{self.base_url}/{path}"
        headers = {"Accept": "application/json"}
        token = self._token_provider() if self._token_provider else self._token
        if token:
            headers["Authorization"] = f"Bearer {token}"
        data = None
        if body is not None:
            headers["Content-Type"] = "application/json"
            data = json.dumps(body).encode()
        return self._send_with_auth_retry(method, url, headers, data, timeout, stream)

    def _send_with_auth_retry(self, method, url, headers, data, timeout, stream):
        status, payload = self._transport(method, url, headers, data, timeout, stream)
        if status == 401 and self._token_provider is not None:
            # the server rejected the cached credential (early
            # revocation, clock skew): force a refresh and retry once,
            # like client-go's exec authenticator
            invalidate = getattr(self._token_provider, "invalidate", None)
            if invalidate is not None:
                invalidate()
                token = self._token_provider()
                if token:
                    headers["Authorization"] = f"Bearer {token}"
                else:
                    # refresh yielded nothing — never resend the header
                    # the server just rejected
                    headers.pop("Authorization", None)
                status, payload = self._transport(
                    method, url, headers, data, timeout, stream
                )
        return status, payload

    def raw_request(
        self,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        content_type: str = "application/json",
        timeout: float = 30.0,
    ) -> tuple[int, bytes]:
        """Untyped request sharing this client's base URL, TLS and
        credentials — the escape hatch the dynamic client
        (``cluster/dynamic.py``) builds on for kinds outside
        ``KIND_REGISTRY``.  Returns ``(status, body)`` without raising.
        Shares ``request()``'s 401 invalidate-and-retry path so a
        rotated service-account token refreshes instead of surfacing
        as a hard error in long e2e runs."""
        url = f"{self.base_url}/{path.lstrip('/')}"
        headers = {"Accept": "application/json"}
        token = self._token_provider() if self._token_provider else self._token
        if token:
            headers["Authorization"] = f"Bearer {token}"
        if body is not None:
            headers["Content-Type"] = content_type
        return self._send_with_auth_retry(method, url, headers, body, timeout, False)

    # ------------------------------------------------------------------
    # paths and serde
    # ------------------------------------------------------------------
    @staticmethod
    def _kind_info(kind: str):
        info = KIND_REGISTRY.get(kind)
        if info is None:
            raise ValueError(f"unregistered kind: {kind}")
        return info

    def _collection_path(self, kind: str, namespace: Optional[str]) -> str:
        prefix, plural, _, _ = self._kind_info(kind)
        if namespace:
            return f"{prefix}/namespaces/{namespace}/{plural}"
        return f"{prefix}/{plural}"

    def _object_path(self, kind: str, namespace: str, name: str) -> str:
        return f"{self._collection_path(kind, namespace)}/{name}"

    def _encode(self, kind: str, obj: Any) -> dict:
        _, _, _, api_version = self._kind_info(kind)
        wire = to_wire(obj)
        wire["apiVersion"] = api_version
        wire["kind"] = kind
        return wire

    def _decode(self, kind: str, data: dict) -> Any:
        _, _, cls, _ = self._kind_info(kind)
        return from_wire(cls, data)

    # ------------------------------------------------------------------
    # ClusterClient
    # ------------------------------------------------------------------
    def get(self, kind: str, namespace: str, name: str) -> Any:
        path = self._object_path(kind, namespace, name)
        status, body = self._request("GET", path)
        if status >= 300:
            _raise_for_status(status, body, f"{kind} {namespace}/{name}")
        return self._decode(kind, json.loads(body))

    def list(self, kind: str, namespace: Optional[str] = None) -> tuple[list[Any], str]:
        """Chunked list, the way client-go reflectors do it: page
        through ``limit``/``continue`` so a large collection never
        arrives as one giant response."""
        base = self._collection_path(kind, namespace)
        items: list[Any] = []
        token = ""
        restarted = False
        while True:
            query = f"?limit={LIST_PAGE_SIZE}"
            if token:
                query += f"&continue={urllib.parse.quote(token)}"
            status, body = self._request("GET", base + query)
            if status == 410 and token and not restarted:
                # continue token expired (apiserver compaction):
                # restart the whole list once, like client-go's pager
                items, token, restarted = [], "", True
                continue
            if status >= 300:
                _raise_for_status(status, body, f"list {kind}")
            payload = json.loads(body)
            items.extend(self._decode(kind, item) for item in payload.get("items", []))
            metadata = payload.get("metadata") or {}
            token = metadata.get("continue") or ""
            if not token:
                return items, metadata.get("resourceVersion", "")

    def create(self, kind: str, obj: Any) -> Any:
        path = self._collection_path(kind, obj.metadata.namespace or None)
        status, body = self._request("POST", path, self._encode(kind, obj))
        if status >= 300:
            _raise_for_status(status, body, f"create {kind}")
        return self._decode(kind, json.loads(body))

    def update(self, kind: str, obj: Any) -> Any:
        path = self._object_path(kind, obj.metadata.namespace, obj.metadata.name)
        status, body = self._request("PUT", path, self._encode(kind, obj))
        if status >= 300:
            _raise_for_status(status, body, f"update {kind}")
        return self._decode(kind, json.loads(body))

    def update_status(self, kind: str, obj: Any) -> Any:
        path = self._object_path(kind, obj.metadata.namespace, obj.metadata.name) + "/status"
        status, body = self._request("PUT", path, self._encode(kind, obj))
        if status >= 300:
            _raise_for_status(status, body, f"update status {kind}")
        return self._decode(kind, json.loads(body))

    def delete(self, kind: str, namespace: str, name: str) -> None:
        path = self._object_path(kind, namespace, name)
        status, body = self._request("DELETE", path)
        if status >= 300:
            _raise_for_status(status, body, f"delete {kind} {namespace}/{name}")

    # watch stream tuning: the server closes the stream politely after
    # WATCH_SERVER_TIMEOUT (a clean relist boundary); the short socket
    # timeout is only a stop()-polling interval — an idle read timeout
    # resumes the same stream, so quiet clusters do NOT trigger
    # relist/resync storms.
    WATCH_SERVER_TIMEOUT = 240
    WATCH_POLL_INTERVAL = 5.0

    def watch(
        self, kind: str, resource_version: str, stop: Callable[[], bool]
    ) -> Iterator[WatchEvent]:
        """One watch stream.  A normally ended stream returns (the
        informer relists and re-watches); hard failures — connect
        errors, non-2xx — RAISE so the informer's error path applies
        its backoff instead of relisting in a tight loop."""
        query = urllib.parse.urlencode(
            {
                "watch": "true",
                "resourceVersion": resource_version or "0",
                "timeoutSeconds": str(self.WATCH_SERVER_TIMEOUT),
            }
        )
        path = f"{self._collection_path(kind, None)}?{query}"
        status, stream = self._request(
            "GET", path, timeout=self.WATCH_POLL_INTERVAL, stream=True
        )
        if status >= 300:
            raise ClusterAPIError(status, f"watch {kind}")
        try:
            while not stop():
                try:
                    line = stream.readline()
                except socket.timeout:
                    continue  # idle: poll stop() and keep the stream
                except (TimeoutError, ssl.SSLError) as err:
                    if "timed out" in str(err).lower():
                        continue
                    raise
                if not line:
                    return  # server closed; informer relists
                if not line.strip():
                    continue
                try:
                    payload = json.loads(line)
                except ValueError:
                    # a line truncated by a mid-read timeout parses as
                    # garbage; skipping is safe — the next relist
                    # (level trigger) recovers any lost event
                    continue
                event_type = payload.get("type", "")
                if event_type == "BOOKMARK":
                    continue
                if event_type == "ERROR":
                    # e.g. 410 Gone — return so the informer relists
                    # at a fresh resourceVersion
                    klog.errorf("watch %s: %r", kind, payload.get("object"))
                    return
                obj = self._decode(kind, payload.get("object") or {})
                yield WatchEvent(event_type, obj)
        except (urllib.error.URLError, ConnectionError, OSError) as err:
            klog.v(4).infof("watch %s: stream ended: %s", kind, err)
        finally:
            try:
                stream.close()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# kubeconfig / in-cluster config resolution
# ---------------------------------------------------------------------------


def _b64_to_tempfile(data_b64: str, suffix: str) -> str:
    raw = base64.b64decode(data_b64)
    handle = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
    handle.write(raw)
    handle.close()
    return handle.name


class ExecCredentialProvider:
    """client.authentication.k8s.io exec-plugin credentials — how
    kubectl authenticates to EKS (``aws eks get-token``).  Runs the
    configured command, parses the ExecCredential JSON, caches the
    token until its expirationTimestamp (re-execs ~1 min early)."""

    def __init__(self, exec_spec: dict, timeout: float = 60.0):
        self._spec = exec_spec
        self._timeout = timeout
        self._lock = threading.Lock()
        self._token: Optional[str] = None
        self._expires: float = 0.0

    def __call__(self) -> Optional[str]:
        with self._lock:
            if self._token is not None and (
                self._expires == 0.0 or time.time() < self._expires - 60
            ):
                return self._token
            self._token, self._expires = self._fetch()
            return self._token

    def invalidate(self) -> None:
        """Drop the cached token so the next call re-execs — the
        client retries once with a fresh credential when the server
        rejects the cached one (early revocation, clock skew)."""
        with self._lock:
            self._token = None
            self._expires = 0.0

    def _fetch(self) -> tuple[Optional[str], float]:
        import subprocess

        command = [self._spec["command"]] + list(self._spec.get("args") or [])
        env = dict(os.environ)
        for pair in self._spec.get("env") or []:
            env[pair["name"]] = pair["value"]
        try:
            result = subprocess.run(
                command, env=env, capture_output=True, text=True, timeout=self._timeout
            )
        except subprocess.TimeoutExpired as err:
            raise ClusterAPIError(
                401,
                f"exec credential plugin {command[0]!r} timed out after {self._timeout}s",
            ) from err
        if result.returncode != 0:
            raise ClusterAPIError(
                401,
                f"exec credential plugin {command[0]!r} failed: {result.stderr.strip()}",
            )
        try:
            credential = json.loads(result.stdout)
        except ValueError as err:
            raise ClusterAPIError(
                401,
                f"exec credential plugin {command[0]!r} printed invalid JSON",
            ) from err
        status = credential.get("status") or {}
        token = status.get("token")
        raw_expiry = status.get("expirationTimestamp")
        if not raw_expiry:
            return token, 0.0  # no expiry advertised: cache for the process
        import datetime

        try:
            expires = datetime.datetime.fromisoformat(
                raw_expiry.replace("Z", "+00:00")
            ).timestamp()
        except ValueError:
            # unparseable expiry must fail STALE (re-exec next call),
            # never "never expires"
            expires = time.time()
        return token, expires


class TokenFileProvider:
    """Rotated token files (projected SA tokens).  The token is cached
    for a short TTL like client-go's file-token cache (~1 min) instead
    of paying an open/read/close on every API request; ``invalidate``
    forces a re-read, which wires token files into the client's
    401-refresh retry."""

    def __init__(self, path: str, ttl: float = 60.0):
        self._path = path
        self._ttl = ttl
        self._lock = threading.Lock()
        self._token: Optional[str] = None
        self._fresh_until = 0.0

    def __call__(self) -> Optional[str]:
        with self._lock:
            now = time.time()
            if self._token is not None and now < self._fresh_until:
                return self._token
            try:
                with open(self._path) as fh:
                    self._token = fh.read().strip()
            except OSError as err:
                if self._token is not None:
                    # transient rotate failure: keep serving the cached
                    # token (client-go's cachingTokenSource does the
                    # same); invalidate() clears it, so real auth
                    # failures still surface through the 401 path
                    klog.warningf(
                        "token file %s unreadable, serving cached token: %s",
                        self._path,
                        err,
                    )
                    return self._token
                raise ClusterAPIError(
                    401, f"token file {self._path!r} unreadable: {err}"
                ) from err
            self._fresh_until = now + self._ttl
            return self._token

    def invalidate(self) -> None:
        with self._lock:
            self._token = None
            self._fresh_until = 0.0


def build_client_from_kubeconfig(
    kubeconfig_path: str, master_url: str = "", context_name: str = ""
) -> RestClusterClient:
    """Parse a kubeconfig (the subset covering clusters/users/contexts
    with certificate/token/exec-plugin auth) and build a client;
    ``master_url`` overrides the cluster server like the reference's
    ``--master`` flag."""
    import yaml

    with open(kubeconfig_path) as fh:
        config = yaml.safe_load(fh) or {}

    contexts = {c["name"]: c["context"] for c in config.get("contexts", [])}
    clusters = {c["name"]: c["cluster"] for c in config.get("clusters", [])}
    users = {u["name"]: u["user"] for u in config.get("users", [])}
    context_name = context_name or config.get("current-context", "")
    if context_name not in contexts:
        raise ValueError(f"kubeconfig has no context {context_name!r}")
    context = contexts[context_name]
    cluster = clusters[context["cluster"]]
    user = users.get(context.get("user", ""), {})

    server = master_url or cluster.get("server", "")
    ssl_context = None
    if server.startswith("https"):
        ssl_context = ssl.create_default_context()
        if cluster.get("insecure-skip-tls-verify"):
            ssl_context.check_hostname = False
            ssl_context.verify_mode = ssl.CERT_NONE
        elif cluster.get("certificate-authority-data"):
            ssl_context = ssl.create_default_context(
                cafile=_b64_to_tempfile(cluster["certificate-authority-data"], ".crt")
            )
        elif cluster.get("certificate-authority"):
            ssl_context = ssl.create_default_context(
                cafile=cluster["certificate-authority"]
            )
        cert_file = user.get("client-certificate")
        key_file = user.get("client-key")
        if user.get("client-certificate-data"):
            cert_file = _b64_to_tempfile(user["client-certificate-data"], ".crt")
        if user.get("client-key-data"):
            key_file = _b64_to_tempfile(user["client-key-data"], ".key")
        if cert_file and key_file:
            ssl_context.load_cert_chain(cert_file, key_file)

    token = user.get("token")
    token_provider: Optional[Callable[[], Optional[str]]] = None
    if user.get("exec"):
        token_provider = ExecCredentialProvider(user["exec"])
    elif user.get("tokenFile") and not token:
        # clientcmd gives a static `token` priority over `tokenFile`
        token_provider = TokenFileProvider(user["tokenFile"])
    return RestClusterClient(
        server, token=token, ssl_context=ssl_context, token_provider=token_provider
    )


SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def build_in_cluster_client() -> RestClusterClient:
    """In-cluster config from the mounted service account, the analog
    of ``rest.InClusterConfig``."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        raise RuntimeError("not running in a cluster (KUBERNETES_SERVICE_HOST unset)")
    token_path = os.path.join(SERVICE_ACCOUNT_DIR, "token")
    with open(token_path):
        pass  # fail fast if the mount is missing
    ssl_context = ssl.create_default_context(
        cafile=os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt")
    )
    # projected SA tokens rotate; cached re-reads like client-go
    return RestClusterClient(
        f"https://{host}:{port}",
        ssl_context=ssl_context,
        token_provider=TokenFileProvider(token_path),
    )


def build_client(kubeconfig: str = "", master: str = "") -> RestClusterClient:
    """Kubeconfig if given (or discoverable), else in-cluster — the
    resolution order of ``clientcmd.BuildConfigFromFlags``."""
    if kubeconfig:
        return build_client_from_kubeconfig(kubeconfig, master)
    if master:
        return RestClusterClient(master)
    return build_in_cluster_client()
