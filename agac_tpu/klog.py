"""Verbosity-gated logging, the analog of k8s.io/klog/v2.

The reference logs exclusively through klog with ``--v`` gated detail
(e.g. per-item sync timing at verbosity 4, reference
``pkg/reconcile/reconcile.go:52-55``).  This module provides the same
surface on top of the stdlib ``logging`` package:

    klog.v(4).infof("Finished syncing %q (%v)", key, elapsed)
    klog.infof / warningf / errorf / fatalf

Verbosity is process-global and set from the CLI's ``-v`` flag
(reference wires klog flags at ``cmd/root.go:20-24``).
"""

from __future__ import annotations

import logging
import sys
import threading

_logger = logging.getLogger("agac")
_verbosity = 0
_lock = threading.Lock()
_configured = False


def init(verbosity: int = 0, stream=None) -> None:
    """Configure the process-global logger.  Safe to call repeatedly;
    a later call may change verbosity and/or redirect the stream."""
    global _verbosity, _configured
    with _lock:
        _verbosity = verbosity
        if not _configured:
            handler = logging.StreamHandler(stream or sys.stderr)
            handler.setFormatter(
                logging.Formatter(
                    fmt="%(levelname).1s%(asctime)s.%(msecs)03d %(name)s %(message)s",
                    datefmt="%m%d %H:%M:%S",
                )
            )
            _logger.addHandler(handler)
            _logger.setLevel(logging.DEBUG)
            _logger.propagate = False
            _configured = True
        elif stream is not None:
            for handler in _logger.handlers:
                if isinstance(handler, logging.StreamHandler):
                    handler.setStream(stream)


def verbosity() -> int:
    return _verbosity


class _V:
    """A verbosity-gated handle, the analog of ``klog.V(n)``."""

    def __init__(self, level: int):
        self._enabled = level <= _verbosity

    def enabled(self) -> bool:
        return self._enabled

    def infof(self, fmt: str, *args) -> None:
        if self._enabled:
            _logger.info(fmt % args if args else fmt)


def v(level: int) -> _V:
    return _V(level)


def infof(fmt: str, *args) -> None:
    _logger.info(fmt % args if args else fmt)


def info(msg: str) -> None:
    _logger.info(msg)


def warningf(fmt: str, *args) -> None:
    _logger.warning(fmt % args if args else fmt)


def warning(msg) -> None:
    _logger.warning(str(msg))


def errorf(fmt: str, *args) -> None:
    _logger.error(fmt % args if args else fmt)


def error(msg) -> None:
    _logger.error(str(msg))


def fatalf(fmt: str, *args) -> None:
    _logger.critical(fmt % args if args else fmt)
    raise SystemExit(255)
