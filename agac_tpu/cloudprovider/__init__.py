"""Cloud provider dispatch.

Capability parity with the reference's ``pkg/cloudprovider/provider.go:8-17``:
the trailing two DNS labels of a load-balancer hostname select the
provider; only AWS exists, and the function is the extension seam for
other clouds.
"""

from __future__ import annotations


def detect_cloud_provider(hostname: str) -> str:
    """Return the provider name for an LB hostname, or raise ValueError."""
    parts = hostname.split(".")
    if len(parts) < 2:
        raise ValueError(f"Unknown cloud provider: {hostname}")
    domain = parts[-2] + "." + parts[-1]
    if domain == "amazonaws.com":
        return "aws"
    raise ValueError(f"Unknown cloud provider: {domain}")
