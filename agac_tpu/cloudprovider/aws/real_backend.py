"""Real AWS service clients over stdlib HTTP with SigV4 signing.

The production counterpart of ``FakeAWSBackend``, implementing the
same three API interfaces the drivers consume — the analog of the
aws-sdk-go-v2 clients the reference constructs
(``pkg/cloudprovider/aws/aws.go:12-38``).  Three wire protocols:

- **Global Accelerator**: AWS JSON 1.1 (``X-Amz-Target:
  GlobalAccelerator_V20180706.<Op>``), global endpoint in us-west-2 —
  the same pinning as the reference (``aws.go:26-28``);
- **ELBv2**: Query protocol (form-encoded ``Action=...``), XML
  responses, regional endpoints;
- **Route53**: REST XML on the global endpoint (signed as us-east-1).

Transport is injectable for tests; error bodies are mapped onto
``AWSAPIError`` with the service error code so the drivers' code-based
branching (``EndpointGroupNotFoundException`` etc.) works identically
against fake and real backends.
"""

from __future__ import annotations

import json
import random
import time
import threading
import uuid
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Callable, Optional

from ... import klog

from .api import ELBv2API, GlobalAcceleratorAPI, Route53API
from .errors import (
    AWSAPIError,
    ERR_ENDPOINT_GROUP_NOT_FOUND,
    ERR_LISTENER_NOT_FOUND,
    EndpointGroupNotFoundException,
    ListenerNotFoundException,
)
from .health import (
    OUTCOME_CONNECTION_ERROR,
    OUTCOME_SERVER_ERROR,
    OUTCOME_THROTTLE,
    THROTTLE_CODES,
    DeadlineExceeded,
    deadline_remaining,
)
from .sigv4 import Credentials, CredentialProvider, sign_request, xml_strip_ns
from .types import (
    Accelerator,
    AliasTarget,
    Change,
    EndpointConfiguration,
    EndpointDescription,
    EndpointGroup,
    HostedZone,
    Listener,
    LoadBalancer,
    PortRange,
    ResourceRecord,
    ResourceRecordSet,
    Tag,
)

GA_ENDPOINT_REGION = "us-west-2"  # Global Accelerator is a global service
GA_TARGET_PREFIX = "GlobalAccelerator_V20180706"
ELBV2_API_VERSION = "2015-12-01"
ROUTE53_API_VERSION = "2013-04-01"

Transport = Callable[[str, str, dict, Optional[bytes], float], tuple[int, bytes]]


def _default_transport(method, url, headers, body, timeout) -> tuple[int, bytes]:
    request = urllib.request.Request(url, data=body, headers=headers, method=method)
    try:
        with urllib.request.urlopen(request, timeout=timeout) as response:
            return response.status, response.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


# The aws-sdk-go-v2 clients the reference constructs retry transiently
# failed calls before the error ever reaches the reconcile loop
# ("standard" retry mode: 3 attempts, exponential backoff with full
# jitter).  Same semantics here, at the one choke point every wire
# protocol shares.
RETRY_ATTEMPTS = 3
RETRY_BASE_DELAY = 0.2
RETRY_MAX_DELAY = 5.0
_RETRYABLE_STATUSES = {429, 500, 502, 503, 504}
# service error codes retryable regardless of HTTP status — the SDK's
# transient/throttling taxonomy (GA/ELBv2 throttles arrive as 400s;
# PriorRequestNotComplete is Route53's).  Compared EXACTLY against the
# parsed service code, never substring-matched against the body (an
# InvalidChangeBatch message echoing a record value that happens to
# contain "Throttling" must not be retried).
RETRYABLE_CODES = frozenset(
    {
        "Throttling",
        "ThrottlingException",
        "ThrottledException",
        "TooManyRequestsException",
        "RequestThrottled",
        "RequestThrottledException",
        "RequestLimitExceeded",
        "SlowDown",
        "ServiceUnavailable",
        "ServiceUnavailableException",
        "RequestTimeout",
        "RequestTimeoutException",
        "PriorRequestNotComplete",
        "TransientFailure",
        "InternalFailure",
        "InternalServiceError",
        "InternalServiceErrorException",
    }
)


# Exception classes a malformed (truncated, garbage, wrong-protocol)
# response body can raise out of json/ElementTree parsing or the
# shape-mapping code below.  Every parse is wrapped so these surface
# as a diagnosable AWSAPIError naming the operation — never a raw
# ParseError/KeyError traceback into the reconcile loop, which would
# be retried as an anonymous error forever.  The analog of
# aws-sdk-go-v2's DeserializationError wrapping, which the reference
# gets from the SDK (go.mod:8-13).
_MALFORMED = (AttributeError, TypeError, ValueError, KeyError, IndexError)


def _deserialization_error(operation: str, why, body: bytes) -> AWSAPIError:
    return AWSAPIError(
        "DeserializationError",
        f"{operation}: malformed response from service ({why}); "
        f"body[:200]={body[:200].decode(errors='replace')!r}",
    )


def _ga_error_code(body: bytes) -> str:
    """Service code from an AWS JSON-1.1 error body (``__type``)."""
    try:
        payload = json.loads(body)
        raw = payload.get("__type") or payload.get("code") or ""
        return raw.split("#")[-1]
    except Exception:
        return ""


def _xml_error_code(body: bytes) -> str:
    """Service code from a Query/REST-XML error body (``<Code>``)."""
    try:
        root = xml_strip_ns(ET.fromstring(body))
        return root.findtext(".//Code") or ""
    except ET.ParseError:
        return ""


class _SignedClient:
    def __init__(
        self,
        service: str,
        region: str,
        endpoint: str,
        credentials=None,
        transport: Optional[Transport] = None,
        timeout: float = 30.0,
        attempts: int = RETRY_ATTEMPTS,
        sleep: Optional[Callable[[float], None]] = None,
        error_code_parser: Callable[[bytes], str] = _xml_error_code,
    ):
        self.service = service
        self.region = region
        self.endpoint = endpoint.rstrip("/")
        if credentials is None:
            self._provider = CredentialProvider()
        elif isinstance(credentials, Credentials):
            self._provider = CredentialProvider(static=credentials)
        else:  # already a provider
            self._provider = credentials
        self._transport = transport or _default_transport
        self._timeout = timeout
        self._attempts = max(1, attempts)
        self._sleep = sleep if sleep is not None else time.sleep
        self._error_code = error_code_parser
        # health-plane seam: called with an outcome classification for
        # every RETRIED attempt (throttle / server-error / connection-
        # error).  The guard layer above the API objects only sees the
        # final result, so without this hook a brownout the in-client
        # retries keep absorbing would be invisible to the AIMD
        # limiter until it overflowed the attempt budget.
        self.on_outcome: Optional[Callable[[str], None]] = None

    def _retryable(self, status: int, body: bytes) -> bool:
        if status in _RETRYABLE_STATUSES:
            return True
        return status >= 400 and self._error_code(body) in RETRYABLE_CODES

    def _report(self, outcome: str) -> None:
        if self.on_outcome is not None:
            try:
                self.on_outcome(outcome)
            except Exception as err:  # observability must not fail the call
                klog.errorf("health outcome hook failed: %s", err)

    def _attempt_outcome(self, status: int, body: bytes) -> str:
        code = self._error_code(body)
        if status == 429 or code in THROTTLE_CODES:
            return OUTCOME_THROTTLE
        return OUTCOME_SERVER_ERROR

    def request(
        self, method: str, path: str, headers: dict[str, str], body: bytes
    ) -> tuple[int, bytes]:
        url = f"{self.endpoint}{path}"
        last_exc: Optional[Exception] = None
        for attempt in range(self._attempts):
            if attempt:
                # full jitter keeps a fleet of workers from thundering
                backoff = random.uniform(
                    0, min(RETRY_MAX_DELAY, RETRY_BASE_DELAY * (2 ** attempt))
                )
                # the retry checks the reconcile deadline (health
                # plane): no point burning a backoff sleep plus another
                # attempt the caller can no longer use — surface the
                # retryable deadline error and free the worker
                remaining = deadline_remaining()
                if remaining is not None and remaining <= backoff:
                    raise DeadlineExceeded(
                        f"{method} {path}: reconcile deadline expired "
                        f"before retry {attempt + 1}/{self._attempts}"
                    )
                self._sleep(backoff)
            # re-sign every attempt: fresh timestamp, and the provider
            # refreshes expiring session credentials (IRSA) transparently
            signed = sign_request(
                method, url, headers, body, self.service, self.region,
                self._provider.get(),
            )
            try:
                status, payload = self._transport(
                    method, url, signed, body or None, self._timeout
                )
            except (urllib.error.URLError, OSError) as err:
                # connection refused/reset, DNS failure, socket timeout.
                # Re-sending after a possible commit is safe everywhere:
                # GA creates carry an IdempotencyToken (below); updates,
                # tag merges and record UPSERTs are idempotent; deletes
                # re-sent after a commit surface NotFound, which every
                # driver path already treats as absence; the rest are
                # reads.
                last_exc = err
                self._report(OUTCOME_CONNECTION_ERROR)
                klog.v(2).infof(
                    "retrying %s %s after connection error (%s, attempt %d/%d)",
                    method, path, err, attempt + 1, self._attempts,
                )
                continue
            if attempt + 1 < self._attempts and self._retryable(status, payload):
                self._report(self._attempt_outcome(status, payload))
                klog.v(2).infof(
                    "retrying %s %s after HTTP %d (attempt %d/%d)",
                    method, path, status, attempt + 1, self._attempts,
                )
                continue
            return status, payload
        raise AWSAPIError(
            "RequestError", f"{method} {url} failed after {self._attempts} attempts: {last_exc}"
        )


# ---------------------------------------------------------------------------
# Global Accelerator (AWS JSON 1.1)
# ---------------------------------------------------------------------------


def _ga_error(operation: str, status: int, body: bytes) -> AWSAPIError:
    code = _ga_error_code(body) or "UnknownError"
    try:
        payload = json.loads(body)
        if not isinstance(payload, dict):
            raise ValueError("not an object")
        message = payload.get("message") or payload.get("Message") or ""
    except Exception:
        # half-written/garbage error envelope: still a typed error
        # naming the operation, with the body excerpt for diagnosis
        message = body[:200].decode(errors="replace")
    # the typed not-found returns carry the same operation prefix as
    # every other GA error — anonymous messages made those two error
    # classes the only undiagnosable ones (ADVICE r5 #4)
    prefixed = f"{operation}: {message or f'HTTP {status}'}"
    if code == ERR_LISTENER_NOT_FOUND:
        return ListenerNotFoundException(prefixed)
    if code == ERR_ENDPOINT_GROUP_NOT_FOUND:
        return EndpointGroupNotFoundException(prefixed)
    return AWSAPIError(code, prefixed)


def _accelerator_from_json(data: dict) -> Accelerator:
    return Accelerator(
        accelerator_arn=data.get("AcceleratorArn", ""),
        name=data.get("Name", ""),
        dns_name=data.get("DnsName", ""),
        enabled=bool(data.get("Enabled", False)),
        status=data.get("Status", ""),
        ip_address_type=data.get("IpAddressType", "IPV4"),
    )


def _listener_from_json(data: dict) -> Listener:
    return Listener(
        listener_arn=data.get("ListenerArn", ""),
        protocol=data.get("Protocol", "TCP"),
        port_ranges=[
            PortRange(p.get("FromPort", 0), p.get("ToPort", 0))
            for p in data.get("PortRanges", [])
        ],
        client_affinity=data.get("ClientAffinity", "NONE"),
    )


def _endpoint_group_from_json(data: dict) -> EndpointGroup:
    return EndpointGroup(
        endpoint_group_arn=data.get("EndpointGroupArn", ""),
        endpoint_group_region=data.get("EndpointGroupRegion", ""),
        endpoint_descriptions=[
            EndpointDescription(
                endpoint_id=d.get("EndpointId", ""),
                weight=d.get("Weight"),
                client_ip_preservation_enabled=bool(
                    d.get("ClientIPPreservationEnabled", False)
                ),
            )
            for d in data.get("EndpointDescriptions", [])
        ],
    )


def _endpoint_configurations_json(configs: list[EndpointConfiguration]) -> list[dict]:
    out = []
    for c in configs:
        entry: dict = {
            "EndpointId": c.endpoint_id,
            "ClientIPPreservationEnabled": c.client_ip_preservation_enabled,
        }
        if c.weight is not None:
            entry["Weight"] = c.weight
        out.append(entry)
    return out


class RealGlobalAcceleratorAPI(GlobalAcceleratorAPI):
    def __init__(
        self, credentials=None, transport=None, endpoint=None,
        attempts=RETRY_ATTEMPTS, sleep=None,
    ):
        self._client = _SignedClient(
            "globalaccelerator",
            GA_ENDPOINT_REGION,
            endpoint or f"https://globalaccelerator.{GA_ENDPOINT_REGION}.amazonaws.com",
            credentials,
            transport,
            attempts=attempts,
            sleep=sleep,
            error_code_parser=_ga_error_code,
        )

    def set_outcome_hook(self, hook) -> None:
        """Feed per-retry outcome classifications to the health plane."""
        self._client.on_outcome = hook

    def _call(self, operation: str, payload: dict, parse=None):
        """POST one JSON-1.1 operation.  ``parse`` maps the decoded
        response dict to the return value; any malformed body — not
        JSON, not an object, or a shape the mapper chokes on — raises
        ``AWSAPIError("DeserializationError")`` naming the operation."""
        body = json.dumps(payload).encode()
        status, response = self._client.request(
            "POST",
            "/",
            {
                "Content-Type": "application/x-amz-json-1.1",
                "X-Amz-Target": f"{GA_TARGET_PREFIX}.{operation}",
            },
            body,
        )
        if status >= 300:
            raise _ga_error(operation, status, response)
        try:
            data = json.loads(response) if response else {}
        except ValueError as err:
            raise _deserialization_error(operation, err, response) from err
        if not isinstance(data, dict):
            raise _deserialization_error(
                operation, f"expected JSON object, got {type(data).__name__}", response
            )
        if parse is None:
            return data
        try:
            return parse(data)
        except _MALFORMED as err:
            raise _deserialization_error(operation, repr(err), response) from err

    # accelerators
    def list_accelerators(self, max_results, next_token):
        payload: dict = {"MaxResults": max_results}
        if next_token:
            payload["NextToken"] = next_token
        return self._call(
            "ListAccelerators",
            payload,
            parse=lambda data: (
                [_accelerator_from_json(a) for a in data.get("Accelerators", [])],
                data.get("NextToken"),
            ),
        )

    def describe_accelerator(self, arn):
        return self._call(
            "DescribeAccelerator",
            {"AcceleratorArn": arn},
            parse=lambda data: _accelerator_from_json(data.get("Accelerator", {})),
        )

    def create_accelerator(self, name, ip_address_type, enabled, tags):
        return self._call(
            "CreateAccelerator",
            {
                "Name": name,
                "IpAddressType": ip_address_type,
                "Enabled": enabled,
                "Tags": [{"Key": t.key, "Value": t.value} for t in tags],
                # one token per logical create, shared by retries: a
                # re-sent request after a timeout-after-commit returns
                # the original resource instead of minting a duplicate
                # (the SDK auto-fills this field for the reference)
                "IdempotencyToken": uuid.uuid4().hex,
            },
            parse=lambda data: _accelerator_from_json(data.get("Accelerator", {})),
        )

    def update_accelerator(self, arn, name=None, enabled=None):
        payload: dict = {"AcceleratorArn": arn}
        if name is not None:
            payload["Name"] = name
        if enabled is not None:
            payload["Enabled"] = enabled
        return self._call(
            "UpdateAccelerator",
            payload,
            parse=lambda data: _accelerator_from_json(data.get("Accelerator", {})),
        )

    def delete_accelerator(self, arn):
        self._call("DeleteAccelerator", {"AcceleratorArn": arn})

    def list_tags_for_resource(self, arn):
        return self._call(
            "ListTagsForResource",
            {"ResourceArn": arn},
            parse=lambda data: [
                Tag(t.get("Key", ""), t.get("Value", "")) for t in data.get("Tags", [])
            ],
        )

    def tag_resource(self, arn, tags):
        self._call(
            "TagResource",
            {
                "ResourceArn": arn,
                "Tags": [{"Key": t.key, "Value": t.value} for t in tags],
            },
        )

    # listeners
    def list_listeners(self, accelerator_arn, max_results, next_token):
        payload: dict = {"AcceleratorArn": accelerator_arn, "MaxResults": max_results}
        if next_token:
            payload["NextToken"] = next_token
        return self._call(
            "ListListeners",
            payload,
            parse=lambda data: (
                [_listener_from_json(l) for l in data.get("Listeners", [])],
                data.get("NextToken"),
            ),
        )

    def create_listener(self, accelerator_arn, port_ranges, protocol, client_affinity):
        return self._call(
            "CreateListener",
            {
                "AcceleratorArn": accelerator_arn,
                "PortRanges": [
                    {"FromPort": p.from_port, "ToPort": p.to_port} for p in port_ranges
                ],
                "Protocol": protocol,
                "ClientAffinity": client_affinity,
                "IdempotencyToken": uuid.uuid4().hex,
            },
            parse=lambda data: _listener_from_json(data.get("Listener", {})),
        )

    def update_listener(self, listener_arn, port_ranges, protocol, client_affinity):
        return self._call(
            "UpdateListener",
            {
                "ListenerArn": listener_arn,
                "PortRanges": [
                    {"FromPort": p.from_port, "ToPort": p.to_port} for p in port_ranges
                ],
                "Protocol": protocol,
                "ClientAffinity": client_affinity,
            },
            parse=lambda data: _listener_from_json(data.get("Listener", {})),
        )

    def delete_listener(self, arn):
        self._call("DeleteListener", {"ListenerArn": arn})

    # endpoint groups
    def list_endpoint_groups(self, listener_arn, max_results, next_token):
        payload: dict = {"ListenerArn": listener_arn, "MaxResults": max_results}
        if next_token:
            payload["NextToken"] = next_token
        return self._call(
            "ListEndpointGroups",
            payload,
            parse=lambda data: (
                [_endpoint_group_from_json(g) for g in data.get("EndpointGroups", [])],
                data.get("NextToken"),
            ),
        )

    def describe_endpoint_group(self, arn):
        return self._call(
            "DescribeEndpointGroup",
            {"EndpointGroupArn": arn},
            parse=lambda data: _endpoint_group_from_json(data.get("EndpointGroup", {})),
        )

    def create_endpoint_group(self, listener_arn, endpoint_group_region, endpoint_configurations):
        return self._call(
            "CreateEndpointGroup",
            {
                "ListenerArn": listener_arn,
                "EndpointGroupRegion": endpoint_group_region,
                "EndpointConfigurations": _endpoint_configurations_json(
                    endpoint_configurations
                ),
                "IdempotencyToken": uuid.uuid4().hex,
            },
            parse=lambda data: _endpoint_group_from_json(data.get("EndpointGroup", {})),
        )

    def update_endpoint_group(self, arn, endpoint_configurations):
        return self._call(
            "UpdateEndpointGroup",
            {
                "EndpointGroupArn": arn,
                "EndpointConfigurations": _endpoint_configurations_json(
                    endpoint_configurations
                ),
            },
            parse=lambda data: _endpoint_group_from_json(data.get("EndpointGroup", {})),
        )

    def delete_endpoint_group(self, arn):
        self._call("DeleteEndpointGroup", {"EndpointGroupArn": arn})

    def add_endpoints(self, arn, endpoint_configurations):
        return self._call(
            "AddEndpoints",
            {
                "EndpointGroupArn": arn,
                "EndpointConfigurations": _endpoint_configurations_json(
                    endpoint_configurations
                ),
            },
            parse=lambda data: [
                EndpointDescription(
                    endpoint_id=d.get("EndpointId", ""),
                    weight=d.get("Weight"),
                    client_ip_preservation_enabled=bool(
                        d.get("ClientIPPreservationEnabled", False)
                    ),
                )
                for d in data.get("EndpointDescriptions", [])
            ],
        )

    def remove_endpoints(self, arn, endpoint_ids):
        self._call(
            "RemoveEndpoints",
            {
                "EndpointGroupArn": arn,
                "EndpointIdentifiers": [
                    {"EndpointId": endpoint_id} for endpoint_id in endpoint_ids
                ],
            },
        )


# ---------------------------------------------------------------------------
# ELBv2 (Query protocol, XML)
# ---------------------------------------------------------------------------


def _xml_error(operation: str, status: int, body: bytes) -> AWSAPIError:
    try:
        root = xml_strip_ns(ET.fromstring(body))
    except ET.ParseError:
        # half-written/garbage error envelope: still a typed error
        # naming the operation, with the body excerpt for diagnosis
        return AWSAPIError(
            "UnknownError",
            f"{operation}: HTTP {status} with unparseable body: "
            f"{body[:200].decode(errors='replace')!r}",
        )
    return AWSAPIError(
        root.findtext(".//Code") or "UnknownError",
        f"{operation}: {root.findtext('.//Message') or f'HTTP {status}'}",
    )


def _parse_xml_response(operation: str, expected_root: str, body: bytes) -> ET.Element:
    """Parse a 2xx XML response body, validating the document is the
    operation's response document.  The root-tag check matters: an
    HTML error page or wrong-protocol body often still parses as XML,
    and without it ``findall`` would quietly return nothing — absence
    where the truth is 'the response was garbage'."""
    try:
        root = xml_strip_ns(ET.fromstring(body))
    except ET.ParseError as err:
        raise _deserialization_error(operation, err, body) from err
    if root.tag != expected_root:
        raise _deserialization_error(
            operation, f"expected <{expected_root}>, got <{root.tag}>", body
        )
    return root


class RealELBv2API(ELBv2API):
    def __init__(
        self, region: str, credentials=None, transport=None, endpoint=None,
        attempts=RETRY_ATTEMPTS, sleep=None,
    ):
        self._client = _SignedClient(
            "elasticloadbalancing",
            region,
            endpoint or f"https://elasticloadbalancing.{region}.amazonaws.com",
            credentials,
            transport,
            attempts=attempts,
            sleep=sleep,
        )

    def set_outcome_hook(self, hook) -> None:
        """Feed per-retry outcome classifications to the health plane."""
        self._client.on_outcome = hook

    # DescribeLoadBalancers accepts at most 20 names per request
    # (ELBv2 API reference); the read plane's coalescer batches up to
    # exactly this, but a direct caller with a wider list must not get
    # a ValidationError — chunk and concatenate.
    MAX_NAMES_PER_CALL = 20

    def describe_load_balancers(self, names):
        if len(names) > self.MAX_NAMES_PER_CALL:
            found = []
            for i in range(0, len(names), self.MAX_NAMES_PER_CALL):
                found.extend(
                    self.describe_load_balancers(
                        names[i : i + self.MAX_NAMES_PER_CALL]
                    )
                )
            return found
        params = {"Action": "DescribeLoadBalancers", "Version": ELBV2_API_VERSION}
        for i, name in enumerate(names, start=1):
            params[f"Names.member.{i}"] = name
        body = urllib.parse.urlencode(params).encode()
        status, response = self._client.request(
            "POST",
            "/",
            {"Content-Type": "application/x-www-form-urlencoded"},
            body,
        )
        if status >= 300:
            raise _xml_error("DescribeLoadBalancers", status, response)
        root = _parse_xml_response(
            "DescribeLoadBalancers", "DescribeLoadBalancersResponse", response
        )
        try:
            return [
                LoadBalancer(
                    load_balancer_arn=member.findtext("LoadBalancerArn", ""),
                    load_balancer_name=member.findtext("LoadBalancerName", ""),
                    dns_name=member.findtext("DNSName", ""),
                    state_code=member.findtext("State/Code", ""),
                    type=member.findtext("Type", ""),
                    scheme=member.findtext("Scheme", ""),
                )
                for member in root.findall(".//LoadBalancers/member")
            ]
        except _MALFORMED as err:
            raise _deserialization_error(
                "DescribeLoadBalancers", repr(err), response
            ) from err


# ---------------------------------------------------------------------------
# Route53 (REST XML)
# ---------------------------------------------------------------------------

_R53_NS = "https://route53.amazonaws.com/doc/2013-04-01/"


def _record_set_to_xml(record: ResourceRecordSet) -> ET.Element:
    rrs = ET.Element("ResourceRecordSet")
    ET.SubElement(rrs, "Name").text = record.name
    ET.SubElement(rrs, "Type").text = record.type
    if record.alias_target is not None:
        alias = ET.SubElement(rrs, "AliasTarget")
        ET.SubElement(alias, "HostedZoneId").text = record.alias_target.hosted_zone_id
        ET.SubElement(alias, "DNSName").text = record.alias_target.dns_name
        ET.SubElement(alias, "EvaluateTargetHealth").text = (
            "true" if record.alias_target.evaluate_target_health else "false"
        )
    if record.ttl is not None:
        ET.SubElement(rrs, "TTL").text = str(record.ttl)
    if record.resource_records:
        records = ET.SubElement(rrs, "ResourceRecords")
        for rr in record.resource_records:
            ET.SubElement(
                ET.SubElement(records, "ResourceRecord"), "Value"
            ).text = rr.value
    return rrs


def _record_set_from_xml(element: ET.Element) -> ResourceRecordSet:
    alias = element.find("AliasTarget")
    ttl = element.findtext("TTL")
    return ResourceRecordSet(
        name=element.findtext("Name", ""),
        type=element.findtext("Type", ""),
        ttl=int(ttl) if ttl else None,
        resource_records=[
            ResourceRecord(value.findtext("Value", ""))
            for value in element.findall("ResourceRecords/ResourceRecord")
        ],
        alias_target=(
            AliasTarget(
                dns_name=alias.findtext("DNSName", ""),
                evaluate_target_health=alias.findtext("EvaluateTargetHealth") == "true",
                hosted_zone_id=alias.findtext("HostedZoneId", ""),
            )
            if alias is not None
            else None
        ),
    )


class RealRoute53API(Route53API):
    def __init__(
        self, credentials=None, transport=None, endpoint=None,
        attempts=RETRY_ATTEMPTS, sleep=None,
    ):
        # Route53 is global; requests are signed against us-east-1
        self._client = _SignedClient(
            "route53",
            "us-east-1",
            endpoint or "https://route53.amazonaws.com",
            credentials,
            transport,
            attempts=attempts,
            sleep=sleep,
        )

    def set_outcome_hook(self, hook) -> None:
        """Feed per-retry outcome classifications to the health plane."""
        self._client.on_outcome = hook

    def _get(self, operation: str, expected_root: str, path: str) -> ET.Element:
        status, response = self._client.request("GET", path, {}, b"")
        if status >= 300:
            raise _xml_error(operation, status, response)
        return _parse_xml_response(operation, expected_root, response)

    @staticmethod
    def _zone_from_xml(element: ET.Element) -> HostedZone:
        return HostedZone(
            id=element.findtext("Id", ""), name=element.findtext("Name", "")
        )

    def list_hosted_zones(self, max_items, marker):
        query = {"maxitems": str(max_items)}
        if marker:
            query["marker"] = marker
        root = self._get(
            "ListHostedZones",
            "ListHostedZonesResponse",
            f"/{ROUTE53_API_VERSION}/hostedzone?{urllib.parse.urlencode(query)}",
        )
        zones = [
            self._zone_from_xml(z) for z in root.findall(".//HostedZones/HostedZone")
        ]
        next_marker = root.findtext("NextMarker")
        return zones, next_marker

    def list_hosted_zones_by_name(self, dns_name, max_items):
        query = urllib.parse.urlencode({"dnsname": dns_name, "maxitems": str(max_items)})
        root = self._get(
            "ListHostedZonesByName",
            "ListHostedZonesByNameResponse",
            f"/{ROUTE53_API_VERSION}/hostedzonesbyname?{query}",
        )
        return [
            self._zone_from_xml(z) for z in root.findall(".//HostedZones/HostedZone")
        ]

    def list_resource_record_sets(self, hosted_zone_id, max_items, start_record_name):
        zone = hosted_zone_id.split("/")[-1]
        query = {"maxitems": str(max_items)}
        if start_record_name:
            query["name"] = start_record_name
        root = self._get(
            "ListResourceRecordSets",
            "ListResourceRecordSetsResponse",
            f"/{ROUTE53_API_VERSION}/hostedzone/{zone}/rrset?{urllib.parse.urlencode(query)}",
        )
        try:
            records = [
                _record_set_from_xml(r)
                for r in root.findall(".//ResourceRecordSets/ResourceRecordSet")
            ]
        except _MALFORMED as err:
            # e.g. a non-numeric TTL: typed, named, never a raw
            # ValueError into the reconcile loop
            raise _deserialization_error(
                "ListResourceRecordSets", repr(err), ET.tostring(root)
            ) from err
        next_name = root.findtext("NextRecordName")
        is_truncated = root.findtext("IsTruncated") == "true"
        return records, (next_name if is_truncated else None)

    def change_resource_record_sets(self, hosted_zone_id, changes: list[Change]):
        zone = hosted_zone_id.split("/")[-1]
        request = ET.Element("ChangeResourceRecordSetsRequest", xmlns=_R53_NS)
        batch = ET.SubElement(request, "ChangeBatch")
        changes_el = ET.SubElement(batch, "Changes")
        for change in changes:
            change_el = ET.SubElement(changes_el, "Change")
            ET.SubElement(change_el, "Action").text = change.action
            change_el.append(_record_set_to_xml(change.record_set))
        body = ET.tostring(request, encoding="utf-8", xml_declaration=True)
        status, response = self._client.request(
            "POST",
            f"/{ROUTE53_API_VERSION}/hostedzone/{zone}/rrset",
            {"Content-Type": "application/xml"},
            body,
        )
        if status >= 300:
            raise _xml_error("ChangeResourceRecordSets", status, response)


_process_provider: Optional[CredentialProvider] = None
_provider_lock = threading.Lock()


def _shared_credential_provider() -> CredentialProvider:
    """ONE provider for the whole process.  `from_environment` runs
    per reconcile (the reference's `NewAWS(region)`-per-item shape);
    a fresh provider each time would redo credential resolution —
    under IRSA that is an STS AssumeRoleWithWebIdentity round trip per
    work item, pure latency plus an STS throttling risk at fleet
    scale.  The provider caches until expiry and refreshes itself, so
    sharing is exactly what it is built for."""
    global _process_provider
    with _provider_lock:
        if _process_provider is None:
            _process_provider = CredentialProvider()
        return _process_provider


@dataclass
class RealAWSClients:
    ga: RealGlobalAcceleratorAPI
    elbv2: RealELBv2API
    route53: RealRoute53API

    @classmethod
    def from_environment(cls, region: str) -> "RealAWSClients":
        provider = _shared_credential_provider()
        return cls(
            ga=RealGlobalAcceleratorAPI(provider),
            elbv2=RealELBv2API(region, provider),
            route53=RealRoute53API(provider),
        )
