"""The AWS API health plane (ISSUE 3): per-service circuit breakers,
AIMD adaptive throttling, reconcile deadlines, and worker heartbeats.

The rest of the stack is built for *transient* faults — in-client
retries (``real_backend.py``), rate-limited requeues (``workqueue.py``)
— which only delay convergence.  Nothing adapts to *sustained*
degradation: a Route53 brownout makes every worker burn its 3 retries
per call, the fixed-rate queues keep feeding the dying service, and a
wedged settle poll holds a worker with no deadline.  This module adds
the sensing layer (Arcturus' stability argument: the control plane
must *measure* backend health and shed load):

- every backend call outcome is **classified** (success / throttle /
  5xx / connection error) into a rolling window per service;
- a **circuit breaker** per service key (``globalaccelerator``,
  ``route53``, ``elbv2[<region>]``) trips on sustained failure:
  closed → open (calls rejected with a retry hint) → half-open
  (single probe calls per interval) → closed on probe success;
- an **AIMD limiter** layered on the workqueue's token bucket
  multiplicatively cuts the effective call rate on throttle
  classifications and additively recovers on success — backpressure
  instead of retry storms;
- a **reconcile deadline** is carried per worker (threading.local,
  set by the reconcile loop) and consulted by settle polls, in-client
  retry backoffs and AIMD pacing waits; expiry raises the retryable
  ``DeadlineExceeded`` instead of wedging the worker;
- a **worker heartbeat table** records what every worker is
  reconciling and since when, so a watchdog (and the manager's
  ``/healthz``) can surface stuck workers, and shutdown can name the
  key a straggler thread is wedged on.

Everything takes an injectable clock so the unit tier drives state
transitions without wall time.  Wiring lives in ``factory.py`` (env
knobs + ``--api-health-*`` flags); controllers translate
``CircuitOpenError`` into a circuit-aware requeue
(``controllers/common.py``).
"""

from __future__ import annotations

import inspect
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from ... import clockseam, klog
from ...observability import instruments
from ...observability.metrics import MetricsRegistry
from .api import ELBv2API, GlobalAcceleratorAPI, Route53API
from .errors import AWSAPIError

# ---------------------------------------------------------------------------
# outcome classification
# ---------------------------------------------------------------------------

OUTCOME_SUCCESS = "success"
OUTCOME_THROTTLE = "throttle"
OUTCOME_SERVER_ERROR = "server-error"
OUTCOME_CONNECTION_ERROR = "connection-error"

# Throttle-shaped service codes (the SDK's throttling taxonomy — the
# subset of real_backend.RETRYABLE_CODES that means "slow down", which
# is what the AIMD limiter reacts to).
THROTTLE_CODES = frozenset(
    {
        "Throttling",
        "ThrottlingException",
        "ThrottledException",
        "TooManyRequestsException",
        "RequestThrottled",
        "RequestThrottledException",
        "RequestLimitExceeded",
        "SlowDown",
        "PriorRequestNotComplete",
    }
)

# 5xx-shaped service codes: the service answered but is failing.
SERVER_ERROR_CODES = frozenset(
    {
        "ServiceUnavailable",
        "ServiceUnavailableException",
        "InternalFailure",
        "InternalServiceError",
        "InternalServiceErrorException",
        "InternalError",
        "TransientFailure",
        "RequestTimeout",
        "RequestTimeoutException",
    }
)

# real_backend raises this after exhausting attempts on pure
# connection errors (refused/reset/DNS) — the service never answered.
CONNECTION_CODES = frozenset({"RequestError"})

_FAILURE_OUTCOMES = frozenset(
    {OUTCOME_THROTTLE, OUTCOME_SERVER_ERROR, OUTCOME_CONNECTION_ERROR}
)


class DeadlineExceeded(AWSAPIError):
    """The reconcile deadline expired mid-operation.  Retryable on
    purpose (NOT a NoRetryError): the item is requeued with backoff and
    the next attempt gets a fresh deadline — the point is to free the
    worker, not to abandon the object."""

    def __init__(self, message: str = "", paced: bool = False):
        # paced=True: the deadline was consumed by ADAPTIVE PACING
        # (AIMD quota backpressure), not by a slow call — the explain
        # plane classifies that requeue as quota-paced, not backoff
        self.paced = paced
        super().__init__("DeadlineExceeded", message)


class CircuitOpenError(AWSAPIError):
    """A call was rejected without touching the wire because the
    service's circuit is open.  ``retry_after`` is the breaker's hint
    for when a probe might be allowed — controllers requeue with it
    instead of burning a rate-limited retry against a dead service."""

    def __init__(self, service: str, retry_after: float):
        self.service = service
        self.retry_after = retry_after
        super().__init__(
            "CircuitOpen",
            f"{service}: circuit open, retry in {retry_after:.1f}s",
        )


def classify_error(err: BaseException) -> Optional[str]:
    """Map a raised backend error onto a health outcome; None means
    neutral (client-side errors — deadlines, circuit rejections, code
    bugs — say nothing about the service's health)."""
    if isinstance(err, (DeadlineExceeded, CircuitOpenError)):
        return None
    if not isinstance(err, AWSAPIError):
        return None
    if err.code in THROTTLE_CODES:
        return OUTCOME_THROTTLE
    if err.code in SERVER_ERROR_CODES:
        return OUTCOME_SERVER_ERROR
    if err.code in CONNECTION_CODES:
        return OUTCOME_CONNECTION_ERROR
    # any other service error (NotFound, InvalidArgument, ...) is a
    # definite answer: the service is healthy enough to reject us
    return OUTCOME_SUCCESS


# ---------------------------------------------------------------------------
# reconcile deadlines (threading.local: one per worker thread)
# ---------------------------------------------------------------------------

_deadline_state = threading.local()


def set_reconcile_deadline(
    timeout: float, clock: Optional[Callable[[], float]] = None
) -> None:
    """Arm this worker's reconcile deadline ``timeout`` seconds from
    now; 0/negative clears it."""
    if timeout <= 0:
        clear_reconcile_deadline()
        return
    clock = clock or clockseam.monotonic
    _deadline_state.deadline = clock() + timeout
    _deadline_state.clock = clock


def clear_reconcile_deadline() -> None:
    _deadline_state.deadline = None
    _deadline_state.clock = None


def reconcile_deadline() -> Optional[float]:
    return getattr(_deadline_state, "deadline", None)


def deadline_remaining() -> Optional[float]:
    """Seconds until this worker's deadline, None when unarmed."""
    deadline = reconcile_deadline()
    if deadline is None:
        return None
    clock = getattr(_deadline_state, "clock", None) or clockseam.monotonic
    return deadline - clock()


def check_deadline(what: str) -> None:
    """Raise the retryable DeadlineExceeded once the worker's deadline
    has passed — the seam every poll/retry loop consults so a wedged
    backend frees the worker instead of holding it."""
    remaining = deadline_remaining()
    if remaining is not None and remaining <= 0:
        raise DeadlineExceeded(f"reconcile deadline expired during {what}")


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half-open"


class CircuitBreaker:
    """Rolling-window circuit breaker.

    Closed: every call allowed; outcomes land in a sliding window.
    When the window holds >= ``min_calls`` outcomes and the failure
    ratio reaches ``failure_ratio``, the circuit opens.  Open: calls
    rejected with a retry hint until ``open_duration`` elapses, then
    half-open: ``probe_budget`` probe calls are allowed per
    ``open_duration`` interval.  A probe success closes the circuit
    (window reset); a probe failure reopens it.
    """

    def __init__(
        self,
        window: float = 30.0,
        min_calls: int = 10,
        failure_ratio: float = 0.5,
        open_duration: float = 15.0,
        probe_budget: int = 1,
        clock: Optional[Callable[[], float]] = None,
    ):
        self._window = window
        self._min_calls = max(1, min_calls)
        self._failure_ratio = failure_ratio
        self._open_duration = open_duration
        self._probe_budget = max(1, probe_budget)
        self._clock = clock or clockseam.monotonic
        self._lock = threading.Lock()
        self._state = STATE_CLOSED
        # (time, failed), append-only in clock order; pruning pops
        # from the left so a busy window costs O(evictions), not a
        # full rebuild per call (a 7-day sim soak hot spot)
        self._outcomes: deque[tuple[float, bool]] = deque()
        self._opened_at = 0.0
        self._probes_left = 0
        self._probe_interval_start = 0.0
        self.opened_total = 0  # times the circuit tripped (observability)
        self.rejected_total = 0  # calls shed while open

    def _prune(self, now: float) -> None:
        cutoff = now - self._window
        while self._outcomes and self._outcomes[0][0] <= cutoff:
            self._outcomes.popleft()

    def state(self) -> str:
        with self._lock:
            return self._effective_state(self._clock())

    def _effective_state(self, now: float) -> str:
        if self._state == STATE_OPEN and now - self._opened_at >= self._open_duration:
            self._state = STATE_HALF_OPEN
            self._probes_left = self._probe_budget
            self._probe_interval_start = now
        return self._state

    def allow(self) -> tuple[bool, float]:
        """(allowed, retry_after).  retry_after is 0 when allowed."""
        with self._lock:
            now = self._clock()
            state = self._effective_state(now)
            if state == STATE_CLOSED:
                return True, 0.0
            if state == STATE_HALF_OPEN:
                if now - self._probe_interval_start >= self._open_duration:
                    # a new probe interval: refill the budget
                    self._probes_left = self._probe_budget
                    self._probe_interval_start = now
                if self._probes_left > 0:
                    self._probes_left -= 1
                    return True, 0.0
                self.rejected_total += 1
                return False, max(
                    self._probe_interval_start + self._open_duration - now, 0.05
                )
            self.rejected_total += 1
            return False, max(self._opened_at + self._open_duration - now, 0.05)

    def record(self, failed: bool) -> None:
        with self._lock:
            now = self._clock()
            state = self._effective_state(now)
            if state == STATE_HALF_OPEN:
                if failed:
                    self._trip(now)
                else:
                    # probe succeeded: close with a clean window
                    self._state = STATE_CLOSED
                    self._outcomes.clear()
                return
            if state == STATE_OPEN:
                # stragglers that were in flight when the circuit
                # tripped; they don't move the (already open) state
                return
            self._outcomes.append((now, failed))
            self._prune(now)
            if not failed or len(self._outcomes) < self._min_calls:
                return
            failures = sum(1 for _, f in self._outcomes if f)
            if failures / len(self._outcomes) >= self._failure_ratio:
                self._trip(now)

    def _trip(self, now: float) -> None:
        self._state = STATE_OPEN
        self._opened_at = now
        self._outcomes.clear()
        self.opened_total += 1

    def snapshot(self) -> dict:
        with self._lock:
            now = self._clock()
            state = self._effective_state(now)
            failures = sum(1 for _, f in self._outcomes if f)
            return {
                "state": state,
                "window_calls": len(self._outcomes),
                "window_failures": failures,
                "opened_total": self.opened_total,
                "rejected_total": self.rejected_total,
            }


# ---------------------------------------------------------------------------
# AIMD adaptive limiter
# ---------------------------------------------------------------------------


class AIMDLimiter:
    """Adaptive call pacing layered on the workqueue's token bucket.

    The bucket enforces whatever rate is current; AIMD moves the rate:
    a throttle classification multiplicatively cuts it
    (``rate *= decrease``, floored), a success additively restores it
    (``rate += increase``, capped at the configured ceiling).  The
    result converges to just under the service's real capacity instead
    of hammering a fixed rate through a brownout.
    """

    def __init__(
        self,
        qps: float = 20.0,
        floor: float = 0.5,
        ceiling: Optional[float] = None,
        increase: float = 0.2,
        decrease: float = 0.5,
        burst: Optional[int] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        # the existing token bucket (reconcile.workqueue) is the
        # enforcement layer; imported lazily to keep this package free
        # of a module-level reconcile dependency
        from ...reconcile.workqueue import BucketRateLimiter

        self._floor = max(floor, 0.01)
        self._ceiling = ceiling if ceiling is not None else qps
        self._increase = increase
        self._decrease = decrease
        self._rate = min(max(qps, self._floor), self._ceiling)
        self._bucket = BucketRateLimiter(
            self._rate, burst if burst is not None else max(1, int(qps)), clock=clock
        )
        self._lock = threading.Lock()

    def rate(self) -> float:
        with self._lock:
            return self._rate

    def ceiling(self) -> float:
        with self._lock:
            return self._ceiling

    def set_ceiling(self, ceiling: float) -> None:
        """Retune the additive-restore cap in place — the sharding
        plane's quota-division seam (ISSUE 8): a replica owning k of N
        shards runs each service at ``base_qps * k/N``, so the fleet's
        aggregate ceiling never exceeds the global budget.  A cut takes
        effect immediately (the live rate is clamped down); growth is
        earned back additively through successes, like any AIMD
        recovery."""
        with self._lock:
            self._ceiling = max(ceiling, self._floor)
            if self._rate > self._ceiling:
                self._rate = self._ceiling
                self._bucket.set_qps(self._rate)

    def on_throttle(self) -> None:
        with self._lock:
            self._rate = max(self._floor, self._rate * self._decrease)
            self._bucket.set_qps(self._rate)

    def on_success(self) -> None:
        with self._lock:
            if self._rate >= self._ceiling:
                return
            self._rate = min(self._ceiling, self._rate + self._increase)
            self._bucket.set_qps(self._rate)

    def reserve(self) -> float:
        """Take one token; returns how long the caller must pace
        before issuing its call (0 when under the current rate)."""
        return self._bucket.when(None)


# ---------------------------------------------------------------------------
# per-service health + the guarded API proxy
# ---------------------------------------------------------------------------


@dataclass
class HealthConfig:
    window: float = 30.0
    min_calls: int = 10
    failure_ratio: float = 0.5
    open_duration: float = 15.0
    probe_budget: int = 1
    # AIMD: 0 disables pacing (circuit breaking only)
    aimd_qps: float = 20.0
    aimd_floor: float = 0.5
    aimd_increase: float = 0.2
    aimd_decrease: float = 0.5
    # never pace a single call longer than this — past it the caller
    # is better off requeueing than holding a worker
    max_pace_wait: float = 5.0


class ServiceHealth:
    """One service's breaker + limiter + counters."""

    def __init__(
        self,
        name: str,
        config: HealthConfig,
        clock: Optional[Callable[[], float]] = None,
        sleep: Optional[Callable[[float], None]] = None,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.name = name
        self._config = config
        self._sleep = sleep or clockseam.sleep
        self.breaker = CircuitBreaker(
            window=config.window,
            min_calls=config.min_calls,
            failure_ratio=config.failure_ratio,
            open_duration=config.open_duration,
            probe_budget=config.probe_budget,
            clock=clock,
        )
        self.limiter = (
            AIMDLimiter(
                qps=config.aimd_qps,
                floor=config.aimd_floor,
                increase=config.aimd_increase,
                decrease=config.aimd_decrease,
                clock=clock,
            )
            if config.aimd_qps > 0
            else None
        )
        # outcome counters and circuit/AIMD views live in the metrics
        # registry (ISSUE 5) — the registry children ARE the counters,
        # so /metrics, snapshot() and bench_detail read one source
        # instead of a privately maintained dict.  ``registry=None``
        # keeps a private registry (test isolation); the factory wires
        # the process-global one.
        metrics = instruments.health_instruments(
            registry if registry is not None else MetricsRegistry()
        )
        self._outcome_counters = {
            outcome: metrics.outcomes.labels(service=name, outcome=outcome)
            for outcome in (
                OUTCOME_SUCCESS,
                OUTCOME_THROTTLE,
                OUTCOME_SERVER_ERROR,
                OUTCOME_CONNECTION_ERROR,
            )
        }
        metrics.watch_service(self)

    def is_open(self) -> bool:
        return self.breaker.state() != STATE_CLOSED

    def before_call(self) -> None:
        """The pre-call gate: circuit check, then AIMD pacing (bounded
        by the worker's reconcile deadline)."""
        allowed, retry_after = self.breaker.allow()
        if not allowed:
            raise CircuitOpenError(self.name, retry_after)
        if self.limiter is None:
            return
        delay = min(self.limiter.reserve(), self._config.max_pace_wait)
        if delay <= 0:
            return
        remaining = deadline_remaining()
        if remaining is not None and remaining <= delay:
            raise DeadlineExceeded(
                f"{self.name}: {delay:.2f}s of adaptive pacing exceeds the "
                f"{remaining:.2f}s left on the reconcile deadline",
                paced=True,
            )
        self._sleep(delay)

    def record(self, outcome: Optional[str]) -> None:
        if outcome is None:
            return
        counter = self._outcome_counters.get(outcome)
        if counter is not None:
            counter.inc()
        self.breaker.record(outcome in _FAILURE_OUTCOMES)
        if self.limiter is not None:
            if outcome == OUTCOME_THROTTLE:
                self.limiter.on_throttle()
            elif outcome == OUTCOME_SUCCESS:
                self.limiter.on_success()

    def record_error(self, err: BaseException) -> None:
        self.record(classify_error(err))

    def snapshot(self) -> dict:
        # rendered FROM the registry children — /healthz, /readyz and
        # /metrics can never disagree about these counts
        counters = {
            outcome: int(counter.value())
            for outcome, counter in self._outcome_counters.items()
        }
        snap = {"circuit": self.breaker.snapshot(), "outcomes": counters}
        if self.limiter is not None:
            snap["aimd_rate"] = round(self.limiter.rate(), 3)
            snap["aimd_ceiling"] = round(self.limiter.ceiling(), 3)
        return snap

    def set_quota_fraction(self, fraction: float) -> None:
        """Scale this service's AIMD ceiling to a slice of the global
        budget (sharding quota division).  Clamped at the limiter's
        floor — a replica owning zero shards idles at floor qps, which
        is why the fleet-aggregate bound is stated over shard OWNERS
        (docs/operations.md "Horizontal sharding")."""
        if self.limiter is not None:
            self.limiter.set_ceiling(self._config.aimd_qps * fraction)


def _api_op_names(*interfaces) -> frozenset[str]:
    return frozenset(
        name
        for cls in interfaces
        for name, member in vars(cls).items()
        if inspect.isfunction(member) and not name.startswith("_")
    )


GA_OPS = _api_op_names(GlobalAcceleratorAPI)
ELBV2_OPS = _api_op_names(ELBv2API)
ROUTE53_OPS = _api_op_names(Route53API)
ALL_OPS = GA_OPS | ELBV2_OPS | ROUTE53_OPS


class HealthGuardedAPI:
    """Proxy one service handle through a ServiceHealth: the breaker
    gates every call, the AIMD limiter paces it, and the outcome is
    classified and recorded.  Non-API attributes pass through, so a
    guarded FakeAWSBackend keeps its test helpers."""

    def __init__(self, inner, health: ServiceHealth, ops: frozenset[str] = ALL_OPS):
        self._inner = inner
        self._health = health
        self._ops = ops

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name not in self._ops or not callable(attr):
            return attr
        health = self._health

        def guarded(*args, **kwargs):
            health.before_call()
            try:
                result = attr(*args, **kwargs)
            except Exception as err:
                health.record_error(err)
                raise
            health.record(OUTCOME_SUCCESS)
            return result

        return guarded


class HealthTracker:
    """Registry of per-service health.  Keys: ``globalaccelerator``,
    ``route53`` (global endpoints, like the drivers treat them) and
    ``elbv2[<region>]`` (regional); ``base_name`` matching strips the
    ``[...]`` suffix so callers can ask about "elbv2" as a whole."""

    def __init__(
        self,
        config: Optional[HealthConfig] = None,
        clock: Optional[Callable[[], float]] = None,
        sleep: Optional[Callable[[float], None]] = None,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.config = config or HealthConfig()
        self._clock = clock or clockseam.monotonic
        self._sleep = sleep or clockseam.sleep
        # one registry for every service's counters/gauges; private by
        # default (tests build many trackers per process), the factory
        # passes the process-global registry so /metrics carries them
        self.registry = registry if registry is not None else MetricsRegistry()
        self._lock = threading.Lock()
        self._services: dict[str, ServiceHealth] = {}
        # the sharding plane's budget slice (ISSUE 8): 1.0 = the whole
        # global budget (single-process semantics); a sharded replica
        # runs at owned/shard_count, rebalanced on every membership
        # change — services created later inherit the current fraction
        self._quota_fraction = 1.0

    def service(self, name: str) -> ServiceHealth:
        with self._lock:
            health = self._services.get(name)
            fraction = self._quota_fraction
            if health is None:
                health = self._services[name] = ServiceHealth(
                    name, self.config, clock=self._clock, sleep=self._sleep,
                    registry=self.registry,
                )
                if fraction != 1.0:
                    health.set_quota_fraction(fraction)
            return health

    def set_quota_fraction(self, fraction: float) -> None:
        """Divide the configured AIMD budget: every service ceiling
        becomes ``aimd_qps * fraction``, now and for services created
        later.  The shard membership's on-change hook drives this, so
        budget follows lease ownership."""
        with self._lock:
            self._quota_fraction = max(0.0, min(1.0, fraction))
            services = list(self._services.values())
            fraction = self._quota_fraction
        for service_health in services:
            service_health.set_quota_fraction(fraction)

    def quota_fraction(self) -> float:
        with self._lock:
            return self._quota_fraction

    def guard(self, inner, name: str, ops: frozenset[str] = ALL_OPS):
        return HealthGuardedAPI(inner, self.service(name), ops)

    @staticmethod
    def _base(name: str) -> str:
        return name.split("[", 1)[0]

    def is_open(self, base_name: str) -> bool:
        with self._lock:
            services = list(self._services.values())
        return any(
            s.name == base_name or self._base(s.name) == base_name
            for s in services
            if s.is_open()
        )

    def open_services(self) -> list[str]:
        with self._lock:
            services = list(self._services.values())
        return sorted(s.name for s in services if s.is_open())

    def snapshot(self) -> dict:
        with self._lock:
            services = dict(self._services)
        return {name: health.snapshot() for name, health in sorted(services.items())}


# ---------------------------------------------------------------------------
# worker heartbeats + watchdog
# ---------------------------------------------------------------------------


class WorkerHeartbeats:
    """What every worker thread is reconciling and since when — the
    liveness table behind the stuck-worker watchdog, the manager's
    ``/healthz``, and shutdown's who-wedged-on-what logging."""

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._clock = clock or clockseam.monotonic
        self._lock = threading.Lock()
        self._table: dict[str, tuple[str, float]] = {}  # thread -> (key, since)

    def begin(self, key: str) -> None:
        with self._lock:
            self._table[threading.current_thread().name] = (key, self._clock())

    def done(self) -> None:
        with self._lock:
            self._table.pop(threading.current_thread().name, None)

    def current_key(self, thread_name: str) -> Optional[str]:
        with self._lock:
            entry = self._table.get(thread_name)
            return entry[0] if entry else None

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            now = self._clock()
            return {
                thread: {"key": key, "age": round(now - since, 3)}
                for thread, (key, since) in sorted(self._table.items())
            }

    def stuck(self, threshold: float) -> list[tuple[str, str, float]]:
        """(thread, key, age) for workers on one item longer than
        ``threshold`` seconds."""
        with self._lock:
            now = self._clock()
            return [
                (thread, key, now - since)
                for thread, (key, since) in sorted(self._table.items())
                if now - since >= threshold
            ]


_heartbeats = WorkerHeartbeats()


def worker_heartbeats() -> WorkerHeartbeats:
    """The process-wide heartbeat table (one reconcile loop per
    process; tests build their own WorkerHeartbeats)."""
    return _heartbeats


def start_worker_watchdog(
    stop: threading.Event,
    heartbeats: Optional[WorkerHeartbeats] = None,
    interval: float = 30.0,
    threshold: float = 300.0,
) -> Optional[threading.Thread]:
    """Daemon that periodically surfaces workers stuck on one item
    past ``threshold`` seconds (a wedged settle poll, a hung call):
    the log line names the worker and the reconcile key so the wedge
    is diagnosable while it is happening, not from a post-mortem.

    Under the sim's cooperative executor (``threads_enabled()`` false)
    this starts nothing and returns None — the sim owns every
    interleaving, and a wild watchdog thread would race virtual time."""
    if not clockseam.threads_enabled():
        return None
    table = heartbeats or worker_heartbeats()

    def loop():
        while not stop.wait(interval):
            for thread, key, age in table.stuck(threshold):
                klog.warningf(
                    "worker %s stuck reconciling %r for %.0fs (threshold %.0fs)",
                    thread, key, age, threshold,
                )

    thread = threading.Thread(target=loop, daemon=True, name="worker-watchdog")
    thread.start()
    return thread
