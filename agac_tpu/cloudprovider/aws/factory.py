"""Cloud factory used by the CLI process.

The production analog of the reference's inline ``NewAWS(region)``
calls: one driver per region, with GA/Route53 pinned to the global
endpoint region (us-west-2, reference ``aws.go:26-32``).

``AGAC_CLOUD=fake`` switches the whole process onto one shared
in-memory backend — the no-credentials demo/e2e mode (the reference
has no equivalent; its e2e needs real AWS).  The fake can be seeded
from the environment so annotated Services find their load balancers:

- ``AGAC_FAKE_LBS``: comma-separated ``name=hostname`` pairs (region
  is parsed from the hostname);
- ``AGAC_FAKE_ZONES``: comma-separated hosted-zone names;
- ``AGAC_FAKE_STATE``: path to a JSON state file that makes the fake
  DURABLE across process generations (``FileBackedFakeAWSBackend``) —
  the kill-recovery drills' ground truth;
- ``AGAC_FAKE_CRASH``: ``op:when[,op:when...]`` one-shot crash faults
  mapped to hard process death (``os._exit(137)``) at the exact API
  boundary — the in-repo ``kill -9`` (see ``FaultPlan.crash``).

The default mode builds the real SigV4 HTTP backend.

Cache wiring: one process-wide instance of each cache, shared by the
per-reconcile drivers — the discovery and hosted-zone snapshots plus
the three coalesced-read-plane caches (accelerator topology, per-zone
record sets, and the per-REGION DescribeLoadBalancers coalescers; a
batch goes out through one region's endpoint, so coalescers must
never be shared across regions).  TTLs come from the environment
(table in docs/operations.md "Runtime knobs"); the ``controller``
subcommand's ``--read-plane-ttl`` flag feeds ``configure_read_plane``.
"""

from __future__ import annotations

import os
import threading

from ...observability import instruments as obs_instruments
from ...observability import metrics as obs_metrics
from ...reconcile.pending import PendingSettleTable
from .batcher import ChangeBatcher
from .cache import (
    AcceleratorTopologyCache,
    DiscoveryCache,
    HostedZoneCache,
    LoadBalancerCoalescer,
    RecordSetCache,
)
from .driver import AWSDriver
from .fake_backend import FakeAWSBackend, FaultPlan, FileBackedFakeAWSBackend
from .health import ELBV2_OPS, GA_OPS, ROUTE53_OPS, HealthConfig, HealthTracker
from .load_balancer import get_lb_name_from_hostname

_fake_backend: FakeAWSBackend | None = None
_lock = threading.Lock()
# process-wide API health plane (circuit breakers + AIMD pacing)
_health_tracker: HealthTracker | None = None
# process-wide cache singletons shared by the per-reconcile drivers
_discovery_cache: DiscoveryCache | None = None
_zone_cache: HostedZoneCache | None = None
_topology_cache: AcceleratorTopologyCache | None = None
_record_cache: RecordSetCache | None = None
_lb_coalescers: dict[str, LoadBalancerCoalescer] = {}
# the async mutation pipeline (ISSUE 6): one pending-settle table and
# one per-zone change batcher per process, shared by every driver
_settle_table: PendingSettleTable | None = None
_change_batcher: ChangeBatcher | None = None

# memoized TTL values (env parsed once per process; a malformed value
# must not poison every reconcile — fall back and say so once)
_ttl_values: dict[str, float] = {}  # agac-lint: ignore[shared-state-census] -- idempotent env memo; racing fills store the same parsed value
# explicit overrides (CLI flags) beat the environment
_ttl_overrides: dict[str, float] = {}


def _env_float(name: str, default: float) -> float:
    if name in _ttl_overrides:
        return _ttl_overrides[name]
    if name in _ttl_values:
        return _ttl_values[name]
    raw = os.environ.get(name, str(default))
    try:
        value = float(raw)
    except ValueError:
        from ... import klog

        klog.errorf("%s=%r is not a number; using default %gs", name, raw, default)
        value = default
    _ttl_values[name] = value
    return value


def configure_read_plane(ttl: float | None) -> None:
    """Pin the three read-plane TTLs from the CLI (``--read-plane-ttl``):
    one knob for the verification-read tick scope.  ``None`` keeps the
    per-cache environment variables / defaults; 0 disables the read
    plane entirely (reference-parity per-object reads)."""
    if ttl is None:
        return
    for name in (
        "AGAC_TOPOLOGY_VERIFY_TTL",
        "AGAC_RECORDSET_CACHE_TTL",
        "AGAC_LB_CACHE_TTL",
    ):
        _ttl_overrides[name] = ttl


def configure_pipeline(
    settle_poll_interval: float | None = None,
    r53_batch_max: float | None = None,
    r53_batch_linger: float | None = None,
) -> None:
    """Pin the async-mutation-pipeline knobs from the CLI
    (``--settle-poll-interval`` / ``--r53-batch-max`` /
    ``--r53-batch-linger``); ``None`` keeps the per-knob environment
    variables / defaults.  settle interval 0 disables the
    pending-settle table (reference-parity blocking settle); linger 0
    disables Route53 change batching (one wire call per mutation)."""
    for name, value in (
        ("AGAC_SETTLE_POLL_INTERVAL", settle_poll_interval),
        ("AGAC_R53_BATCH_MAX", r53_batch_max),
        ("AGAC_R53_BATCH_LINGER", r53_batch_linger),
    ):
        if value is not None:
            _ttl_overrides[name] = value


def settle_poll_interval() -> float:
    """The pending-settle scheduler's tick period: each tick re-checks
    every parked chain in coalesced reads.  1 s default — the checks
    are one ListAccelerators for all parked teardowns plus pure
    in-memory peeks, so a tight tick is cheap and convergence latency
    for resolved waits stays ~1 s.  0 disables the whole table."""
    return _env_float("AGAC_SETTLE_POLL_INTERVAL", 1.0)


def shared_settle_table() -> PendingSettleTable | None:
    """The process-wide pending-settle table, or None when disabled
    (``AGAC_SETTLE_POLL_INTERVAL=0``).  The manager runs the poll-tick
    scheduler over it (``Manager.run``)."""
    global _settle_table
    if settle_poll_interval() <= 0:
        return None
    with _lock:
        if _settle_table is None:
            _settle_table = PendingSettleTable(registry=obs_metrics.registry())
        return _settle_table


def shared_change_batcher() -> ChangeBatcher | None:
    """The process-wide per-zone Route53 change batcher, or None when
    disabled (``AGAC_R53_BATCH_LINGER=0``, the default — batching is
    opt-in until a deployment raises the linger; see docs/operations.md
    "Async mutation pipeline")."""
    global _change_batcher
    linger = _env_float("AGAC_R53_BATCH_LINGER", 0.0)
    if linger <= 0:
        return None
    with _lock:
        if _change_batcher is None:
            _change_batcher = ChangeBatcher(
                max_changes=int(_env_float("AGAC_R53_BATCH_MAX", 100)),
                linger=linger,
                registry=obs_metrics.registry(),
            )
        return _change_batcher


def _chain_stage_requeue() -> float:
    """Stage-yield requeue delay for the interleaved accelerator
    chain; 0 disables staging (one worker holds the item across the
    whole create chain — reference parity)."""
    if _env_float("AGAC_CHAIN_STAGES", 1.0) <= 0:
        return 0.0
    return _env_float("AGAC_CHAIN_STAGE_REQUEUE", 0.01)


def pipeline_stats() -> dict:
    """Pending-settle + batcher counters — the bench/healthz hook."""
    with _lock:
        table, batcher = _settle_table, _change_batcher
    stats = {}
    if table is not None:
        stats["pending_settle"] = table.stats()
    if batcher is not None:
        stats["r53_batching"] = batcher.stats()
    return stats


def configure_api_health(
    window: float | None = None,
    failure_ratio: float | None = None,
    min_calls: float | None = None,
    open_duration: float | None = None,
    probe_budget: float | None = None,
    aimd_qps: float | None = None,
) -> None:
    """Pin the API health plane knobs from the CLI (``--api-health-*``
    flags); ``None`` keeps the per-knob environment variables /
    defaults.  window 0 disables the whole plane (reference-parity
    fixed-rate retries)."""
    for name, value in (
        ("AGAC_API_HEALTH_WINDOW", window),
        ("AGAC_API_HEALTH_FAILURE_RATIO", failure_ratio),
        ("AGAC_API_HEALTH_MIN_CALLS", min_calls),
        ("AGAC_API_HEALTH_OPEN_DURATION", open_duration),
        ("AGAC_API_HEALTH_PROBE_BUDGET", probe_budget),
        ("AGAC_API_HEALTH_AIMD_QPS", aimd_qps),
    ):
        if value is not None:
            _ttl_overrides[name] = value


def shared_health_tracker() -> HealthTracker | None:
    """The process-wide health tracker, or None when disabled
    (``AGAC_API_HEALTH_WINDOW=0``).  Knob table in docs/operations.md
    "API health plane"."""
    global _health_tracker
    # 30 s rolling window / 50% failure ratio over >= 10 calls: wide
    # enough that one unlucky burst of throttles never trips the
    # breaker, tight enough that a real brownout opens it within one
    # drift verify round
    window = _env_float("AGAC_API_HEALTH_WINDOW", 30.0)
    if window <= 0:
        return None
    with _lock:
        if _health_tracker is None:
            _health_tracker = HealthTracker(
                registry=obs_metrics.registry(),
                config=HealthConfig(
                    window=window,
                    min_calls=int(_env_float("AGAC_API_HEALTH_MIN_CALLS", 10)),
                    failure_ratio=_env_float("AGAC_API_HEALTH_FAILURE_RATIO", 0.5),
                    # 15 s open: long enough to actually shed load,
                    # short enough that recovery is noticed within one
                    # requeue interval
                    open_duration=_env_float("AGAC_API_HEALTH_OPEN_DURATION", 15.0),
                    probe_budget=int(_env_float("AGAC_API_HEALTH_PROBE_BUDGET", 1)),
                    # AIMD ceiling: 20 calls/s per service per process
                    # (comfortably above steady-state need; the point
                    # is the multiplicative cut under throttling)
                    aimd_qps=_env_float("AGAC_API_HEALTH_AIMD_QPS", 20.0),
                )
            )
        return _health_tracker


def api_health_stats() -> dict:
    """Per-circuit state + outcome counters — the observability hook
    the manager's /readyz endpoint and the bench export."""
    with _lock:
        tracker = _health_tracker
    return tracker.snapshot() if tracker is not None else {}


def _discovery_cache_ttl() -> float:
    # 30 s default: the write journal (cache.py) makes the TTL a pure
    # cross-process staleness bound — local writes are always visible —
    # so it can match the 30 s informer-resync staleness the reference
    # already tolerates; measured at N=1000 this cuts refresh scans 6x
    # vs the old 5 s with no correctness cost
    return _env_float("AGAC_DISCOVERY_CACHE_TTL", 30.0)


def _zone_cache_ttl() -> float:
    # 60 s: hosted zones are created by humans, not this controller —
    # the TTL only bounds how long a zone deleted out-of-band keeps
    # resolving (and the ensure path invalidates explicitly on
    # NoSuchHostedZone anyway); 0 disables
    return _env_float("AGAC_ZONE_CACHE_TTL", 60.0)


def _shared_zone_cache() -> HostedZoneCache | None:
    global _zone_cache
    ttl = _zone_cache_ttl()
    if ttl <= 0:
        return None
    with _lock:
        if _zone_cache is None:
            _zone_cache = HostedZoneCache(ttl=ttl)
        return _zone_cache


def _shared_discovery_cache() -> DiscoveryCache | None:
    global _discovery_cache
    ttl = _discovery_cache_ttl()
    if ttl <= 0:
        return None
    tracker = shared_health_tracker()
    # 300 s: between full tag re-lists, snapshot reloads REUSE known
    # accelerators' tags (local writes are write-through exact) and
    # only new arns pay a live ListTagsForResource — the O(N) tag-read
    # stall per reload is gone, at the cost of out-of-band TAG edits
    # being detected within 300 s instead of the 30 s snapshot TTL
    # (ISSUE 6 satellite; bound documented in docs/operations.md).
    # <= 0 restores the legacy full re-read per reload.
    tags_ttl = _env_float("AGAC_DISCOVERY_TAGS_TTL", 300.0)
    with _lock:
        if _discovery_cache is None:
            _discovery_cache = DiscoveryCache(
                ttl=ttl,
                tags_ttl=tags_ttl if tags_ttl > 0 else None,
                # degraded mode: with the GA circuit open, serve the
                # expired discovery snapshot stale rather than dispatch
                # a doomed O(N) rescan (staleness bound: the outage)
                degraded=(
                    (lambda: tracker.is_open("globalaccelerator"))
                    if tracker is not None
                    else None
                ),
            )
        return _discovery_cache


def _shared_topology_cache() -> AcceleratorTopologyCache | None:
    global _topology_cache
    # 15 s verify window: the verification dedup scope of one drift
    # tick (periods are >= 300 s at any fleet size worth ticking, see
    # docs/operations.md); 0 disables.  The full-relist TTL bounds how
    # long the write-through listener identity is trusted before ports/
    # protocol are re-read from AWS — 900 s keeps that within a few
    # ticks at production periods.
    verify_ttl = _env_float("AGAC_TOPOLOGY_VERIFY_TTL", 15.0)
    full_ttl = _env_float("AGAC_TOPOLOGY_FULL_TTL", 900.0)
    if verify_ttl <= 0:
        return None
    with _lock:
        if _topology_cache is None:
            _topology_cache = AcceleratorTopologyCache(
                verify_ttl=verify_ttl, full_ttl=max(full_ttl, verify_ttl)
            )
        return _topology_cache


def _shared_record_cache() -> RecordSetCache | None:
    global _record_cache
    # 15 s: the per-zone snapshot scope of one verification round; the
    # driver folds its own change batches back in, so the TTL only
    # bounds detection of OUT-OF-BAND record edits; 0 disables
    ttl = _env_float("AGAC_RECORDSET_CACHE_TTL", 15.0)
    if ttl <= 0:
        return None
    tracker = shared_health_tracker()
    with _lock:
        if _record_cache is None:
            _record_cache = RecordSetCache(
                ttl=ttl,
                # degraded mode: with the Route53 circuit open, serve
                # expired zone snapshots stale (see DiscoveryCache)
                degraded=(
                    (lambda: tracker.is_open("route53"))
                    if tracker is not None
                    else None
                ),
            )
        return _record_cache


def _shared_lb_coalescer(region: str) -> LoadBalancerCoalescer | None:
    # 15 s: LB state/DNS are re-read every verification round; the
    # 10 ms gather window turns a tick's concurrent single-name
    # lookups into ~worker-pool-sized wire batches; 0 disables
    ttl = _env_float("AGAC_LB_CACHE_TTL", 15.0)
    if ttl <= 0:
        return None
    window = _env_float("AGAC_LB_BATCH_WINDOW", 0.01)
    with _lock:
        coalescer = _lb_coalescers.get(region)
        if coalescer is None:
            coalescer = _lb_coalescers[region] = LoadBalancerCoalescer(
                ttl=ttl, batch_window=max(window, 0.0)
            )
        return coalescer


def _seed_from_environment(backend: FakeAWSBackend) -> None:
    from ... import klog

    for pair in filter(None, os.environ.get("AGAC_FAKE_LBS", "").split(",")):
        name, _, hostname = pair.partition("=")
        if not hostname:
            continue
        try:
            _, region = get_lb_name_from_hostname(hostname)
        except ValueError as err:
            # a malformed entry must not poison every reconcile or
            # leave the backend half-seeded
            klog.errorf("AGAC_FAKE_LBS: skipping %r: %s", pair, err)
            continue
        backend.add_load_balancer(name, region, hostname)
    for zone in filter(None, os.environ.get("AGAC_FAKE_ZONES", "").split(",")):
        backend.add_hosted_zone(zone)


def _install_crash_plan(backend: FakeAWSBackend) -> None:
    """``AGAC_FAKE_CRASH=op:when[,op:when...]`` arms one-shot crash
    faults (``FaultPlan.crash``) on the shared fake backend, mapped to
    hard process death — the ``kill -9`` analog the kill-recovery
    drills in ``tests/test_process_e2e.py`` drive.  ``when`` is
    ``before`` (default) or ``after-commit``."""
    raw = os.environ.get("AGAC_FAKE_CRASH", "")
    if not raw:
        return
    from ... import klog

    plan = backend.install_fault_plan(FaultPlan(exempt_creator=False))
    for entry in filter(None, raw.split(",")):
        op, _, when = entry.partition(":")
        plan.crash(op.strip(), when=when.strip() or "before")

    def die(crash):
        klog.errorf("AGAC_FAKE_CRASH: %s — exiting hard", crash)
        os._exit(137)  # the kill -9 exit status, uncatchable like it

    plan.on_crash = die


def shared_fake_backend() -> FakeAWSBackend:
    global _fake_backend
    with _lock:
        if _fake_backend is None:
            # AGAC_FAKE_STATE makes the fake AWS durable (a JSON state
            # file shared across process generations) — committed
            # mutations survive a kill -9, which is what makes crash
            # drills against AGAC_CLOUD=fake meaningful
            state_path = os.environ.get("AGAC_FAKE_STATE", "")
            # AGAC_FAKE_SETTLE=N makes accelerator create/update settle
            # through N describe/list reads before DEPLOYED — the seam
            # the kill-mid-settle process drill uses to exercise the
            # pending-settle path against a real controller process
            settle = int(os.environ.get("AGAC_FAKE_SETTLE", "0") or 0)
            # AGAC_FAKE_LATENCY=S shapes every fake API call with S
            # seconds of wire latency — the multi-process sharding
            # bench's capacity model (worker pool x latency per
            # process)
            latency = float(os.environ.get("AGAC_FAKE_LATENCY", "0") or 0)
            # AGAC_FAKE_QUOTA_ACCELERATORS raises the fake account's
            # accelerator quota (default 20) the way a real account
            # requests a quota increase — fleet-scale process drills
            # and the sharding bench need hundreds
            quota = int(os.environ.get("AGAC_FAKE_QUOTA_ACCELERATORS", "20") or 20)
            if state_path:
                _fake_backend = FileBackedFakeAWSBackend(
                    state_path, settle_describes=settle, latency=latency,
                    quota_accelerators=quota,
                )
            else:
                _fake_backend = FakeAWSBackend(
                    settle_describes=settle, latency=latency,
                    quota_accelerators=quota,
                )
            _seed_from_environment(_fake_backend)
            _install_crash_plan(_fake_backend)
        return _fake_backend


def invalidate_read_plane() -> None:
    """Drop every process-wide read-plane snapshot (ISSUE 8): wired as
    ``Manager.on_reshard``, so a replica adopting another process's
    keyspace re-reads AWS instead of trusting snapshots taken before
    the ownership change — a stale discovery snapshot at adoption time
    means duplicate accelerators."""
    with _lock:
        discovery, zones = _discovery_cache, _zone_cache
        topology, records = _topology_cache, _record_cache
    if discovery is not None:
        discovery.invalidate()
    if zones is not None:
        zones.invalidate()
    if topology is not None:
        topology.invalidate_all()
    if records is not None:
        records.invalidate_all()


def read_plane_stats() -> dict:
    """Efficacy counters of every live cache (hits / misses /
    single-flight waits / batch sizes) — the observability hook the
    bench exports per phase."""
    stats = {}
    with _lock:
        named = {
            "discovery": _discovery_cache,
            "zones": _zone_cache,
            "topology": _topology_cache,
            "record_sets": _record_cache,
        }
        coalescers = dict(_lb_coalescers)
    for name, cache in named.items():
        if cache is not None:
            stats[name] = cache.stats()
    for region, coalescer in coalescers.items():
        stats[f"load_balancers[{region}]"] = coalescer.stats()
    return stats


def _guarded_handles(ga, elbv2, route53, region: str):
    """Wrap the three service handles in the health plane's guards
    (circuit gate + AIMD pacing + outcome classification); pass-through
    when the plane is disabled.  GA and Route53 are global endpoints —
    one circuit each; ELBv2 is regional — one circuit per region."""
    tracker = shared_health_tracker()
    if tracker is None:
        return ga, elbv2, route53
    return (
        tracker.guard(ga, "globalaccelerator", GA_OPS),
        tracker.guard(elbv2, f"elbv2[{region}]", ELBV2_OPS),
        tracker.guard(route53, "route53", ROUTE53_OPS),
    )


def _driver_timing() -> dict:
    """Driver pacing knobs, env-overridable: production keeps the
    reference's constants (10 s settle poll / 180 s budget, 30 s
    LB-not-active requeue, 60 s accelerator-missing requeue); the
    fake-backed drills and demos shrink them so convergence is
    observable in seconds."""
    from .driver import ACCELERATOR_MISSING_RETRY, LB_NOT_ACTIVE_RETRY

    return dict(
        poll_interval=_env_float("AGAC_POLL_INTERVAL", 10.0),
        poll_timeout=_env_float("AGAC_POLL_TIMEOUT", 180.0),
        lb_not_active_retry=_env_float(
            "AGAC_LB_NOT_ACTIVE_RETRY", LB_NOT_ACTIVE_RETRY
        ),
        accelerator_missing_retry=_env_float(
            "AGAC_ACCELERATOR_MISSING_RETRY", ACCELERATOR_MISSING_RETRY
        ),
    )


def real_cloud_factory(region: str) -> AWSDriver:
    caches = dict(
        discovery_cache=_shared_discovery_cache(),
        zone_cache=_shared_zone_cache(),
        topology_cache=_shared_topology_cache(),
        record_cache=_shared_record_cache(),
        lb_coalescer=_shared_lb_coalescer(region),
        settle_table=shared_settle_table(),
        change_batcher=shared_change_batcher(),
        stage_requeue=_chain_stage_requeue(),
        **_driver_timing(),
    )
    # expose every live cache's hit/miss counters as collection-time
    # gauges on the global registry (ISSUE 5) — the caches keep their
    # own counters, /metrics reads them through read_plane_stats
    obs_instruments.read_plane_instruments(obs_metrics.registry()).watch_stats(
        read_plane_stats
    )
    if os.environ.get("AGAC_CLOUD") == "fake":
        backend = shared_fake_backend()
        ga, elbv2, route53 = _guarded_handles(backend, backend, backend, region)
        return AWSDriver(ga, elbv2, route53, **caches)
    from .real_backend import RealAWSClients

    clients = RealAWSClients.from_environment(region)
    tracker = shared_health_tracker()
    if tracker is not None:
        # the in-client retry loop reports per-attempt throttle/5xx
        # classifications, so a brownout the 3-attempt retries keep
        # absorbing still drives the AIMD limiter down
        clients.ga.set_outcome_hook(tracker.service("globalaccelerator").record)
        clients.elbv2.set_outcome_hook(tracker.service(f"elbv2[{region}]").record)
        clients.route53.set_outcome_hook(tracker.service("route53").record)
    ga, elbv2, route53 = _guarded_handles(
        clients.ga, clients.elbv2, clients.route53, region
    )
    return AWSDriver(ga, elbv2, route53, **caches)
