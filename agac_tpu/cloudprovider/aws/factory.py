"""Cloud factory used by the CLI process.

The production analog of the reference's inline ``NewAWS(region)``
calls: one driver per region, with GA/Route53 pinned to the global
endpoint region (us-west-2, reference ``aws.go:26-32``).

``AGAC_CLOUD=fake`` switches the whole process onto one shared
in-memory backend — the no-credentials demo/e2e mode (the reference
has no equivalent; its e2e needs real AWS).  The fake can be seeded
from the environment so annotated Services find their load balancers:

- ``AGAC_FAKE_LBS``: comma-separated ``name=hostname`` pairs (region
  is parsed from the hostname);
- ``AGAC_FAKE_ZONES``: comma-separated hosted-zone names.

The default mode builds the real SigV4 HTTP backend.
"""

from __future__ import annotations

import os
import threading

from .cache import DiscoveryCache, HostedZoneCache
from .driver import AWSDriver
from .fake_backend import FakeAWSBackend
from .load_balancer import get_lb_name_from_hostname

_fake_backend: FakeAWSBackend | None = None
_lock = threading.Lock()
# one process-wide discovery cache shared by the per-reconcile drivers
# (ttl via AGAC_DISCOVERY_CACHE_TTL; 0 disables)
_discovery_cache: DiscoveryCache | None = None


_discovery_ttl: float | None = None


def _discovery_cache_ttl() -> float:
    global _discovery_ttl
    if _discovery_ttl is not None:
        return _discovery_ttl
    # 30 s default: the write journal (cache.py) makes the TTL a pure
    # cross-process staleness bound — local writes are always visible —
    # so it can match the 30 s informer-resync staleness the reference
    # already tolerates; measured at N=1000 this cuts refresh scans 6x
    # vs the old 5 s with no correctness cost
    raw = os.environ.get("AGAC_DISCOVERY_CACHE_TTL", "30")
    try:
        ttl = float(raw)
    except ValueError:
        # a malformed value must not poison every reconcile; fall back
        # to the default and say so once per process (memoization
        # below is the dedup)
        from ... import klog

        klog.errorf(
            "AGAC_DISCOVERY_CACHE_TTL=%r is not a number; using default 30s", raw
        )
        ttl = 30.0
    _discovery_ttl = ttl
    return ttl


_zone_cache: HostedZoneCache | None = None
_zone_ttl: float | None = None


def _zone_cache_ttl() -> float:
    global _zone_ttl
    if _zone_ttl is not None:
        return _zone_ttl
    # 60 s: hosted zones are created by humans, not this controller —
    # the TTL only bounds how long a zone deleted out-of-band keeps
    # resolving (and the ensure path invalidates explicitly on
    # NoSuchHostedZone anyway); 0 disables
    raw = os.environ.get("AGAC_ZONE_CACHE_TTL", "60")
    try:
        ttl = float(raw)
    except ValueError:
        from ... import klog

        klog.errorf("AGAC_ZONE_CACHE_TTL=%r is not a number; using default 60s", raw)
        ttl = 60.0
    _zone_ttl = ttl
    return ttl


def _shared_zone_cache() -> HostedZoneCache | None:
    global _zone_cache
    ttl = _zone_cache_ttl()
    if ttl <= 0:
        return None
    with _lock:
        if _zone_cache is None:
            _zone_cache = HostedZoneCache(ttl=ttl)
        return _zone_cache


def _shared_discovery_cache() -> DiscoveryCache | None:
    global _discovery_cache
    ttl = _discovery_cache_ttl()
    if ttl <= 0:
        return None
    with _lock:
        if _discovery_cache is None:
            _discovery_cache = DiscoveryCache(ttl=ttl)
        return _discovery_cache


def _seed_from_environment(backend: FakeAWSBackend) -> None:
    from ... import klog

    for pair in filter(None, os.environ.get("AGAC_FAKE_LBS", "").split(",")):
        name, _, hostname = pair.partition("=")
        if not hostname:
            continue
        try:
            _, region = get_lb_name_from_hostname(hostname)
        except ValueError as err:
            # a malformed entry must not poison every reconcile or
            # leave the backend half-seeded
            klog.errorf("AGAC_FAKE_LBS: skipping %r: %s", pair, err)
            continue
        backend.add_load_balancer(name, region, hostname)
    for zone in filter(None, os.environ.get("AGAC_FAKE_ZONES", "").split(",")):
        backend.add_hosted_zone(zone)


def shared_fake_backend() -> FakeAWSBackend:
    global _fake_backend
    with _lock:
        if _fake_backend is None:
            _fake_backend = FakeAWSBackend()
            _seed_from_environment(_fake_backend)
        return _fake_backend


def real_cloud_factory(region: str) -> AWSDriver:
    cache = _shared_discovery_cache()
    zone_cache = _shared_zone_cache()
    if os.environ.get("AGAC_CLOUD") == "fake":
        backend = shared_fake_backend()
        return AWSDriver(
            backend, backend, backend,
            discovery_cache=cache, zone_cache=zone_cache,
        )
    from .real_backend import RealAWSClients

    clients = RealAWSClients.from_environment(region)
    return AWSDriver(
        clients.ga, clients.elbv2, clients.route53,
        discovery_cache=cache, zone_cache=zone_cache,
    )
