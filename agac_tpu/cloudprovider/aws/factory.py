"""Cloud factory used by the CLI process.

The production analog of the reference's inline ``NewAWS(region)``
calls: one driver per region, with GA/Route53 pinned to the global
endpoint region (us-west-2, reference ``aws.go:26-32``).

``AGAC_CLOUD=fake`` switches the whole process onto one shared
in-memory backend — the no-credentials demo/e2e mode (the reference
has no equivalent; its e2e needs real AWS).  The default mode builds
the real SigV4 HTTP backend.
"""

from __future__ import annotations

import os
import threading

from .driver import AWSDriver
from .fake_backend import FakeAWSBackend

_fake_backend: FakeAWSBackend | None = None
_lock = threading.Lock()


def shared_fake_backend() -> FakeAWSBackend:
    global _fake_backend
    with _lock:
        if _fake_backend is None:
            _fake_backend = FakeAWSBackend()
        return _fake_backend


def real_cloud_factory(region: str) -> AWSDriver:
    if os.environ.get("AGAC_CLOUD") == "fake":
        backend = shared_fake_backend()
        return AWSDriver(backend, backend, backend)
    from .real_backend import RealAWSClients

    clients = RealAWSClients.from_environment(region)
    return AWSDriver(clients.ga, clients.elbv2, clients.route53)
