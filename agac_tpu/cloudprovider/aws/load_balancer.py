"""ELB hostname reverse-engineering.

Capability parity with the reference's
``pkg/cloudprovider/aws/load_balancer.go:32-98``: the controllers only
have the LB hostname from Service/Ingress status, and must recover the
LB *name* (to DescribeLoadBalancers) and *region* (to build a regional
client) from it.  Four hostname shapes exist:

- public ALB:    ``<name>-<hash>.<region>.elb.amazonaws.com``
- internal ALB:  ``internal-<name>-<hash>.<region>.elb.amazonaws.com``
- public NLB:    ``<name>-<hash>.elb.<region>.amazonaws.com``
- internal NLB:  ``<name>-<hash>.elb.<region>.amazonaws.com``

(ALBs put the region *after* ``elb``; NLBs before — the regexes keyed
on that, reference ``load_balancer.go:33-34``.)  The unit-test table in
``load_balancer_test.go:9-50`` is the behavioral contract.
"""

from __future__ import annotations

import re

_ALB_SUFFIX = re.compile(r"\.elb\.amazonaws\.com$")
_NLB_SUFFIX = re.compile(r"\.elb\..+\.amazonaws\.com$")
_INTERNAL_PREFIX = re.compile(r"^internal-")
_INTERNAL_ALB_NAME = re.compile(r"^internal\-([\w\-]+)\-[\w]+$")
_NAME_WITH_HASH = re.compile(r"^([\w\-]+)\-[\w]+$")


def get_lb_name_from_hostname(hostname: str) -> tuple[str, str]:
    """Return (lb_name, region) parsed from an ELB hostname.

    Raises ValueError for hostnames that are not Elastic Load
    Balancers or do not parse.
    """
    if _ALB_SUFFIX.search(hostname):
        return _match_alb_hostname(hostname)
    if _NLB_SUFFIX.search(hostname):
        return _match_nlb_hostname(hostname)
    raise ValueError(f"{hostname} is not Elastic Load Balancer")


def _match_alb_hostname(hostname: str) -> tuple[str, str]:
    parts = hostname.split(".")
    subdomain, region = parts[0], parts[1]
    if _INTERNAL_PREFIX.search(subdomain):
        match = _INTERNAL_ALB_NAME.match(subdomain)
        if not match:
            raise ValueError(f"Failed to parse subdomain for internal ALB: {subdomain}")
        return match.group(1), region
    match = _NAME_WITH_HASH.match(subdomain)
    if not match:
        raise ValueError(f"Failed to parse subdomain for public ALB: {subdomain}")
    return match.group(1), region


def _match_nlb_hostname(hostname: str) -> tuple[str, str]:
    parts = hostname.split(".")
    subdomain, region = parts[0], parts[2]
    match = _NAME_WITH_HASH.match(subdomain)
    if not match:
        raise ValueError(f"Failed to parse subdomain for NLB: {subdomain}")
    return match.group(1), region


def get_region_from_arn(arn: str) -> str:
    """ARNs are ``arn:partition:service:region:account:resource``
    (reference ``load_balancer.go:95-98``)."""
    return arn.split(":")[3]
