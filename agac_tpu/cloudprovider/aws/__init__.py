"""AWS cloud layer: resource types, API interfaces, the in-memory
fake backend, and the high-level resource drivers (Global Accelerator,
Route53, ELBv2 lookups).

Deliberate improvement over the reference (SURVEY.md §7 stage 3): the
drivers depend on abstract API interfaces instead of concrete SDK
clients, so the fake backend can be injected and the whole driver
logic — ownership tags, drift detection, rollback, delete
orchestration — is unit-testable.  The reference constructs ``NewAWS``
inline in its process funcs (e.g.
``pkg/controller/globalaccelerator/service.go:35,65,101``), which is
why its AWS layer has no unit tests.
"""

from .types import (
    Accelerator,
    AliasTarget,
    Change,
    EndpointConfiguration,
    EndpointDescription,
    EndpointGroup,
    HostedZone,
    Listener,
    LoadBalancer,
    PortRange,
    ResourceRecord,
    ResourceRecordSet,
    Tag,
)
from .errors import (
    AWSAPIError,
    ERR_ENDPOINT_GROUP_NOT_FOUND,
    ERR_LISTENER_NOT_FOUND,
    EndpointGroupNotFoundException,
    ListenerNotFoundException,
    aws_error_code,
)
from .load_balancer import get_lb_name_from_hostname, get_region_from_arn
from .cache import (
    AcceleratorTopologyCache,
    DiscoveryCache,
    HostedZoneCache,
    LoadBalancerCoalescer,
    RecordSetCache,
)
from .driver import AWSDriver, Route53OwnerValue
from .fake_backend import FakeAWSBackend, FaultPlan
from .health import (
    AIMDLimiter,
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceeded,
    HealthConfig,
    HealthTracker,
    WorkerHeartbeats,
)

__all__ = [
    "Accelerator",
    "Tag",
    "Listener",
    "PortRange",
    "EndpointGroup",
    "EndpointDescription",
    "EndpointConfiguration",
    "LoadBalancer",
    "HostedZone",
    "ResourceRecordSet",
    "ResourceRecord",
    "AliasTarget",
    "Change",
    "AWSAPIError",
    "ListenerNotFoundException",
    "EndpointGroupNotFoundException",
    "ERR_LISTENER_NOT_FOUND",
    "ERR_ENDPOINT_GROUP_NOT_FOUND",
    "aws_error_code",
    "get_lb_name_from_hostname",
    "get_region_from_arn",
    "AWSDriver",
    "Route53OwnerValue",
    "FakeAWSBackend",
    "FaultPlan",
    "AIMDLimiter",
    "CircuitBreaker",
    "CircuitOpenError",
    "DeadlineExceeded",
    "HealthConfig",
    "HealthTracker",
    "WorkerHeartbeats",
    "DiscoveryCache",
    "HostedZoneCache",
    "AcceleratorTopologyCache",
    "RecordSetCache",
    "LoadBalancerCoalescer",
]
