"""Abstract AWS service interfaces — the injection seam between the
drivers and either the real AWS APIs or the in-memory fake backend.

The operation set is exactly what the reference's drivers call on
aws-sdk-go-v2 (SURVEY.md §2 rows 12-15); list operations are
paginated with (max_results, next_token) pairs the way the reference
consumes SDK paginators (``pkg/cloudprovider/aws/global_accelerator.go:619-636``,
``route53.go:199-213,318-332``).
"""

from __future__ import annotations

import abc
from typing import Optional

from .types import (
    Accelerator,
    Change,
    EndpointConfiguration,
    EndpointDescription,
    EndpointGroup,
    HostedZone,
    Listener,
    LoadBalancer,
    PortRange,
    ResourceRecordSet,
    Tag,
)


class GlobalAcceleratorAPI(abc.ABC):
    # accelerators
    @abc.abstractmethod
    def list_accelerators(
        self, max_results: int, next_token: Optional[str]
    ) -> tuple[list[Accelerator], Optional[str]]: ...

    @abc.abstractmethod
    def describe_accelerator(self, arn: str) -> Accelerator: ...

    @abc.abstractmethod
    def create_accelerator(
        self, name: str, ip_address_type: str, enabled: bool, tags: list[Tag]
    ) -> Accelerator: ...

    @abc.abstractmethod
    def update_accelerator(
        self, arn: str, name: Optional[str] = None, enabled: Optional[bool] = None
    ) -> Accelerator: ...

    @abc.abstractmethod
    def delete_accelerator(self, arn: str) -> None: ...

    @abc.abstractmethod
    def list_tags_for_resource(self, arn: str) -> list[Tag]: ...

    @abc.abstractmethod
    def tag_resource(self, arn: str, tags: list[Tag]) -> None: ...

    # listeners
    @abc.abstractmethod
    def list_listeners(
        self, accelerator_arn: str, max_results: int, next_token: Optional[str]
    ) -> tuple[list[Listener], Optional[str]]: ...

    @abc.abstractmethod
    def create_listener(
        self,
        accelerator_arn: str,
        port_ranges: list[PortRange],
        protocol: str,
        client_affinity: str,
    ) -> Listener: ...

    @abc.abstractmethod
    def update_listener(
        self,
        listener_arn: str,
        port_ranges: list[PortRange],
        protocol: str,
        client_affinity: str,
    ) -> Listener: ...

    @abc.abstractmethod
    def delete_listener(self, arn: str) -> None: ...

    # endpoint groups
    @abc.abstractmethod
    def list_endpoint_groups(
        self, listener_arn: str, max_results: int, next_token: Optional[str]
    ) -> tuple[list[EndpointGroup], Optional[str]]: ...

    @abc.abstractmethod
    def describe_endpoint_group(self, arn: str) -> EndpointGroup: ...

    @abc.abstractmethod
    def create_endpoint_group(
        self,
        listener_arn: str,
        endpoint_group_region: str,
        endpoint_configurations: list[EndpointConfiguration],
    ) -> EndpointGroup: ...

    @abc.abstractmethod
    def update_endpoint_group(
        self, arn: str, endpoint_configurations: list[EndpointConfiguration]
    ) -> EndpointGroup: ...

    @abc.abstractmethod
    def delete_endpoint_group(self, arn: str) -> None: ...

    @abc.abstractmethod
    def add_endpoints(
        self, arn: str, endpoint_configurations: list[EndpointConfiguration]
    ) -> list[EndpointDescription]: ...

    @abc.abstractmethod
    def remove_endpoints(self, arn: str, endpoint_ids: list[str]) -> None: ...


class ELBv2API(abc.ABC):
    @abc.abstractmethod
    def describe_load_balancers(self, names: list[str]) -> list[LoadBalancer]: ...


class Route53API(abc.ABC):
    @abc.abstractmethod
    def list_hosted_zones(
        self, max_items: int, marker: Optional[str]
    ) -> tuple[list[HostedZone], Optional[str]]: ...

    @abc.abstractmethod
    def list_hosted_zones_by_name(
        self, dns_name: str, max_items: int
    ) -> list[HostedZone]: ...

    @abc.abstractmethod
    def list_resource_record_sets(
        self, hosted_zone_id: str, max_items: int, start_record_name: Optional[str]
    ) -> tuple[list[ResourceRecordSet], Optional[str]]: ...

    @abc.abstractmethod
    def change_resource_record_sets(
        self, hosted_zone_id: str, changes: list[Change]
    ) -> None: ...
