"""AWS Signature Version 4 request signing and credential resolution,
stdlib only.

The authentication layer the reference gets from aws-sdk-go-v2's
``config.LoadDefaultConfig`` (``pkg/cloudprovider/aws/aws.go:18-38``).
Credential resolution order: environment (``AWS_ACCESS_KEY_ID`` /
``AWS_SECRET_ACCESS_KEY`` / ``AWS_SESSION_TOKEN``) → IRSA web
identity (``AWS_ROLE_ARN`` + ``AWS_WEB_IDENTITY_TOKEN_FILE``, the
standard EKS service-account setup, exchanged through STS
``AssumeRoleWithWebIdentity`` — an unsigned call) → shared
credentials file (``~/.aws/credentials``, profile from
``AWS_PROFILE``).  ``CredentialProvider`` caches and transparently
re-resolves expiring session credentials, which a long-running
controller needs.
"""

from __future__ import annotations

import configparser
import datetime
import hashlib
import hmac
import os
import threading
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Callable, Optional


@dataclass
class Credentials:
    access_key_id: str
    secret_access_key: str
    session_token: Optional[str] = None
    expiration: Optional[float] = None  # unix epoch; None = static

STS_ENDPOINT = "https://sts.amazonaws.com/"
_EXPIRY_MARGIN = 300.0  # refresh 5 min before expiry


def xml_strip_ns(root: ET.Element) -> ET.Element:
    """Strip XML namespaces in place (AWS XML responses are easier to
    navigate without them); shared with the real service clients."""
    for element in root.iter():
        if "}" in element.tag:
            element.tag = element.tag.split("}", 1)[1]
    return root


def _assume_role_with_web_identity(
    role_arn: str, token_file: str, urlopen=urllib.request.urlopen
) -> Credentials:
    """IRSA: exchange the projected service-account token for session
    credentials.  AssumeRoleWithWebIdentity takes no signature."""
    with open(token_file) as fh:
        token = fh.read().strip()
    body = urllib.parse.urlencode(
        {
            "Action": "AssumeRoleWithWebIdentity",
            "Version": "2011-06-15",
            "RoleArn": role_arn,
            "RoleSessionName": os.environ.get(
                "AWS_ROLE_SESSION_NAME", "aws-global-accelerator-controller"
            ),
            "WebIdentityToken": token,
        }
    ).encode()
    request = urllib.request.Request(
        STS_ENDPOINT,
        data=body,
        headers={"Content-Type": "application/x-www-form-urlencoded"},
        method="POST",
    )
    with urlopen(request, timeout=30) as response:
        payload = response.read()
    root = xml_strip_ns(ET.fromstring(payload))
    creds = root.find(".//Credentials")
    if creds is None:
        raise RuntimeError("STS AssumeRoleWithWebIdentity returned no credentials")
    expiration_text = creds.findtext("Expiration", "")
    expiration = None
    if expiration_text:
        expiration = (
            datetime.datetime.strptime(expiration_text, "%Y-%m-%dT%H:%M:%SZ")
            .replace(tzinfo=datetime.timezone.utc)
            .timestamp()
        )
    return Credentials(
        access_key_id=creds.findtext("AccessKeyId", ""),
        secret_access_key=creds.findtext("SecretAccessKey", ""),
        session_token=creds.findtext("SessionToken"),
        expiration=expiration,
    )


def resolve_credentials(urlopen=urllib.request.urlopen) -> Credentials:
    access_key = os.environ.get("AWS_ACCESS_KEY_ID")
    secret_key = os.environ.get("AWS_SECRET_ACCESS_KEY")
    if access_key and secret_key:
        return Credentials(access_key, secret_key, os.environ.get("AWS_SESSION_TOKEN"))
    role_arn = os.environ.get("AWS_ROLE_ARN")
    token_file = os.environ.get("AWS_WEB_IDENTITY_TOKEN_FILE")
    if role_arn and token_file:
        return _assume_role_with_web_identity(role_arn, token_file, urlopen)
    path = os.environ.get(
        "AWS_SHARED_CREDENTIALS_FILE", os.path.expanduser("~/.aws/credentials")
    )
    profile = os.environ.get("AWS_PROFILE", "default")
    parser = configparser.ConfigParser()
    if parser.read(path) and parser.has_section(profile):
        section = parser[profile]
        if "aws_access_key_id" in section and "aws_secret_access_key" in section:
            return Credentials(
                section["aws_access_key_id"],
                section["aws_secret_access_key"],
                section.get("aws_session_token"),
            )
    raise RuntimeError(
        "no AWS credentials found (env AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY, "
        f"IRSA AWS_ROLE_ARN/AWS_WEB_IDENTITY_TOKEN_FILE, or {path} profile {profile!r})"
    )


class CredentialProvider:
    """Caches credentials and re-resolves them before expiry; safe to
    share across service clients and threads.

    Resolved credentials WITHOUT an expiration (env vars, shared
    credentials file) are still re-resolved every
    ``STATIC_REFRESH_SECONDS`` so in-place key rotation is picked up —
    the provider is shared process-wide, and without a TTL a rotated
    credentials file would be ignored until restart (the reference
    re-resolves per reconcile via its ``NewAWS`` calls).  Explicit
    static ``Credentials`` passed to the constructor are honored as-is:
    non-expiring ones never re-resolve; expiring ones (e.g. session
    credentials) are served until the expiry margin, after which the
    resolver is *tried* for fresher credentials — but a failing
    resolver falls back to the static while it remains actually valid
    (the margin is an optimization, not a validity boundary).
    """

    STATIC_REFRESH_SECONDS = 300.0

    def __init__(
        self,
        static: Optional[Credentials] = None,
        resolver: Callable[[], Credentials] = resolve_credentials,
        clock: Callable[[], float] = None,
    ):
        import time as _time

        self._static = static
        self._resolver = resolver
        self._clock = clock or _time.time
        self._cached: Optional[Credentials] = static
        self._lock = threading.Lock()
        self._resolve_cooldown_until = 0.0
        self._resolved_at = 0.0

    def get(self) -> Credentials:
        with self._lock:
            cached = self._cached
            if cached is self._static and cached is not None:
                # explicit static creds: never re-resolve while valid —
                # non-expiring ones forever, expiring ones until the
                # expiry margin (only then fall through to the resolver)
                if (
                    cached.expiration is None
                    or cached.expiration - self._clock() > _EXPIRY_MARGIN
                ):
                    return cached
            elif cached is not None:
                fresh_enough = (
                    self._clock() - self._resolved_at < self.STATIC_REFRESH_SECONDS
                    if cached.expiration is None
                    else cached.expiration - self._clock() > _EXPIRY_MARGIN
                )
                if fresh_enough:
                    return cached
            if self._static is not None and self._static.expiration is None:
                return self._static
            def cached_still_valid() -> bool:
                return cached is not None and (
                    cached.expiration is None or cached.expiration > self._clock()
                )

            # after a resolver failure, don't retry on every call —
            # each attempt can block tens of seconds under this lock;
            # serve the still-valid cache during the cooldown
            if self._clock() < self._resolve_cooldown_until and cached_still_valid():
                return cached
            try:
                self._cached = self._resolver()
                self._resolved_at = self._clock()
                self._resolve_cooldown_until = 0.0
            except Exception:
                # transient resolver failure (e.g. STS unreachable):
                # keep serving cached credentials while they are still
                # actually valid — refresh margin is an optimization,
                # not a validity boundary
                self._resolve_cooldown_until = self._clock() + 30.0
                if cached_still_valid():
                    return cached
                raise
            return self._cached


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, message: str) -> bytes:
    return hmac.new(key, message.encode(), hashlib.sha256).digest()


def derive_signing_key(
    secret_access_key: str, date_stamp: str, region: str, service: str
) -> bytes:
    """The SigV4 key-derivation chain
    ``HMAC(HMAC(HMAC(HMAC("AWS4"+secret, date), region), service), "aws4_request")``.
    Validated byte-for-byte against AWS's published derivation examples
    (``tests/test_sigv4_aws_vectors.py``)."""
    return _hmac(
        _hmac(
            _hmac(_hmac(f"AWS4{secret_access_key}".encode(), date_stamp), region),
            service,
        ),
        "aws4_request",
    )


def _canonical_query(query: str) -> str:
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    encoded = [
        (urllib.parse.quote(k, safe="-_.~"), urllib.parse.quote(v, safe="-_.~"))
        for k, v in pairs
    ]
    return "&".join(f"{k}={v}" for k, v in sorted(encoded))


def sign_request(
    method: str,
    url: str,
    headers: dict[str, str],
    body: bytes,
    service: str,
    region: str,
    credentials: Credentials,
    now: Optional[datetime.datetime] = None,
) -> dict[str, str]:
    """Return ``headers`` plus the SigV4 ``Authorization``,
    ``X-Amz-Date`` (and session-token) headers for the request."""
    parsed = urllib.parse.urlsplit(url)
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date_stamp = now.strftime("%Y%m%d")

    signed = dict(headers)
    signed["Host"] = parsed.netloc
    signed["X-Amz-Date"] = amz_date
    if credentials.session_token:
        signed["X-Amz-Security-Token"] = credentials.session_token

    payload_hash = _sha256_hex(body or b"")
    lower = {k.lower(): v.strip() for k, v in signed.items()}
    signed_header_names = ";".join(sorted(lower))
    canonical_headers = "".join(f"{k}:{lower[k]}\n" for k in sorted(lower))
    canonical_request = "\n".join(
        [
            method,
            urllib.parse.quote(parsed.path or "/", safe="/-_.~%"),
            _canonical_query(parsed.query),
            canonical_headers,
            signed_header_names,
            payload_hash,
        ]
    )
    scope = f"{date_stamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            _sha256_hex(canonical_request.encode()),
        ]
    )
    key = derive_signing_key(
        credentials.secret_access_key, date_stamp, region, service
    )
    signature = hmac.new(key, string_to_sign.encode(), hashlib.sha256).hexdigest()
    signed["Authorization"] = (
        "AWS4-HMAC-SHA256 "
        f"Credential={credentials.access_key_id}/{scope}, "
        f"SignedHeaders={signed_header_names}, "
        f"Signature={signature}"
    )
    return signed
