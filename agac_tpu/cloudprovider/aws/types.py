"""AWS resource types used by the drivers — the analog of the
aws-sdk-go-v2 ``types`` packages the reference imports (gatypes,
elbv2types, route53types).

Only the fields the framework reads or writes are modeled.  Enum-ish
string constants follow the AWS wire values.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# Protocols (gatypes.Protocol)
PROTOCOL_TCP = "TCP"
PROTOCOL_UDP = "UDP"

# Accelerator status (gatypes.AcceleratorStatus)
ACCELERATOR_STATUS_DEPLOYED = "DEPLOYED"
ACCELERATOR_STATUS_IN_PROGRESS = "IN_PROGRESS"

# Load balancer states (elbv2types.LoadBalancerStateEnum)
LB_STATE_ACTIVE = "active"
LB_STATE_PROVISIONING = "provisioning"
LB_STATE_FAILED = "failed"

# Client affinity (gatypes.ClientAffinity)
CLIENT_AFFINITY_NONE = "NONE"

# IP address type
IP_ADDRESS_TYPE_IPV4 = "IPV4"

# Route53 record types and change actions
RR_TYPE_A = "A"
RR_TYPE_TXT = "TXT"
RR_TYPE_CNAME = "CNAME"
CHANGE_ACTION_CREATE = "CREATE"
CHANGE_ACTION_DELETE = "DELETE"
CHANGE_ACTION_UPSERT = "UPSERT"

# The fixed hosted zone of every Global Accelerator alias target
# (reference ``pkg/cloudprovider/aws/route53.go:250-257``).
GLOBAL_ACCELERATOR_HOSTED_ZONE_ID = "Z2BJ6XQ5FK7U4H"


# Tag and Accelerator are frozen: they flow through the shared
# DiscoveryCache snapshot (cache.py), which hands the same objects to
# every worker without defensive copies.
@dataclass(frozen=True)
class Tag:
    key: str
    value: str


@dataclass(frozen=True)
class Accelerator:
    accelerator_arn: str = ""
    name: str = ""
    dns_name: str = ""
    enabled: bool = True
    status: str = ACCELERATOR_STATUS_DEPLOYED
    ip_address_type: str = IP_ADDRESS_TYPE_IPV4


@dataclass
class PortRange:
    from_port: int
    to_port: int


@dataclass
class Listener:
    listener_arn: str = ""
    protocol: str = PROTOCOL_TCP
    port_ranges: list[PortRange] = field(default_factory=list)
    client_affinity: str = CLIENT_AFFINITY_NONE


@dataclass
class EndpointDescription:
    endpoint_id: str = ""
    weight: Optional[int] = None
    client_ip_preservation_enabled: bool = False


@dataclass
class EndpointConfiguration:
    endpoint_id: str = ""
    weight: Optional[int] = None
    client_ip_preservation_enabled: bool = False


@dataclass
class EndpointGroup:
    endpoint_group_arn: str = ""
    endpoint_group_region: str = ""
    endpoint_descriptions: list[EndpointDescription] = field(default_factory=list)


@dataclass
class LoadBalancer:
    load_balancer_arn: str = ""
    load_balancer_name: str = ""
    dns_name: str = ""
    state_code: str = LB_STATE_ACTIVE
    type: str = "network"  # "network" | "application"
    scheme: str = "internet-facing"


@dataclass
class HostedZone:
    id: str = ""
    name: str = ""  # always dot-terminated, e.g. "example.com."


@dataclass
class ResourceRecord:
    value: str = ""


@dataclass
class AliasTarget:
    dns_name: str = ""
    evaluate_target_health: bool = True
    hosted_zone_id: str = ""


@dataclass
class ResourceRecordSet:
    name: str = ""  # dot-terminated on the wire
    type: str = RR_TYPE_A
    ttl: Optional[int] = None
    resource_records: list[ResourceRecord] = field(default_factory=list)
    alias_target: Optional[AliasTarget] = None


@dataclass
class Change:
    action: str
    record_set: ResourceRecordSet
