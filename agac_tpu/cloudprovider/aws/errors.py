"""AWS API error model.

The reference distinguishes AWS failures by smithy error code
(``awsErr.ErrorCode() == ErrEndpointGroupNotFoundException``,
reference ``pkg/controller/endpointgroupbinding/reconcile.go:48-64``)
and by typed not-found exceptions
(``gatypes.ListenerNotFoundException`` handling in
``pkg/cloudprovider/aws/global_accelerator.go:296-310``).  Here every
API error carries a ``code``; the two not-found codes the drivers
branch on get their own subclasses.
"""

from __future__ import annotations


ERR_LISTENER_NOT_FOUND = "ListenerNotFoundException"
ERR_ENDPOINT_GROUP_NOT_FOUND = "EndpointGroupNotFoundException"
ERR_ACCELERATOR_NOT_FOUND = "AcceleratorNotFoundException"
ERR_ACCELERATOR_NOT_DISABLED = "AcceleratorNotDisabledException"
ERR_ASSOCIATED_LISTENER_FOUND = "AssociatedListenerFoundException"
ERR_ASSOCIATED_ENDPOINT_GROUP_FOUND = "AssociatedEndpointGroupFoundException"
ERR_LOAD_BALANCER_NOT_FOUND = "LoadBalancerNotFound"
ERR_NO_SUCH_HOSTED_ZONE = "NoSuchHostedZone"
ERR_INVALID_CHANGE_BATCH = "InvalidChangeBatch"
ERR_INVALID_ARGUMENT = "InvalidArgumentException"
ERR_INVALID_PORT_RANGE = "InvalidPortRangeException"
ERR_LIMIT_EXCEEDED = "LimitExceededException"


class AWSAPIError(Exception):
    """An AWS API failure with a service error code."""

    def __init__(self, code: str, message: str = ""):
        self.code = code
        super().__init__(f"{code}: {message}" if message else code)


class ListenerNotFoundException(AWSAPIError):
    def __init__(self, message: str = ""):
        super().__init__(ERR_LISTENER_NOT_FOUND, message)


class EndpointGroupNotFoundException(AWSAPIError):
    def __init__(self, message: str = ""):
        super().__init__(ERR_ENDPOINT_GROUP_NOT_FOUND, message)


def aws_error_code(err: BaseException) -> str:
    """The smithy ``ErrorCode()`` analog: empty for non-AWS errors."""
    return err.code if isinstance(err, AWSAPIError) else ""
