"""In-memory AWS backend implementing all three service interfaces.

The test double the reference never had (SURVEY.md §4: "no fake AWS
client exists; methods on *AWS* are never unit-tested").  Behaviors
reproduced because the drivers depend on them:

- **Accelerator status settling**: create/update puts an accelerator
  into IN_PROGRESS; it becomes DEPLOYED after ``settle_describes``
  describe/list calls — so the disable → poll-until-DEPLOYED → delete
  orchestration (reference ``global_accelerator.go:724-765``) is
  actually exercised by tests.
- **Deletion ordering constraints**: an enabled accelerator or one
  with listeners cannot be deleted; a listener with endpoint groups
  cannot be deleted — making the endpoint-group → listener →
  accelerator teardown order (``global_accelerator.go:252-270``)
  observable.
- **Route53 change batches**: CREATE fails on an existing name+type,
  DELETE on a missing one, UPSERT always applies; record names are
  stored dot-terminated with ``*`` escaped as ``\\052`` the way
  Route53 does (``route53.go:369-371``).
- **Pagination** on every list operation, honoring max_results.
- **Documented AWS invariants** (VERDICT r3 next#5 — a fake that
  accepts inputs real AWS rejects certifies nothing): accelerator
  name charset/length per the CreateAccelerator API reference, port
  ranges 1-65535, the default service quotas (accelerators per
  account, listeners per accelerator, port ranges per listener,
  endpoint groups per listener, endpoints per endpoint group, tags
  per resource), and Route53 change-batch limits — each rejected
  with the service's documented error code
  (InvalidArgumentException / InvalidPortRangeException /
  LimitExceededException / InvalidChangeBatch).  Quotas are
  constructor-tunable the way real accounts raise them.
"""

from __future__ import annotations

import inspect
import json
import os
import random
import re
import threading
import uuid
from collections import deque
from dataclasses import replace
from typing import Callable, Optional

from ... import clockseam
from ...analysis import racecheck
from .api import ELBv2API, GlobalAcceleratorAPI, Route53API
from .errors import (
    AWSAPIError,
    ERR_ACCELERATOR_NOT_DISABLED,
    ERR_ACCELERATOR_NOT_FOUND,
    ERR_ASSOCIATED_ENDPOINT_GROUP_FOUND,
    ERR_ASSOCIATED_LISTENER_FOUND,
    ERR_INVALID_ARGUMENT,
    ERR_INVALID_CHANGE_BATCH,
    ERR_INVALID_PORT_RANGE,
    ERR_LIMIT_EXCEEDED,
    ERR_LOAD_BALANCER_NOT_FOUND,
    ERR_NO_SUCH_HOSTED_ZONE,
    EndpointGroupNotFoundException,
    ListenerNotFoundException,
)
from .types import (
    ACCELERATOR_STATUS_DEPLOYED,
    ACCELERATOR_STATUS_IN_PROGRESS,
    CHANGE_ACTION_CREATE,
    CHANGE_ACTION_DELETE,
    CHANGE_ACTION_UPSERT,
    Accelerator,
    Change,
    EndpointDescription,
    EndpointGroup,
    HostedZone,
    Listener,
    LoadBalancer,
    PortRange,
    ResourceRecordSet,
    Tag,
)

_ACCOUNT = "123456789012"

# CreateAccelerator Name constraint (GA API reference): up to 64
# characters, only alphanumerics/periods/hyphens, must not begin or
# end with a hyphen or period
_ACCELERATOR_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9.-]{0,62}[A-Za-z0-9]$|^[A-Za-z0-9]$")

_VALID_PROTOCOLS = frozenset({"TCP", "UDP"})
_VALID_CLIENT_AFFINITY = frozenset({"NONE", "SOURCE_IP"})
_VALID_IP_ADDRESS_TYPES = frozenset({"IPV4", "DUAL_STACK"})
# Route53 record types the 2013-04-01 API accepts
_VALID_RR_TYPES = frozenset(
    {"A", "AAAA", "CAA", "CNAME", "DS", "MX", "NAPTR", "NS", "PTR",
     "SOA", "SPF", "SRV", "TXT"}
)
_MAX_TTL = 2_147_483_647  # Route53 TTL is a 32-bit signed int


def _validate_accelerator_name(name: str) -> None:
    if not _ACCELERATOR_NAME_RE.match(name or ""):
        raise AWSAPIError(
            ERR_INVALID_ARGUMENT,
            f"Accelerator name {name!r} must be 1-64 alphanumeric, period or "
            "hyphen characters and must not begin or end with a hyphen or period",
        )


def _validate_port_ranges(port_ranges, max_ranges: int) -> None:
    if not port_ranges:
        raise AWSAPIError(ERR_INVALID_ARGUMENT, "at least one port range is required")
    if len(port_ranges) > max_ranges:
        raise AWSAPIError(
            ERR_LIMIT_EXCEEDED,
            f"{len(port_ranges)} port ranges exceeds the {max_ranges} per-listener quota",
        )
    for port_range in port_ranges:
        from_port = getattr(port_range, "from_port", None)
        to_port = getattr(port_range, "to_port", None)
        if from_port is None or to_port is None:
            raise AWSAPIError(
                ERR_INVALID_ARGUMENT,
                f"port range {port_range!r} must carry FromPort and ToPort",
            )
        if not (1 <= from_port <= 65535 and 1 <= to_port <= 65535):
            raise AWSAPIError(
                ERR_INVALID_PORT_RANGE,
                f"port range {from_port}-{to_port} outside 1-65535",
            )
        if from_port > to_port:
            raise AWSAPIError(
                ERR_INVALID_PORT_RANGE,
                f"FromPort {from_port} greater than ToPort {to_port}",
            )


def _validate_listener_args(port_ranges, protocol, client_affinity, max_ranges) -> None:
    _validate_port_ranges(port_ranges, max_ranges)
    if protocol not in _VALID_PROTOCOLS:
        raise AWSAPIError(ERR_INVALID_ARGUMENT, f"invalid Protocol {protocol!r}")
    if client_affinity not in _VALID_CLIENT_AFFINITY:
        raise AWSAPIError(
            ERR_INVALID_ARGUMENT, f"invalid ClientAffinity {client_affinity!r}"
        )


def _paginate(items: list, max_results: int, next_token: Optional[str]):
    start = int(next_token) if next_token else 0
    page = items[start : start + max_results]
    token = str(start + max_results) if start + max_results < len(items) else None
    return page, token


# every method the drivers can reach — exactly the three API
# interfaces, so test helpers (add_load_balancer, records_in_zone, ...)
# stay fault-free under an installed FaultPlan
API_OPS = frozenset(
    name
    for cls in (GlobalAcceleratorAPI, ELBv2API, Route53API)
    for name, member in vars(cls).items()
    if inspect.isfunction(member) and not name.startswith("_")
)

_MUTATING_PREFIXES = (
    "create_", "update_", "delete_", "add_", "remove_", "tag_", "change_",
)


class SimulatedCrash(BaseException):
    """The process/worker died at this exact API-call boundary.

    Raised by ``FaultPlan.crash`` schedules.  A ``BaseException`` on
    purpose: the retry/requeue machinery catches ``Exception`` — a
    crash must never be absorbed into a backoff retry, because the
    whole point is that NOTHING after the death point runs.  The drill
    harness maps it to real death: in-process drills let it kill the
    worker thread; the subprocess drills (``AGAC_FAKE_CRASH``,
    factory.py) map it to ``os._exit`` — the ``kill -9`` analog."""

    def __init__(self, op: str, when: str):
        self.op = op
        self.when = when
        super().__init__(f"simulated crash {when} {op}")


class _SerialCounter:
    """``itertools.count`` with a readable current value, so durable
    backends can persist it and resume without ID collisions."""

    __slots__ = ("value",)

    def __init__(self, start: int = 1):
        self.value = start

    def __next__(self) -> int:
        value = self.value
        self.value += 1
        return value

    def __iter__(self) -> "_SerialCounter":
        return self


class _Fault:
    """One scripted fault: ``kind`` is fail / commit-then-fail / hang;
    ``remaining`` counts down to exhaustion."""

    __slots__ = ("kind", "code", "remaining")

    def __init__(self, kind: str, code: str, remaining: int):
        self.kind = kind
        self.code = code
        self.remaining = remaining


class FaultPlan:
    """First-class fault injection for ``FakeAWSBackend`` — the
    promotion of the chaos tier's ad-hoc ``__getattribute__`` subclass
    hooks into one scripted API (ISSUE 3 satellite).  Three layers,
    consulted in order for every API call from a non-exempt thread:

    1. **scripted schedules** per op (FIFO): ``throttle(op, times)``,
       ``fail(op, times, code)``, ``fail_after_commit(op, times)`` (the
       ambiguous-timeout shape: the change commits, the caller sees an
       error), ``hang_until_deadline(op)`` (the call blocks until the
       calling worker's reconcile deadline expires, then surfaces a
       timeout — the wedge shape the deadline machinery exists to cut),
       and ``crash(op, when="before"|"after-commit")`` (the caller DIES
       at the op boundary — a ``SimulatedCrash`` the kill-recovery
       drills map to worker/process death; ``after-commit`` commits the
       mutation first, the torn-write shape of a kill -9 mid-chain);
    2. **outages**: ``outage(*ops)`` fails every call until
       ``restore()`` — the sustained-brownout shape the circuit
       breaker reacts to;
    3. **chaos**: ``chaos(seed, fault_budget, p, ambiguous)`` — the
       seeded randomized mode the chaos e2e tier runs (finite budget,
       so every run terminates).

    The thread that builds the plan is exempt by default so test
    assertion predicates read clean truth through the same API.
    ``faults_served`` / ``served_by_op`` count injected faults —
    during an outage they equal the calls attempted against the dead
    service, which is what the brownout call-budget assertions bound.
    """

    def __init__(self, exempt_creator: bool = True):
        self._lock = threading.Lock()
        self._scripts: dict[str, deque[_Fault]] = {}
        self._outages: dict[str, str] = {}  # op -> error code
        self._rng: Optional[random.Random] = None
        self._p = 0.0
        self._ambiguous = 0.0
        self.fault_budget = 0
        self.faults_served = 0
        self.served_by_op: dict[str, int] = {}
        self._exempt: set = {threading.current_thread()} if exempt_creator else set()
        # safety valve for hang_until_deadline when no deadline is
        # armed: never block a call longer than this
        self.max_hang = 30.0
        # how a SimulatedCrash becomes death: None raises it (kills
        # the worker thread in in-process drills); the subprocess
        # drills set os._exit here — the kill -9 analog
        self.on_crash: Optional[Callable[[SimulatedCrash], None]] = None

    # -- scripted schedules -------------------------------------------------
    def _script(self, op: str, kind: str, code: str, times: int) -> "FaultPlan":
        if op not in API_OPS:
            raise ValueError(f"unknown API op {op!r}")
        with self._lock:
            self._scripts.setdefault(op, deque()).append(_Fault(kind, code, times))
        return self

    def throttle(self, op: str, times: int = 1, code: str = "ThrottlingException") -> "FaultPlan":
        return self._script(op, "fail", code, times)

    def fail(self, op: str, times: int = 1, code: str = "InternalFailure") -> "FaultPlan":
        return self._script(op, "fail", code, times)

    def fail_after_commit(self, op: str, times: int = 1, code: str = "RequestTimeout") -> "FaultPlan":
        return self._script(op, "commit-then-fail", code, times)

    def hang_until_deadline(self, op: str, times: int = 1) -> "FaultPlan":
        return self._script(op, "hang", "RequestTimeout", times)

    def crash(self, op: str, when: str = "before", times: int = 1) -> "FaultPlan":
        """Kill the caller at this op boundary: ``when="before"`` dies
        without committing (the op never ran), ``when="after-commit"``
        commits the change first (a durable backend has already flushed
        it) and THEN dies — the torn-write shape a ``kill -9``
        mid-mutation leaves behind.  The death is a ``SimulatedCrash``
        (a BaseException, so no retry path can absorb it); set
        ``on_crash`` to map it to real process death (the subprocess
        drills use ``os._exit``)."""
        if when not in ("before", "after-commit"):
            raise ValueError(f"crash when= must be 'before' or 'after-commit', got {when!r}")
        return self._script(op, f"crash-{when}", "SimulatedCrash", times)

    # -- sustained outage ---------------------------------------------------
    def outage(self, *ops: str, code: str = "ServiceUnavailable") -> "FaultPlan":
        unknown = [op for op in ops if op not in API_OPS]
        if unknown:
            raise ValueError(f"unknown API ops {unknown!r}")
        with self._lock:
            for op in ops:
                self._outages[op] = code
        return self

    def restore(self, *ops: str) -> "FaultPlan":
        """End an outage for the given ops (none = all)."""
        with self._lock:
            if ops:
                for op in ops:
                    self._outages.pop(op, None)
            else:
                self._outages.clear()
        return self

    # -- randomized chaos ---------------------------------------------------
    def chaos(
        self, seed: int, fault_budget: int, p: float = 0.25, ambiguous: float = 0.4
    ) -> "FaultPlan":
        """Any API call may fail with a retryable error at probability
        ``p`` while the budget lasts; mutating ops additionally fail
        *after* committing with conditional probability ``ambiguous``."""
        with self._lock:
            self._rng = random.Random(seed)
            self._p = p
            self._ambiguous = ambiguous
            self.fault_budget = fault_budget
        return self

    def refill(self, budget: int) -> None:
        with self._lock:
            self.fault_budget = budget

    # -- bookkeeping --------------------------------------------------------
    def exempt(self, thread: Optional[threading.Thread] = None) -> "FaultPlan":
        with self._lock:
            self._exempt.add(thread or threading.current_thread())
        return self

    def faults_for(self, *ops: str) -> int:
        with self._lock:
            return sum(self.served_by_op.get(op, 0) for op in ops)

    def _serve(self, op: str) -> None:
        self.faults_served += 1
        self.served_by_op[op] = self.served_by_op.get(op, 0) + 1

    # -- the engine ---------------------------------------------------------
    def _decide(self, op: str) -> Optional[tuple[str, str]]:
        """(kind, code) to inject for this call, or None."""
        if threading.current_thread() in self._exempt:
            return None
        with self._lock:
            schedule = self._scripts.get(op)
            while schedule:
                fault = schedule[0]
                if fault.remaining <= 0:
                    schedule.popleft()
                    continue
                fault.remaining -= 1
                self._serve(op)
                return fault.kind, fault.code
            code = self._outages.get(op)
            if code is not None:
                self._serve(op)
                return "fail", code
            if self._rng is not None and self.fault_budget > 0:
                if self._rng.random() < self._p:
                    self.fault_budget -= 1
                    self._serve(op)
                    if op.startswith(_MUTATING_PREFIXES) and self._rng.random() < self._ambiguous:
                        return "commit-then-fail", "RequestTimeout"
                    return "fail", "ThrottlingException"
        return None

    def _hang(self, op: str) -> None:
        """Block like a wedged backend call, bounded by the calling
        worker's reconcile deadline (health plane) or ``max_hang``,
        then surface the timeout shape a real stuck call produces."""
        from .health import deadline_remaining

        remaining = deadline_remaining()
        wait = self.max_hang if remaining is None else min(remaining + 0.05, self.max_hang)
        if wait > 0:
            # through the clock seam (ISSUE 7): a hang fault burns
            # VIRTUAL time under the sim runtime instead of stalling
            # the cooperative scheduler on a real Event wait
            clockseam.sleep(wait)
        raise AWSAPIError("RequestTimeout", f"fault plan: {op} hung past deadline")

    def _die(self, crash: SimulatedCrash) -> None:
        hook = self.on_crash
        if hook is not None:
            hook(crash)
        raise crash

    def wrap(self, op: str, call):
        def faulted(*args, **kwargs):
            fate = self._decide(op)
            if fate is None:
                return call(*args, **kwargs)
            kind, code = fate
            if kind == "hang":
                self._hang(op)
            if kind == "fail":
                raise AWSAPIError(code, f"fault plan: {op}")
            if kind == "crash-before":
                self._die(SimulatedCrash(op, "before"))
            result = call(*args, **kwargs)  # commit-then-fail / crash-after-commit
            del result
            if kind == "crash-after-commit":
                self._die(SimulatedCrash(op, "after-commit"))
            raise AWSAPIError(code, f"fault plan (after commit): {op}")

        return faulted


class _AcceleratorState:
    def __init__(self, accelerator: Accelerator, tags: list[Tag], settle: int):
        self.accelerator = accelerator
        self.tags = tags
        self.listeners: dict[str, Listener] = {}
        self.pending_describes = settle  # describes until DEPLOYED


class FakeAWSBackend(GlobalAcceleratorAPI, ELBv2API, Route53API):
    """One object implements all three services; hand it to the driver
    as ga_api, elb_api and route53_api."""

    def __init__(
        self,
        settle_describes: int = 0,
        # per-call wire latency in seconds (0 = instant): the
        # multi-process sharding bench (ISSUE 8) shapes real
        # subprocesses with it so throughput is bound by each
        # process's worker pool x latency — the capacity model
        # sharding divides — instead of by raw fake-op speed.
        # Sleeps go through the clock seam (virtual under the sim).
        latency: float = 0.0,
        # the documented default service quotas; raise them the way a
        # real account requests quota increases (the bench's 1000-
        # accelerator fleet does)
        quota_accelerators: int = 20,
        quota_listeners_per_accelerator: int = 10,
        quota_port_ranges_per_listener: int = 10,
        quota_endpoint_groups_per_listener: int = 10,
        quota_endpoints_per_group: int = 10,
        quota_tags_per_resource: int = 50,
        quota_changes_per_batch: int = 1000,
    ):
        # racecheck seam: with the lock-order watchdog enabled (tests)
        # the backend lock participates in cycle detection and the
        # shared service tables below become guarded dicts that record
        # any mutation performed without this lock held — the fake is
        # hit concurrently by every controller worker plus test-side
        # tamper threads, exactly the surface Go's -race covered for
        # the reference.
        lock = racecheck.make_rlock("fake-backend")
        self._lock = lock
        self.settle_describes = settle_describes
        self.latency = max(0.0, latency)
        self.quota_accelerators = quota_accelerators
        self.quota_listeners_per_accelerator = quota_listeners_per_accelerator
        self.quota_port_ranges_per_listener = quota_port_ranges_per_listener
        self.quota_endpoint_groups_per_listener = quota_endpoint_groups_per_listener
        self.quota_endpoints_per_group = quota_endpoints_per_group
        self.quota_tags_per_resource = quota_tags_per_resource
        self.quota_changes_per_batch = quota_changes_per_batch
        # reads of self.* here would recurse into test subclasses'
        # __getattribute__ fault hooks before their own __init__ ran —
        # close over the local ``lock`` instead
        guard = lambda name: racecheck.guard_dict({}, lock, f"fake-backend.{name}")
        self._accelerators: dict[str, _AcceleratorState] = guard("_accelerators")
        # listener arn -> (accelerator arn); endpoint groups keyed by arn
        self._listener_parent: dict[str, str] = guard("_listener_parent")
        self._endpoint_groups: dict[str, EndpointGroup] = guard("_endpoint_groups")
        self._eg_parent: dict[str, str] = guard("_eg_parent")  # eg arn -> listener arn
        self._load_balancers: dict[str, LoadBalancer] = guard("_load_balancers")  # name -> LB
        self._zones: dict[str, HostedZone] = guard("_zones")  # id -> zone
        self._records: dict[str, dict[tuple[str, str], ResourceRecordSet]] = guard("_records")
        self._counter = _SerialCounter()
        # derived indexes (plain dicts, always mutated under the lock;
        # insertion-ordered so iteration stays deterministic for the
        # sim replay contract): arns still settling toward DEPLOYED —
        # so a ListAccelerators page settles O(pending), not O(fleet) —
        # and listener arn -> its endpoint-group arns, so per-chain
        # listing is O(chain), not a scan of every group in the fleet
        self._settling: dict[str, None] = {}
        self._egs_by_listener: dict[str, dict[str, None]] = {}
        # memoized ListAccelerators item list, dropped whenever any
        # accelerator payload changes — a paginated drain at N=10k is
        # ~100 page calls, and rebuilding the O(N) list per page made
        # every drain O(N^2/page) in the 7-day sim soak
        self._accel_list_cache: "Optional[list[Accelerator]]" = None
        # call log for assertions ("CreateAccelerator", arn), ...
        self.calls: list[tuple] = []
        # first-class fault injection (see FaultPlan); None = clean
        self.fault_plan: Optional[FaultPlan] = None
        # durability seam (see FileBackedFakeAWSBackend): wraps every
        # API op INSIDE the fault plan, so a commit is flushed to disk
        # before a commit-then-fail error or an after-commit crash
        # surfaces — exactly the ordering a real backend gives a dying
        # client
        self._persist_hook: Optional[Callable] = None

    def install_fault_plan(self, plan: Optional[FaultPlan] = None) -> FaultPlan:
        """Attach a FaultPlan (building one if not given) and return
        it; every subsequent API call from a non-exempt thread consults
        it.  Replaces the old pattern of ad-hoc ``__getattribute__``
        subclasses in the chaos/resilience tiers."""
        self.fault_plan = plan if plan is not None else FaultPlan()
        return self.fault_plan

    def __getattribute__(self, name):
        attr = super().__getattribute__(name)
        if name in API_OPS:
            # __dict__ lookup, not self.fault_plan: attribute access
            # here would recurse, and during __init__ the slots may not
            # exist yet
            state = super().__getattribute__("__dict__")
            persist = state.get("_persist_hook")
            if persist is not None:
                attr = persist(name, attr)
            plan = state.get("fault_plan")
            if plan is not None:
                attr = plan.wrap(name, attr)
            latency = state.get("latency", 0.0)
            if latency:
                inner = attr

                def paced(*args, __inner=inner, **kwargs):
                    clockseam.sleep(latency)
                    return __inner(*args, **kwargs)

                attr = paced
        return attr

    # ------------------------------------------------------------------
    # test helpers
    # ------------------------------------------------------------------
    def add_load_balancer(
        self,
        name: str,
        region: str,
        dns_name: str,
        state_code: str = "active",
        lb_type: str = "network",
        scheme: str = "internet-facing",
    ) -> LoadBalancer:
        with self._lock:
            # idempotent on (name, dns): a restarted process re-seeding
            # the same env-declared LB must not mint a new arn — the
            # durable state's endpoint groups reference the old one
            existing = self._load_balancers.get(name)
            if existing is not None and existing.dns_name == dns_name:
                return existing
            arn = (
                f"arn:aws:elasticloadbalancing:{region}:{_ACCOUNT}:"
                f"loadbalancer/{'net' if lb_type == 'network' else 'app'}/{name}/{next(self._counter):016x}"
            )
            lb = LoadBalancer(
                load_balancer_arn=arn,
                load_balancer_name=name,
                dns_name=dns_name,
                state_code=state_code,
                type=lb_type,
                scheme=scheme,
            )
            self._load_balancers[name] = lb
        return lb

    def set_load_balancer_state(self, name: str, state_code: str) -> None:
        with self._lock:
            self._load_balancers[name].state_code = state_code

    def add_hosted_zone(self, name: str) -> HostedZone:
        if not name.endswith("."):
            name += "."
        with self._lock:
            # idempotent by name (same rationale as add_load_balancer:
            # restart re-seeding must not duplicate the zone)
            for zone in self._zones.values():
                if zone.name == name:
                    return zone
            zone = HostedZone(id=f"/hostedzone/Z{next(self._counter):08X}", name=name)
            self._zones[zone.id] = zone
            self._records.setdefault(zone.id, {})
        return zone

    def records_in_zone(self, zone_id: str) -> list[ResourceRecordSet]:
        with self._lock:
            return list(self._records.get(zone_id, {}).values())

    def all_accelerator_arns(self) -> list[str]:
        with self._lock:
            return list(self._accelerators.keys())

    def chain_counts(self) -> tuple[int, int, int]:
        """(accelerators, listeners, endpoint groups) — the complete-
        chain convergence odometer.  With staged chains (ISSUE 6) an
        accelerator exists passes before its listener/endpoint group
        do, so counting accelerators alone would declare convergence
        early."""
        with self._lock:
            return (
                len(self._accelerators),
                len(self._listener_parent),
                len(self._endpoint_groups),
            )

    def accelerator_owners(self) -> dict[str, Optional[str]]:
        """arn -> owner-tag value — a test/oracle helper read that is
        neither faulted nor call-counted (sim oracles snapshot GC
        ground truth through this without perturbing fault budgets or
        quiescence windows)."""
        with self._lock:
            return {
                arn: next(
                    (
                        t.value
                        for t in state.tags
                        # keep in sync with driver.OWNER_TAG_KEY (the
                        # fake never imports the driver)
                        if t.key == "aws-global-accelerator-owner"
                    ),
                    None,
                )
                for arn, state in self._accelerators.items()
            }

    def all_hosted_zone_ids(self) -> list[str]:
        """Every hosted-zone id (unfaulted helper; see above)."""
        with self._lock:
            return sorted(self._zones.keys())

    # ------------------------------------------------------------------
    # GlobalAcceleratorAPI
    # ------------------------------------------------------------------
    def _settle(self, state: _AcceleratorState) -> None:
        if state.pending_describes > 0:
            state.pending_describes -= 1
            if state.pending_describes == 0:
                state.accelerator = replace(
                    state.accelerator, status=ACCELERATOR_STATUS_DEPLOYED
                )
                self._settling.pop(state.accelerator.accelerator_arn, None)
                self._accel_list_cache = None

    def _get_state(self, arn: str) -> _AcceleratorState:
        state = self._accelerators.get(arn)
        if state is None:
            raise AWSAPIError(ERR_ACCELERATOR_NOT_FOUND, arn)
        return state

    def list_accelerators(self, max_results, next_token):
        with self._lock:
            self.calls.append(("ListAccelerators",))
            for arn in list(self._settling):
                state = self._accelerators.get(arn)
                if state is None:
                    self._settling.pop(arn, None)
                else:
                    self._settle(state)
            if self._accel_list_cache is None:
                self._accel_list_cache = [
                    s.accelerator for s in self._accelerators.values()
                ]
            return _paginate(self._accel_list_cache, max_results, next_token)

    def describe_accelerator(self, arn):
        with self._lock:
            self.calls.append(("DescribeAccelerator", arn))
            state = self._get_state(arn)
            self._settle(state)
            return state.accelerator

    def create_accelerator(self, name, ip_address_type, enabled, tags):
        _validate_accelerator_name(name)
        if ip_address_type not in _VALID_IP_ADDRESS_TYPES:
            raise AWSAPIError(
                ERR_INVALID_ARGUMENT, f"invalid IpAddressType {ip_address_type!r}"
            )
        with self._lock:
            if len(tags) > self.quota_tags_per_resource:
                raise AWSAPIError(
                    ERR_LIMIT_EXCEEDED,
                    f"{len(tags)} tags exceeds the {self.quota_tags_per_resource} "
                    "per-resource quota",
                )
            if len(self._accelerators) >= self.quota_accelerators:
                raise AWSAPIError(
                    ERR_LIMIT_EXCEEDED,
                    f"account quota of {self.quota_accelerators} accelerators reached",
                )
            # uuid5 over the serial counter, not uuid4: the ARN must be
            # re-derivable on incident replay (counter state travels in
            # the capture snapshot; random minting would diverge)
            arn = (
                f"arn:aws:globalaccelerator::{_ACCOUNT}:accelerator/"
                f"{uuid.uuid5(uuid.NAMESPACE_URL, f'agac/{_ACCOUNT}/{next(self._counter)}')}"
            )
            accelerator = Accelerator(
                accelerator_arn=arn,
                name=name,
                dns_name=f"a{next(self._counter):016x}.awsglobalaccelerator.com",
                enabled=enabled,
                status=(
                    ACCELERATOR_STATUS_IN_PROGRESS
                    if self.settle_describes
                    else ACCELERATOR_STATUS_DEPLOYED
                ),
                ip_address_type=ip_address_type,
            )
            self._accelerators[arn] = _AcceleratorState(
                accelerator, list(tags), self.settle_describes
            )
            self._accel_list_cache = None
            if self.settle_describes:
                self._settling[arn] = None
            self.calls.append(("CreateAccelerator", arn))
            return accelerator

    def update_accelerator(self, arn, name=None, enabled=None):
        if name is not None:
            _validate_accelerator_name(name)
        with self._lock:
            state = self._get_state(arn)
            changes = {}
            if name is not None:
                changes["name"] = name
            if enabled is not None:
                changes["enabled"] = enabled
            if self.settle_describes:
                changes["status"] = ACCELERATOR_STATUS_IN_PROGRESS
                state.pending_describes = self.settle_describes
                self._settling[arn] = None
            state.accelerator = replace(state.accelerator, **changes)
            self._accel_list_cache = None
            self.calls.append(("UpdateAccelerator", arn))
            return state.accelerator

    def delete_accelerator(self, arn):
        with self._lock:
            state = self._get_state(arn)
            if state.accelerator.enabled:
                raise AWSAPIError(
                    ERR_ACCELERATOR_NOT_DISABLED, "accelerator must be disabled"
                )
            if state.listeners:
                raise AWSAPIError(
                    ERR_ASSOCIATED_LISTENER_FOUND, "accelerator still has listeners"
                )
            del self._accelerators[arn]
            self._accel_list_cache = None
            self.calls.append(("DeleteAccelerator", arn))

    def list_tags_for_resource(self, arn):
        with self._lock:
            self.calls.append(("ListTagsForResource", arn))
            return list(self._get_state(arn).tags)

    def tag_resource(self, arn, tags):
        with self._lock:
            state = self._get_state(arn)
            merged = {t.key: t.value for t in state.tags}
            merged.update({t.key: t.value for t in tags})
            if len(merged) > self.quota_tags_per_resource:
                raise AWSAPIError(
                    ERR_LIMIT_EXCEEDED,
                    f"{len(merged)} tags exceeds the "
                    f"{self.quota_tags_per_resource} per-resource quota",
                )
            state.tags = [Tag(k, v) for k, v in merged.items()]
            self.calls.append(("TagResource", arn))

    def list_listeners(self, accelerator_arn, max_results, next_token):
        with self._lock:
            self.calls.append(("ListListeners", accelerator_arn))
            state = self._get_state(accelerator_arn)
            items = [
                Listener(
                    listener_arn=l.listener_arn,
                    protocol=l.protocol,
                    port_ranges=list(l.port_ranges),
                    client_affinity=l.client_affinity,
                )
                for l in state.listeners.values()
            ]
            return _paginate(items, max_results, next_token)

    def create_listener(self, accelerator_arn, port_ranges, protocol, client_affinity):
        _validate_listener_args(
            port_ranges, protocol, client_affinity,
            self.quota_port_ranges_per_listener,
        )
        with self._lock:
            state = self._get_state(accelerator_arn)
            if len(state.listeners) >= self.quota_listeners_per_accelerator:
                raise AWSAPIError(
                    ERR_LIMIT_EXCEEDED,
                    f"accelerator quota of {self.quota_listeners_per_accelerator} "
                    "listeners reached",
                )
            arn = f"{accelerator_arn}/listener/{next(self._counter):08x}"
            listener = Listener(
                listener_arn=arn,
                protocol=protocol,
                port_ranges=list(port_ranges),
                client_affinity=client_affinity,
            )
            state.listeners[arn] = listener
            self._listener_parent[arn] = accelerator_arn
            self.calls.append(("CreateListener", arn))
            return Listener(**{**vars(listener), "port_ranges": list(port_ranges)})

    def _get_listener(self, listener_arn: str) -> Listener:
        parent = self._listener_parent.get(listener_arn)
        if parent is None or parent not in self._accelerators:
            raise ListenerNotFoundException(listener_arn)
        return self._accelerators[parent].listeners[listener_arn]

    def update_listener(self, listener_arn, port_ranges, protocol, client_affinity):
        _validate_listener_args(
            port_ranges, protocol, client_affinity,
            self.quota_port_ranges_per_listener,
        )
        with self._lock:
            listener = self._get_listener(listener_arn)
            listener.port_ranges = list(port_ranges)
            listener.protocol = protocol
            listener.client_affinity = client_affinity
            self.calls.append(("UpdateListener", listener_arn))
            return Listener(**{**vars(listener), "port_ranges": list(port_ranges)})

    def delete_listener(self, arn):
        with self._lock:
            listener = self._get_listener(arn)
            if self._egs_by_listener.get(arn):
                raise AWSAPIError(
                    ERR_ASSOCIATED_ENDPOINT_GROUP_FOUND,
                    "listener still has endpoint groups",
                )
            parent = self._listener_parent.pop(arn)
            del self._accelerators[parent].listeners[arn]
            self.calls.append(("DeleteListener", arn))

    def list_endpoint_groups(self, listener_arn, max_results, next_token):
        with self._lock:
            self.calls.append(("ListEndpointGroups", listener_arn))
            self._get_listener(listener_arn)  # existence check
            items = [
                self._copy_eg(self._endpoint_groups[arn])
                for arn in self._egs_by_listener.get(listener_arn, ())
            ]
            return _paginate(items, max_results, next_token)

    @staticmethod
    def _copy_eg(eg: EndpointGroup) -> EndpointGroup:
        return EndpointGroup(
            endpoint_group_arn=eg.endpoint_group_arn,
            endpoint_group_region=eg.endpoint_group_region,
            endpoint_descriptions=[
                EndpointDescription(**vars(d)) for d in eg.endpoint_descriptions
            ],
        )

    def describe_endpoint_group(self, arn):
        with self._lock:
            self.calls.append(("DescribeEndpointGroup", arn))
            eg = self._endpoint_groups.get(arn)
            if eg is None:
                raise EndpointGroupNotFoundException(arn)
            return self._copy_eg(eg)

    def _validate_endpoint_configurations(self, configs) -> None:
        if len(configs) > self.quota_endpoints_per_group:
            raise AWSAPIError(
                ERR_LIMIT_EXCEEDED,
                f"{len(configs)} endpoints exceeds the "
                f"{self.quota_endpoints_per_group} per-group quota",
            )
        for config in configs:
            if not config.endpoint_id:
                raise AWSAPIError(ERR_INVALID_ARGUMENT, "EndpointId is required")
            if config.weight is not None and not (0 <= config.weight <= 255):
                raise AWSAPIError(
                    ERR_INVALID_ARGUMENT,
                    f"endpoint Weight {config.weight} outside 0-255",
                )

    def create_endpoint_group(self, listener_arn, endpoint_group_region, endpoint_configurations):
        if not endpoint_group_region:
            raise AWSAPIError(ERR_INVALID_ARGUMENT, "EndpointGroupRegion is required")
        self._validate_endpoint_configurations(endpoint_configurations)
        with self._lock:
            self._get_listener(listener_arn)
            groups_on_listener = len(self._egs_by_listener.get(listener_arn, ()))
            if groups_on_listener >= self.quota_endpoint_groups_per_listener:
                raise AWSAPIError(
                    ERR_LIMIT_EXCEEDED,
                    f"listener quota of {self.quota_endpoint_groups_per_listener} "
                    "endpoint groups reached",
                )
            arn = f"{listener_arn}/endpoint-group/{next(self._counter):08x}"
            eg = EndpointGroup(
                endpoint_group_arn=arn,
                endpoint_group_region=endpoint_group_region,
                endpoint_descriptions=[
                    EndpointDescription(
                        endpoint_id=c.endpoint_id,
                        weight=c.weight,
                        client_ip_preservation_enabled=c.client_ip_preservation_enabled,
                    )
                    for c in endpoint_configurations
                ],
            )
            self._endpoint_groups[arn] = eg
            self._eg_parent[arn] = listener_arn
            self._egs_by_listener.setdefault(listener_arn, {})[arn] = None
            self.calls.append(("CreateEndpointGroup", arn))
            return self._copy_eg(eg)

    def update_endpoint_group(self, arn, endpoint_configurations):
        """UpdateEndpointGroup treats the configuration list as the
        COMPLETE desired endpoint set (real AWS semantics) — callers
        updating one endpoint must send all of them."""
        self._validate_endpoint_configurations(endpoint_configurations)
        with self._lock:
            eg = self._endpoint_groups.get(arn)
            if eg is None:
                raise EndpointGroupNotFoundException(arn)
            eg.endpoint_descriptions = [
                EndpointDescription(
                    endpoint_id=c.endpoint_id,
                    weight=c.weight,
                    client_ip_preservation_enabled=c.client_ip_preservation_enabled,
                )
                for c in endpoint_configurations
            ]
            self.calls.append(("UpdateEndpointGroup", arn))
            return self._copy_eg(eg)

    def delete_endpoint_group(self, arn):
        with self._lock:
            if arn not in self._endpoint_groups:
                raise EndpointGroupNotFoundException(arn)
            del self._endpoint_groups[arn]
            parent = self._eg_parent.pop(arn)
            bucket = self._egs_by_listener.get(parent)
            if bucket is not None:
                bucket.pop(arn, None)
                if not bucket:
                    del self._egs_by_listener[parent]
            self.calls.append(("DeleteEndpointGroup", arn))

    def add_endpoints(self, arn, endpoint_configurations):
        self._validate_endpoint_configurations(endpoint_configurations)
        with self._lock:
            eg = self._endpoint_groups.get(arn)
            if eg is None:
                raise EndpointGroupNotFoundException(arn)
            new_ids = {c.endpoint_id for c in endpoint_configurations} - {
                d.endpoint_id for d in eg.endpoint_descriptions
            }
            if len(eg.endpoint_descriptions) + len(new_ids) > self.quota_endpoints_per_group:
                raise AWSAPIError(
                    ERR_LIMIT_EXCEEDED,
                    f"group quota of {self.quota_endpoints_per_group} endpoints reached",
                )
            added = []
            for c in endpoint_configurations:
                desc = EndpointDescription(
                    endpoint_id=c.endpoint_id,
                    weight=c.weight,
                    client_ip_preservation_enabled=c.client_ip_preservation_enabled,
                )
                existing = [d for d in eg.endpoint_descriptions if d.endpoint_id == c.endpoint_id]
                if existing:
                    existing[0].weight = c.weight
                    existing[0].client_ip_preservation_enabled = c.client_ip_preservation_enabled
                    added.append(existing[0])
                else:
                    eg.endpoint_descriptions.append(desc)
                    added.append(desc)
            self.calls.append(("AddEndpoints", arn))
            return [EndpointDescription(**vars(d)) for d in added]

    def remove_endpoints(self, arn, endpoint_ids):
        with self._lock:
            eg = self._endpoint_groups.get(arn)
            if eg is None:
                raise EndpointGroupNotFoundException(arn)
            eg.endpoint_descriptions = [
                d for d in eg.endpoint_descriptions if d.endpoint_id not in endpoint_ids
            ]
            self.calls.append(("RemoveEndpoints", arn))

    # ------------------------------------------------------------------
    # ELBv2API
    # ------------------------------------------------------------------
    def describe_load_balancers(self, names):
        with self._lock:
            # batch size in the log so the read-plane call-budget and
            # coalescer tests can assert wire-call counts AND widths
            self.calls.append(("DescribeLoadBalancers", len(names)))
            found = [
                LoadBalancer(**vars(self._load_balancers[n]))
                for n in names
                if n in self._load_balancers
            ]
            if not found:
                raise AWSAPIError(
                    ERR_LOAD_BALANCER_NOT_FOUND,
                    f"Load balancers '{names}' not found",
                )
            return found

    # ------------------------------------------------------------------
    # Route53API
    # ------------------------------------------------------------------
    @staticmethod
    def _wire_name(name: str) -> str:
        """Route53 stores names dot-terminated with ``*`` as ``\\052``."""
        if not name.endswith("."):
            name += "."
        return name.replace("*", "\\052", 1)

    def list_hosted_zones(self, max_items, marker):
        with self._lock:
            self.calls.append(("ListHostedZones",))
            zones = sorted(self._zones.values(), key=lambda z: z.name)
            return _paginate([HostedZone(**vars(z)) for z in zones], max_items, marker)

    def list_hosted_zones_by_name(self, dns_name, max_items):
        """Lexicographic from ``dns_name`` onward, like the real API."""
        if not dns_name.endswith("."):
            dns_name += "."
        with self._lock:
            self.calls.append(("ListHostedZonesByName", dns_name))
            # Route53 orders by reversed-label DNS name; plain name sort
            # is enough for the "does an exact zone exist" probe the
            # driver performs (reference route53.go:337-357).
            zones = sorted(self._zones.values(), key=lambda z: z.name)
            after = [HostedZone(**vars(z)) for z in zones if z.name >= dns_name]
            return after[:max_items]

    @staticmethod
    def _copy_rrs(r: ResourceRecordSet) -> ResourceRecordSet:
        from .types import AliasTarget, ResourceRecord

        return ResourceRecordSet(
            name=r.name,
            type=r.type,
            ttl=r.ttl,
            resource_records=[ResourceRecord(rr.value) for rr in r.resource_records],
            alias_target=AliasTarget(**vars(r.alias_target)) if r.alias_target else None,
        )

    def list_resource_record_sets(self, hosted_zone_id, max_items, start_record_name):
        with self._lock:
            self.calls.append(("ListResourceRecordSets", hosted_zone_id))
            if hosted_zone_id not in self._zones:
                raise AWSAPIError(ERR_NO_SUCH_HOSTED_ZONE, hosted_zone_id)
            records = sorted(
                self._records[hosted_zone_id].values(), key=lambda r: (r.name, r.type)
            )
            items = [self._copy_rrs(r) for r in records]
            return _paginate(items, max_items, start_record_name)

    def change_resource_record_sets(self, hosted_zone_id, changes: list[Change]):
        if not changes:
            raise AWSAPIError(
                ERR_INVALID_CHANGE_BATCH, "change batch must not be empty"
            )
        if len(changes) > self.quota_changes_per_batch:
            raise AWSAPIError(
                ERR_INVALID_CHANGE_BATCH,
                f"{len(changes)} changes exceeds the "
                f"{self.quota_changes_per_batch} per-batch limit",
            )
        with self._lock:
            if hosted_zone_id not in self._zones:
                raise AWSAPIError(ERR_NO_SUCH_HOSTED_ZONE, hosted_zone_id)
            table = self._records[hosted_zone_id]
            # validate the whole batch first: Route53 batches are atomic
            for change in changes:
                record_set = change.record_set
                if record_set.type not in _VALID_RR_TYPES:
                    raise AWSAPIError(
                        ERR_INVALID_CHANGE_BATCH,
                        f"invalid record type {record_set.type!r}",
                    )
                if not record_set.name:
                    raise AWSAPIError(
                        ERR_INVALID_CHANGE_BATCH, "record name is required"
                    )
                if record_set.ttl is not None and not (0 <= record_set.ttl <= _MAX_TTL):
                    raise AWSAPIError(
                        ERR_INVALID_CHANGE_BATCH,
                        f"TTL {record_set.ttl} outside 0-{_MAX_TTL}",
                    )
                if record_set.alias_target is None and record_set.ttl is None:
                    # a non-alias record set must carry a TTL
                    raise AWSAPIError(
                        ERR_INVALID_CHANGE_BATCH,
                        f"record {record_set.name!r} has neither AliasTarget nor TTL",
                    )
            for change in changes:
                record = change.record_set
                key = (self._wire_name(record.name), record.type)
                if change.action == CHANGE_ACTION_CREATE and key in table:
                    raise AWSAPIError(
                        ERR_INVALID_CHANGE_BATCH,
                        f"record {key} already exists",
                    )
                if change.action == CHANGE_ACTION_DELETE and key not in table:
                    raise AWSAPIError(
                        ERR_INVALID_CHANGE_BATCH,
                        f"record {key} does not exist",
                    )
                if change.action not in (
                    CHANGE_ACTION_CREATE,
                    CHANGE_ACTION_DELETE,
                    CHANGE_ACTION_UPSERT,
                ):
                    raise AWSAPIError(ERR_INVALID_CHANGE_BATCH, change.action)
            for change in changes:
                record = self._copy_rrs(change.record_set)
                record.name = self._wire_name(record.name)
                if record.alias_target and not record.alias_target.dns_name.endswith("."):
                    # Route53 returns alias DNSNames dot-terminated
                    # regardless of how they were submitted
                    record.alias_target.dns_name += "."
                key = (record.name, record.type)
                if change.action == CHANGE_ACTION_DELETE:
                    del table[key]
                else:
                    table[key] = record
            self.calls.append(("ChangeResourceRecordSets", hosted_zone_id))

    # -- serialization ---------------------------------------------------
    def _encode(self) -> dict:
        """The complete service state as JSON-able primitives (caller
        holds ``self._lock``)."""

        def encode_rrs(r: ResourceRecordSet) -> dict:
            return {
                "name": r.name,
                "type": r.type,
                "ttl": r.ttl,
                "values": [rr.value for rr in r.resource_records],
                "alias": dict(vars(r.alias_target)) if r.alias_target else None,
            }

        return {
            "counter": self._counter.value,
            "accelerators": [
                {
                    "accelerator": dict(vars(state.accelerator)),
                    "tags": [[t.key, t.value] for t in state.tags],
                    "pending_describes": state.pending_describes,
                    "listeners": [
                        {
                            "listener_arn": listener.listener_arn,
                            "protocol": listener.protocol,
                            "client_affinity": listener.client_affinity,
                            "port_ranges": [
                                [p.from_port, p.to_port] for p in listener.port_ranges
                            ],
                        }
                        for listener in state.listeners.values()
                    ],
                }
                for state in self._accelerators.values()
            ],
            "endpoint_groups": [
                {
                    "endpoint_group_arn": eg.endpoint_group_arn,
                    "region": eg.endpoint_group_region,
                    "parent": self._eg_parent[arn],
                    "endpoints": [dict(vars(d)) for d in eg.endpoint_descriptions],
                }
                for arn, eg in self._endpoint_groups.items()
            ],
            "load_balancers": [dict(vars(lb)) for lb in self._load_balancers.values()],
            "zones": [dict(vars(z)) for z in self._zones.values()],
            "records": {
                zone_id: [encode_rrs(r) for r in table.values()]
                for zone_id, table in self._records.items()
            },
        }

    def _apply_state(self, data: dict) -> None:
        """Replace in-memory state with ``data`` (caller holds
        ``self._lock``).  The guarded dicts are mutated in place so the
        racecheck instrumentation survives the reload."""
        from .types import AliasTarget, ResourceRecord

        self._counter.value = max(self._counter.value, int(data.get("counter", 1)))
        self._accelerators.clear()
        self._listener_parent.clear()
        self._settling.clear()
        self._egs_by_listener.clear()
        self._accel_list_cache = None
        for entry in data.get("accelerators", []):
            accelerator = Accelerator(**entry["accelerator"])
            state = _AcceleratorState(
                accelerator,
                [Tag(k, v) for k, v in entry["tags"]],
                int(entry.get("pending_describes", 0)),
            )
            for ldata in entry.get("listeners", []):
                listener = Listener(
                    listener_arn=ldata["listener_arn"],
                    protocol=ldata["protocol"],
                    client_affinity=ldata["client_affinity"],
                    port_ranges=[PortRange(f, t) for f, t in ldata["port_ranges"]],
                )
                state.listeners[listener.listener_arn] = listener
                self._listener_parent[listener.listener_arn] = (
                    accelerator.accelerator_arn
                )
            self._accelerators[accelerator.accelerator_arn] = state
            if state.pending_describes > 0:
                self._settling[accelerator.accelerator_arn] = None
        self._endpoint_groups.clear()
        self._eg_parent.clear()
        for entry in data.get("endpoint_groups", []):
            eg = EndpointGroup(
                endpoint_group_arn=entry["endpoint_group_arn"],
                endpoint_group_region=entry["region"],
                endpoint_descriptions=[
                    EndpointDescription(**d) for d in entry.get("endpoints", [])
                ],
            )
            self._endpoint_groups[eg.endpoint_group_arn] = eg
            self._eg_parent[eg.endpoint_group_arn] = entry["parent"]
            self._egs_by_listener.setdefault(entry["parent"], {})[
                eg.endpoint_group_arn
            ] = None
        self._load_balancers.clear()
        for entry in data.get("load_balancers", []):
            lb = LoadBalancer(**entry)
            self._load_balancers[lb.load_balancer_name] = lb
        self._zones.clear()
        self._records.clear()
        for entry in data.get("zones", []):
            zone = HostedZone(**entry)
            self._zones[zone.id] = zone
            self._records[zone.id] = {}
        for zone_id, records in data.get("records", {}).items():
            table = self._records.setdefault(zone_id, {})
            for rdata in records:
                record = ResourceRecordSet(
                    name=rdata["name"],
                    type=rdata["type"],
                    ttl=rdata["ttl"],
                    resource_records=[ResourceRecord(v) for v in rdata["values"]],
                    alias_target=(
                        AliasTarget(**rdata["alias"]) if rdata["alias"] else None
                    ),
                )
                table[(record.name, record.type)] = record

    def snapshot_state(self) -> dict:
        """The full service state, JSON-able — the incident capture's
        AWS seed (ISSUE 19): a replay restores it verbatim before
        re-deriving the recorded call stream."""
        with self._lock:
            return self._encode()

    def restore_state(self, data: dict) -> None:
        """Replace all service state with a ``snapshot_state()`` dump."""
        with self._lock:
            self._apply_state(data)


class FileBackedFakeAWSBackend(FakeAWSBackend):
    """Durable fake AWS: committed state survives process death.

    Every mutating API call is flushed to a JSON state file (written
    atomically: tmp + ``os.replace``), and every API call first reloads
    the file if another process changed it — so a controller process
    killed mid-mutation leaves behind EXACTLY the AWS state its
    committed calls created, and the next generation (a restarted
    controller, a standby manager, or the asserting test) reads that
    ground truth.  This is what makes real kill-and-restart
    convergence drills possible with ``AGAC_CLOUD=fake``: without it,
    the in-memory "AWS" dies with the process and crash consistency is
    unfalsifiable.

    The persistence seam sits INSIDE the fault plan (see
    ``FakeAWSBackend.__getattribute__``): a ``fail_after_commit`` or
    ``crash(op, when="after-commit")`` fires only after the commit hit
    disk, matching a real backend's view of a dying client.

    Multi-writer safe (ISSUE 8): sharded deployments run several
    concurrently-live controller processes against one "account", so
    every mutating op holds an interprocess ``flock`` on a sidecar
    lock file across reload → apply → save.  The state file is then a
    serialized op log — a committed mutation can never be clobbered by
    a concurrent writer's stale whole-file write (the lost-update race
    the old single-writer design tolerated because the leader-failover
    drill killed the old leader before the standby mutated).  Reads
    stay lock-free: atomic replace means a reload always sees a
    complete snapshot, just possibly a stale one — exactly AWS's
    read-after-write consistency model."""

    _SEED_HELPERS = frozenset(
        {"add_load_balancer", "add_hosted_zone", "set_load_balancer_state"}
    )

    # read-path reload throttle (ISSUE 10): with several live writers
    # the state file changes constantly, so an unthrottled read path
    # re-parses the whole JSON on nearly every API call — at 4-8
    # sharded subprocesses on one box that parse cost was a measurable
    # slice of the scaling curve.  Reads may serve state up to this
    # many seconds stale (mutations still force-reload under the
    # flock), which is exactly the read-after-write consistency model
    # the class docstring documents.
    READ_RELOAD_INTERVAL = 0.05

    def __init__(self, state_path: str, **kwargs):
        super().__init__(**kwargs)
        self._state_path = str(state_path)
        self._state_stamp: Optional[tuple] = None
        self._state_serial = 0
        self._last_reload_check = -1.0
        # interprocess mutation arbitration (see class docstring);
        # thread-local depth makes driver orchestrations that issue
        # several ops reentrancy-safe within one thread
        self._ipc_lock_path = f"{self._state_path}.lock"
        self._ipc_depth = threading.local()
        self._persist_hook = self._persisted
        self._reload_if_changed()

    def _interprocess_write_lock(self):
        backend = self

        class _Held:
            def __enter__(self):
                depth = getattr(backend._ipc_depth, "value", 0)
                backend._ipc_depth.value = depth + 1
                if depth:
                    self._f = None
                    return self
                import fcntl

                self._f = open(backend._ipc_lock_path, "a+")
                fcntl.flock(self._f, fcntl.LOCK_EX)
                return self

            def __exit__(self, *exc):
                backend._ipc_depth.value -= 1
                if self._f is not None:
                    import fcntl

                    fcntl.flock(self._f, fcntl.LOCK_UN)
                    self._f.close()

        return _Held()

    # -- the API-op seam (installed via _persist_hook) ------------------
    def _persisted(self, name: str, call):
        mutating = name.startswith(_MUTATING_PREFIXES)

        def synced(*args, **kwargs):
            if not mutating:
                self._reload_if_changed()
                return call(*args, **kwargs)
            # serialize reload → apply → save across processes: the
            # state file becomes an op log, never a lost update.  The
            # reload is FORCED, not stamp-gated: stat stamps are not
            # collision-proof here (mtime granularity, size ties, and
            # immediate inode recycling under os.replace all observed
            # on container filesystems), and a skipped reload in the
            # write path clobbers the other process's committed ops.
            with self._interprocess_write_lock():
                self._reload_if_changed(force=True)
                result = call(*args, **kwargs)
                self._save()
            return result

        return synced

    # -- test helpers stay coherent across processes too ----------------
    def add_load_balancer(self, *args, **kwargs):
        with self._interprocess_write_lock():
            self._reload_if_changed(force=True)
            lb = super().add_load_balancer(*args, **kwargs)
            self._save()
        return lb

    def add_hosted_zone(self, *args, **kwargs):
        with self._interprocess_write_lock():
            self._reload_if_changed(force=True)
            zone = super().add_hosted_zone(*args, **kwargs)
            self._save()
        return zone

    def set_load_balancer_state(self, *args, **kwargs):
        with self._interprocess_write_lock():
            self._reload_if_changed(force=True)
            super().set_load_balancer_state(*args, **kwargs)
            self._save()

    def records_in_zone(self, zone_id):
        self._reload_if_changed()
        return super().records_in_zone(zone_id)

    def all_accelerator_arns(self):
        self._reload_if_changed()
        return super().all_accelerator_arns()

    def chain_counts(self):
        self._reload_if_changed()
        return super().chain_counts()

    def accelerator_owners(self):
        self._reload_if_changed()
        return super().accelerator_owners()

    def all_hosted_zone_ids(self):
        self._reload_if_changed()
        return super().all_hosted_zone_ids()

    def zone_id_by_name(self, name: str) -> Optional[str]:
        """Resolve a zone id by name — the assertion-side lookup a
        fresh process needs (zone IDS are minted by whichever process
        seeded first)."""
        if not name.endswith("."):
            name += "."
        self._reload_if_changed()
        with self._lock:
            for zone in self._zones.values():
                if zone.name == name:
                    return zone.id
        return None

    # -- the file ---------------------------------------------------------
    def _stat_stamp(self) -> Optional[tuple]:
        try:
            stat = os.stat(self._state_path)
        except FileNotFoundError:
            return None
        # st_ino is the collision breaker: every _save replaces the
        # file with a fresh inode, so two different states can never
        # share a stamp even when mtime_ns granularity and byte size
        # collide (the lost-update the sharded multi-writer drill
        # caught — two processes' saves a few hundred µs apart)
        return (stat.st_mtime_ns, stat.st_size, stat.st_ino)

    def _save(self) -> None:
        with self._lock:
            # the write serial leads the payload so a reader can skip
            # the full parse+apply when the file still holds ITS OWN
            # last-synced state (serials are strictly increasing under
            # the flock, so equal serial == identical content); compact
            # separators because the dump runs inside the interprocess
            # flock — every byte is serialized time across the fleet
            self._state_serial = getattr(self, "_state_serial", 0) + 1
            body = {"serial": self._state_serial}
            body.update(self._encode())
            payload = json.dumps(body, separators=(",", ":"))
        tmp = f"{self._state_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            # no fsync: the crash model is process death (kill -9 —
            # the drills' SIGKILL), which never loses OS-buffered
            # writes; rename atomicity below is what guards torn
            # files.  fsync only protects against POWER loss, which
            # nothing here simulates, and it cost ~10% of the flock
            # critical section at fleet scale.
            f.write(payload)
        # atomic replace: a reader (or a process killed mid-save) can
        # never observe a torn file
        os.replace(tmp, self._state_path)
        self._state_stamp = self._stat_stamp()

    def _file_serial(self) -> Optional[int]:
        """The leading write serial of the state file, read without
        parsing the body (48 bytes cover {"serial":<20 digits>,)."""
        try:
            with open(self._state_path) as f:
                prefix = f.read(48)
        except OSError:
            return None
        if not prefix.startswith('{"serial":'):
            return None
        digits = prefix[len('{"serial":'):].split(",", 1)[0]
        try:
            return int(digits)
        except ValueError:
            return None

    def _reload_if_changed(self, force: bool = False) -> None:
        if not force:
            # read path: throttle the stat+parse to the documented
            # staleness window (mutations always force through this)
            now = clockseam.monotonic()
            if 0.0 <= now - self._last_reload_check < self.READ_RELOAD_INTERVAL:
                return
            self._last_reload_check = now
        stamp = self._stat_stamp()
        if stamp is None:
            return
        if stamp == self._state_stamp and not force:
            return
        # serial short-circuit (ISSUE 10): stat stamps are not
        # collision-proof (the forced mutation path exists because of
        # that), but the embedded write serial IS — it only advances
        # under the flock.  When the file still carries the serial this
        # process last wrote/loaded, the ~4 ms parse+apply is skipped;
        # with N concurrent writers that converts 1/N of every flock
        # critical section into a 48-byte read.
        serial = self._file_serial()
        if serial is not None and serial == getattr(self, "_state_serial", None):
            self._state_stamp = stamp
            return
        with open(self._state_path) as f:
            data = json.load(f)
        with self._lock:
            self._apply_state(data)
            self._state_serial = int(data.get("serial", 0) or 0)
        self._state_stamp = stamp
