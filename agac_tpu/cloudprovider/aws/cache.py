"""Accelerator-discovery cache.

The reference's hottest path is discovery: every reconcile lists ALL
accelerators and then calls ListTagsForResource per accelerator —
O(total accelerators) AWS calls per work item (reference
``pkg/cloudprovider/aws/global_accelerator.go:87-110``; flagged as the
hot spot in SURVEY.md §3.2).  This cache memoizes the
(accelerator, tags) snapshot for a short TTL and absorbs this
process's own writes, so:

- a converged steady state (resyncs, level-trigger re-reconciles)
  costs one AWS list per TTL window instead of per item;
- any local write is immediately visible, so a reconcile never acts
  on its own stale write;
- cross-process writes (another controller instance) are visible
  after at most the TTL — the same order of staleness the reference
  already tolerates between its 30 s informer resyncs, since
  reconciles are level-triggered and idempotent.

Opt-in: drivers constructed without a cache behave exactly like the
reference (fresh scan every call).

Two mechanisms keep creation storms O(N) instead of O(N^2):

- **Single-flight loading.**  Only one worker runs the O(N) scan at a
  time; concurrent missers wait for its snapshot instead of issuing
  duplicate scans.  (Measured under the shaped-latency bench at
  N=1000: without this, ~32 workers each re-scan on every miss.)
- **A write journal during loads.**  A write landing while a scan is
  in flight used to discard the scan's result (the scan may predate
  the write), so during a storm — where every item writes — no
  snapshot ever got stored and every reconcile paid a fresh O(N)
  scan.  Instead, writes made during a load are journaled and FOLDED
  INTO the loaded snapshot before it is stored: the writer knows
  exactly the (accelerator, tags) it wrote, so local knowledge
  repairs whatever the scan missed.  ``invalidate`` (external/unknown
  change) journaled during a load still prevents the store.

Snapshot entries are SHARED between callers, never copied per read:
``Accelerator`` and ``Tag`` are frozen dataclasses, and the snapshot
list itself is replaced wholesale, never mutated in place.  (A
defensive deepcopy per hit used to dominate the steady-state reconcile
profile.)
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .types import Accelerator, Tag

Snapshot = list[tuple[Accelerator, list[Tag]]]


class HostedZoneCache:
    """TTL snapshot of ALL hosted zones, so ``get_hosted_zone``'s
    parent-domain walk (reference ``route53.go:334-358``) runs in
    memory instead of costing ~2 ListHostedZonesByName probes per
    Route53 ensure — half the Route53 quota spend under the
    shaped-latency bench, against a zone set that is created by
    humans and changes about never.

    Staleness is handled at the callers, cheaply: a hostname that
    does NOT resolve in the snapshot falls back to a live walk (a
    zone created moments ago is still found, and the stale snapshot
    is dropped); a cached zone that was deleted out-of-band surfaces
    as NoSuchHostedZone on first use, which invalidates the snapshot
    so the retry re-reads.  Loads are single-flight: concurrent
    missers wait for one zone list instead of issuing their own."""

    def __init__(self, ttl: float = 60.0, clock: Callable[[], float] = time.monotonic):
        self._ttl = ttl
        self._clock = clock
        self._lock = threading.Lock()
        self._zones: Optional[list] = None
        self._by_name: Optional[dict] = None
        self._expires = 0.0
        self._load_event: Optional[threading.Event] = None
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _build_index(zones: list) -> dict:
        """name → zone, NAME-SORTED first-wins: Route53 allows
        duplicate zone names, and the live ListHostedZonesByName probe
        (max_items=1) returns the name-ordered first — sorting before
        setdefault keeps the cached walk's winner identical to the
        probe's regardless of ListHostedZones iteration order."""
        by_name: dict = {}
        for zone in sorted(zones, key=lambda z: z.name):
            by_name.setdefault(zone.name, zone)
        return by_name

    def zones(self, loader: Callable[[], list]) -> list:
        """The zone snapshot, loading through ``loader`` (a full
        ListHostedZones drain) when absent or expired."""
        while True:
            with self._lock:
                if self._zones is not None and self._clock() < self._expires:
                    self.hits += 1
                    return self._zones
                if self._load_event is None:
                    self._load_event = event = threading.Event()
                    self.misses += 1
                    break
                event = self._load_event
            event.wait()
        try:
            zones = list(loader())
        except BaseException:
            with self._lock:
                self._load_event = None
            event.set()
            raise
        with self._lock:
            self._zones = zones
            self._by_name = self._build_index(zones)
            self._expires = self._clock() + self._ttl
            self._load_event = None
        event.set()
        return zones

    def zone_index(self, loader: Callable[[], list]) -> dict:
        """The name → zone index for the current snapshot, built once
        per load (not per walk)."""
        zones = self.zones(loader)
        with self._lock:
            if self._zones is zones and self._by_name is not None:
                return self._by_name
        # the snapshot changed between zones() and here (rare):
        # build from the list this caller actually holds
        return self._build_index(zones)

    def invalidate(self) -> None:
        with self._lock:
            self._zones = None
            self._by_name = None
            self._expires = 0.0


class DiscoveryCache:
    def __init__(self, ttl: float = 5.0, clock: Callable[[], float] = time.monotonic):
        self._ttl = ttl
        self._clock = clock
        self._lock = threading.Lock()
        self._snapshot: Optional[Snapshot] = None
        self._expires = 0.0
        # set while a load is in flight; completion (success or not)
        # sets it.  Guarded by _lock.
        self._load_event: Optional[threading.Event] = None
        # writes observed while the in-flight load runs, replayed onto
        # the loaded snapshot before it is stored.  Guarded by _lock.
        self._journal: Optional[list] = None
        self.hits = 0
        self.misses = 0

    def get(self, loader: Callable[[], Snapshot]) -> Snapshot:
        """Return the cached snapshot, loading through ``loader`` when
        absent or expired.

        The load runs OUTSIDE the lock (holding it across the O(N)
        scan would convoy all workers behind one loader) and is
        SINGLE-FLIGHT: a second misser waits for the first's snapshot
        instead of scanning again.  Writes that land during the scan
        are journaled and folded into the snapshot before it is
        stored, so a stale scan can never mask a newer local write."""
        while True:
            with self._lock:
                if self._snapshot is not None and self._clock() < self._expires:
                    self.hits += 1
                    return self._snapshot
                if self._load_event is None:
                    self._load_event = event = threading.Event()
                    self._journal = []
                    self.misses += 1
                    break
                event = self._load_event
            # another worker is already scanning: wait for its result,
            # then re-check (it may have failed — then we lead a retry)
            event.wait()
        try:
            snapshot = list(loader())
        except BaseException:
            with self._lock:
                self._load_event = None
                self._journal = None
            event.set()
            raise
        with self._lock:
            journal = self._journal or []
            self._load_event = None
            self._journal = None
            discard = False
            for op, payload in journal:
                if op == "invalidate":
                    discard = True
                elif op == "upsert":
                    accelerator, tags = payload
                    snapshot = [
                        item
                        for item in snapshot
                        if item[0].accelerator_arn != accelerator.accelerator_arn
                    ] + [(accelerator, tags)]
                else:  # remove
                    snapshot = [
                        item for item in snapshot if item[0].accelerator_arn != payload
                    ]
            if discard:
                self._snapshot = None
                self._expires = 0.0
            else:
                self._snapshot = snapshot
                self._expires = self._clock() + self._ttl
        event.set()
        return snapshot

    def invalidate(self) -> None:
        """External/unknown change: drop the snapshot, and poison any
        in-flight load so its result is returned but not stored."""
        with self._lock:
            self._snapshot = None
            self._expires = 0.0
            if self._journal is not None:
                self._journal.append(("invalidate", None))

    def upsert(self, accelerator: Accelerator, tags: list[Tag]) -> None:
        """Fold a local create/update into the snapshot instead of
        discarding it.  During creation storms every item writes; a
        blanket invalidate would force a full O(N) rescan per write,
        making convergence O(N^2) AWS calls.  The writer knows exactly
        the (accelerator, tags) it wrote, so the snapshot can absorb
        it and stay warm.  Expiry is left unchanged: entries from the
        original load still refresh within the TTL, so cross-process
        staleness bounds are unaffected.  During an in-flight load the
        write is also journaled so the loaded snapshot cannot miss it."""
        entry = (accelerator, list(tags))
        with self._lock:
            if self._journal is not None:
                self._journal.append(("upsert", entry))
            if self._snapshot is not None:
                self._snapshot = [
                    item
                    for item in self._snapshot
                    if item[0].accelerator_arn != accelerator.accelerator_arn
                ] + [entry]

    def remove(self, accelerator_arn: str) -> None:
        """Drop a locally deleted accelerator from the snapshot (same
        rationale and journal semantics as ``upsert``)."""
        with self._lock:
            if self._journal is not None:
                self._journal.append(("remove", accelerator_arn))
            if self._snapshot is not None:
                self._snapshot = [
                    item
                    for item in self._snapshot
                    if item[0].accelerator_arn != accelerator_arn
                ]
