"""Accelerator-discovery cache.

The reference's hottest path is discovery: every reconcile lists ALL
accelerators and then calls ListTagsForResource per accelerator —
O(total accelerators) AWS calls per work item (reference
``pkg/cloudprovider/aws/global_accelerator.go:87-110``; flagged as the
hot spot in SURVEY.md §3.2).  This cache memoizes the
(accelerator, tags) snapshot for a short TTL and is invalidated by
every mutating driver operation in this process, so:

- a converged steady state (resyncs, level-trigger re-reconciles)
  costs one AWS list per TTL window instead of per item;
- any local write immediately invalidates, so a reconcile never acts
  on its own stale write;
- cross-process writes (another controller instance) are visible
  after at most the TTL — the same order of staleness the reference
  already tolerates between its 30 s informer resyncs, since
  reconciles are level-triggered and idempotent.

Opt-in: drivers constructed without a cache behave exactly like the
reference (fresh scan every call).

Snapshot entries are SHARED between callers, never copied per read:
``Accelerator`` and ``Tag`` are frozen dataclasses, and the snapshot
list itself is replaced wholesale, never mutated in place.  (A
defensive deepcopy per hit used to dominate the steady-state reconcile
profile.)
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .types import Accelerator, Tag

Snapshot = list[tuple[Accelerator, list[Tag]]]


class DiscoveryCache:
    def __init__(self, ttl: float = 5.0, clock: Callable[[], float] = time.monotonic):
        self._ttl = ttl
        self._clock = clock
        self._lock = threading.Lock()
        self._snapshot: Optional[Snapshot] = None
        self._expires = 0.0
        self._generation = 0
        self.hits = 0
        self.misses = 0

    def get(self, loader: Callable[[], Snapshot]) -> Snapshot:
        """Return the cached snapshot, loading through ``loader`` when
        absent or expired.

        The load runs OUTSIDE the lock: during creation storms every
        write invalidates, and holding the lock across the O(N) scan
        would convoy all workers behind one loader (measured 2x
        slowdown).  Concurrent loads are allowed; a loaded snapshot is
        only stored if no invalidation happened since the load began
        (generation check), so a stale scan can never mask a newer
        local write."""
        with self._lock:
            if self._snapshot is not None and self._clock() < self._expires:
                self.hits += 1
                return self._snapshot
            self.misses += 1
            generation = self._generation
        snapshot = loader()
        with self._lock:
            if self._generation == generation:
                self._snapshot = snapshot
                self._expires = self._clock() + self._ttl
        return snapshot

    def invalidate(self) -> None:
        with self._lock:
            self._generation += 1
            self._snapshot = None
            self._expires = 0.0

    def upsert(self, accelerator: Accelerator, tags: list[Tag]) -> None:
        """Fold a local create/update into the snapshot instead of
        discarding it.  During creation storms every item writes; a
        blanket invalidate would force a full O(N) rescan per write,
        making convergence O(N^2) AWS calls.  The writer knows exactly
        the (accelerator, tags) it wrote, so the snapshot can absorb
        it and stay warm.  Expiry is left unchanged: entries from the
        original load still refresh within the TTL, so cross-process
        staleness bounds are unaffected.  The generation bump keeps an
        in-flight loader (started before this write) from storing a
        snapshot that misses it."""
        entry = (accelerator, list(tags))
        with self._lock:
            self._generation += 1
            if self._snapshot is None:
                return
            self._snapshot = [
                item
                for item in self._snapshot
                if item[0].accelerator_arn != accelerator.accelerator_arn
            ] + [entry]

    def remove(self, accelerator_arn: str) -> None:
        """Drop a locally deleted accelerator from the snapshot (same
        rationale and generation semantics as ``upsert``)."""
        with self._lock:
            self._generation += 1
            if self._snapshot is None:
                return
            self._snapshot = [
                item
                for item in self._snapshot
                if item[0].accelerator_arn != accelerator_arn
            ]
