"""The coalesced read plane: discovery, topology, zone, record-set and
load-balancer caches.

The reference's hottest path is discovery: every reconcile lists ALL
accelerators and then calls ListTagsForResource per accelerator —
O(total accelerators) AWS calls per work item (reference
``pkg/cloudprovider/aws/global_accelerator.go:87-110``; flagged as the
hot spot in SURVEY.md §3.2).  This cache memoizes the
(accelerator, tags) snapshot for a short TTL and absorbs this
process's own writes, so:

- a converged steady state (resyncs, level-trigger re-reconciles)
  costs one AWS list per TTL window instead of per item;
- any local write is immediately visible, so a reconcile never acts
  on its own stale write;
- cross-process writes (another controller instance) are visible
  after at most the TTL — the same order of staleness the reference
  already tolerates between its 30 s informer resyncs, since
  reconciles are level-triggered and idempotent.

Opt-in: drivers constructed without a cache behave exactly like the
reference (fresh scan every call).

Two mechanisms keep creation storms O(N) instead of O(N^2):

- **Single-flight loading.**  Only one worker runs the O(N) scan at a
  time; concurrent missers wait for its snapshot instead of issuing
  duplicate scans.  (Measured under the shaped-latency bench at
  N=1000: without this, ~32 workers each re-scan on every miss.)
- **A write journal during loads.**  A write landing while a scan is
  in flight used to discard the scan's result (the scan may predate
  the write), so during a storm — where every item writes — no
  snapshot ever got stored and every reconcile paid a fresh O(N)
  scan.  Instead, writes made during a load are journaled and FOLDED
  INTO the loaded snapshot before it is stored: the writer knows
  exactly the (accelerator, tags) it wrote, so local knowledge
  repairs whatever the scan missed.  ``invalidate`` (external/unknown
  change) journaled during a load still prevents the store.

Snapshot entries are SHARED between callers, never copied per read:
``Accelerator`` and ``Tag`` are frozen dataclasses, and the snapshot
list itself is replaced wholesale, never mutated in place.  (A
defensive deepcopy per hit used to dominate the steady-state reconcile
profile.)

Beyond the two discovery caches, this module carries the three caches
of the coalesced VERIFICATION read plane (ISSUE 2): drift ticks used
to pay O(N) per-object reads — three GA list calls per accelerator,
one ListResourceRecordSets per hostname against a handful of shared
zones, and one single-name DescribeLoadBalancers per object.  The
read plane collapses those to ~one GA read per accelerator, one
record-set list per hosted zone per tick window, and multi-name
DescribeLoadBalancers wire calls:

- ``AcceleratorTopologyCache`` — per-accelerator (listener, endpoint
  group) chains, write-through from the driver's own mutate chains;
- ``RecordSetCache`` — per-zone record-set snapshots with the change
  batches the driver commits folded back in;
- ``LoadBalancerCoalescer`` — a TTL cache plus a gatherer that merges
  concurrent single-name lookups into one multi-name wire call.

All three are TICK-SCOPED by construction: drift verification exists
to catch out-of-band tampering, so snapshots are shared within one
verification round (TTLs well under any sane ``--drift-resync-period``)
and re-read on the next, and every mismatch/not-found path invalidates
the same way ``HostedZoneCache`` does.  Local writes are folded or
write-through applied, never masked.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ... import clockseam

from .errors import ListenerNotFoundException
from .types import (
    CHANGE_ACTION_DELETE,
    Accelerator,
    EndpointGroup,
    Listener,
    LoadBalancer,
    ResourceRecordSet,
    Tag,
)

Snapshot = list[tuple[Accelerator, list[Tag]]]


class HostedZoneCache:
    """TTL snapshot of ALL hosted zones, so ``get_hosted_zone``'s
    parent-domain walk (reference ``route53.go:334-358``) runs in
    memory instead of costing ~2 ListHostedZonesByName probes per
    Route53 ensure — half the Route53 quota spend under the
    shaped-latency bench, against a zone set that is created by
    humans and changes about never.

    Staleness is handled at the callers, cheaply: a hostname that
    does NOT resolve in the snapshot falls back to a live walk (a
    zone created moments ago is still found, and the stale snapshot
    is dropped); a cached zone that was deleted out-of-band surfaces
    as NoSuchHostedZone on first use, which invalidates the snapshot
    so the retry re-reads.  Loads are single-flight: concurrent
    missers wait for one zone list instead of issuing their own."""

    def __init__(self, ttl: float = 60.0, clock: Optional[Callable[[], float]] = None):
        self._ttl = ttl
        self._clock = clock or clockseam.monotonic
        self._lock = threading.Lock()
        self._zones: Optional[list] = None
        self._by_name: Optional[dict] = None
        self._expires = 0.0
        self._load_event: Optional[threading.Event] = None
        self.hits = 0
        self.misses = 0
        self.waits = 0  # callers that parked behind another's load

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses, "waits": self.waits}

    @staticmethod
    def _build_index(zones: list) -> dict:
        """name → zone, NAME-SORTED first-wins: Route53 allows
        duplicate zone names, and the live ListHostedZonesByName probe
        (max_items=1) returns the name-ordered first — sorting before
        setdefault keeps the cached walk's winner identical to the
        probe's regardless of ListHostedZones iteration order."""
        by_name: dict = {}
        for zone in sorted(zones, key=lambda z: z.name):
            by_name.setdefault(zone.name, zone)
        return by_name

    def zones(self, loader: Callable[[], list]) -> list:
        """The zone snapshot, loading through ``loader`` (a full
        ListHostedZones drain) when absent or expired."""
        while True:
            with self._lock:
                if self._zones is not None and self._clock() < self._expires:
                    self.hits += 1
                    return self._zones
                if self._load_event is None:
                    self._load_event = event = threading.Event()
                    self.misses += 1
                    break
                event = self._load_event
                self.waits += 1
            event.wait()
        try:
            zones = list(loader())
        except BaseException:
            with self._lock:
                self._load_event = None
            event.set()
            raise
        with self._lock:
            self._zones = zones
            self._by_name = self._build_index(zones)
            self._expires = self._clock() + self._ttl
            self._load_event = None
        event.set()
        return zones

    def zone_index(self, loader: Callable[[], list]) -> dict:
        """The name → zone index for the current snapshot, built once
        per load (not per walk)."""
        zones = self.zones(loader)
        with self._lock:
            if self._zones is zones and self._by_name is not None:
                return self._by_name
        # the snapshot changed between zones() and here (rare):
        # build from the list this caller actually holds
        return self._build_index(zones)

    def invalidate(self) -> None:
        with self._lock:
            self._zones = None
            self._by_name = None
            self._expires = 0.0


class DiscoveryCache:
    def __init__(
        self,
        ttl: float = 5.0,
        clock: Optional[Callable[[], float]] = None,
        degraded: Optional[Callable[[], bool]] = None,
        tags_ttl: Optional[float] = None,
    ):
        self._ttl = ttl
        self._clock = clock or clockseam.monotonic
        # incremental snapshot refresh (ISSUE 6): with tags_ttl set,
        # a reload may REUSE the tags of accelerators the previous
        # snapshot already knew (``reusable_tags``) instead of paying
        # one ListTagsForResource per accelerator per reload — local
        # writes are write-through (upsert) so they are always exact,
        # and out-of-band TAG edits are re-detected within tags_ttl
        # (a full tag re-list).  None (default) = legacy behavior:
        # every reload re-reads every accelerator's tags, and the tag
        # tamper-detection bound stays the snapshot TTL itself.
        self._tags_ttl = tags_ttl
        self._tags_loaded_at: Optional[float] = None
        self._tags_refreshing = False
        # health-plane hook (factory wires it to "is the GA circuit
        # open"): while True, an expired snapshot is served stale
        # instead of dispatching a reload that is known to fail —
        # bounded staleness beats a guaranteed error during a brownout
        self._degraded = degraded
        self._lock = threading.Lock()
        # the snapshot proper: arn -> (Accelerator, tags), plus an
        # inverted tag index (key, value) -> set of arns so tag-scan
        # queries (`match`) cost O(result), not O(fleet) — the 7-day
        # sim soak surfaced the linear scan as an O(N^2) convergence
        # wall at N=10k.  ``_list_cache`` memoizes the list view
        # ``get``/``peek`` hand out; any write drops it.
        self._entries: Optional[dict[str, tuple[Accelerator, list[Tag]]]] = None
        self._by_tag: dict[tuple[str, str], set[str]] = {}
        self._list_cache: Optional[Snapshot] = None
        self._expires = 0.0
        # set while a load is in flight; completion (success or not)
        # sets it.  Guarded by _lock.
        self._load_event: Optional[threading.Event] = None
        # writes observed while the in-flight load runs, replayed onto
        # the loaded snapshot before it is stored.  Guarded by _lock.
        self._journal: Optional[list] = None
        self.hits = 0
        self.misses = 0
        self.waits = 0  # callers that parked behind another's load
        self.stale_serves = 0  # expired snapshots served while degraded
        self.tag_full_refreshes = 0  # loads that re-read every tag set
        self.tag_incremental_loads = 0  # loads that reused known tags

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "waits": self.waits,
                "stale_serves": self.stale_serves,
                "tag_full_refreshes": self.tag_full_refreshes,
                "tag_incremental_loads": self.tag_incremental_loads,
            }

    def reusable_tags(self) -> dict:
        """arn → tags the in-flight loader may reuse instead of
        re-listing live (the incremental-refresh seam the driver's
        ``_load_discovery_snapshot`` consults).  Empty when the cache
        holds nothing, when incremental refresh is off (tags_ttl
        None), or when the tag set is due for a full re-read — the
        load that receives {} IS the full refresh, and its successful
        store restamps the tag clock."""
        with self._lock:
            now = self._clock()
            due = (
                self._tags_ttl is None
                or self._entries is None
                or self._tags_loaded_at is None
                or now >= self._tags_loaded_at + self._tags_ttl
            )
            if due:
                self.tag_full_refreshes += 1
                self._tags_refreshing = True
                return {}
            self.tag_incremental_loads += 1
            return {arn: tags for arn, (_, tags) in self._entries.items()}

    @staticmethod
    def _build_index(
        entries: dict[str, tuple[Accelerator, list[Tag]]],
    ) -> dict[tuple[str, str], set[str]]:
        by_tag: dict[tuple[str, str], set[str]] = {}
        for arn, (_, tags) in entries.items():
            for tag in tags:
                by_tag.setdefault((tag.key, tag.value), set()).add(arn)
        return by_tag

    def _index_add(self, arn: str, tags: list[Tag]) -> None:
        for tag in tags:
            self._by_tag.setdefault((tag.key, tag.value), set()).add(arn)

    def _index_discard(self, arn: str, tags: list[Tag]) -> None:
        for tag in tags:
            bucket = self._by_tag.get((tag.key, tag.value))
            if bucket is not None:
                bucket.discard(arn)
                if not bucket:
                    del self._by_tag[(tag.key, tag.value)]

    def _ensure(self, loader: Callable[[], Snapshot]):
        """Guarantee a fresh snapshot, loading through ``loader`` when
        absent or expired; returns ``(entries, by_tag)`` — the stored
        structures on the normal path, transient ones when a journaled
        ``invalidate`` poisoned the store.

        The load runs OUTSIDE the lock (holding it across the O(N)
        scan would convoy all workers behind one loader) and is
        SINGLE-FLIGHT: a second misser waits for the first's snapshot
        instead of scanning again.  Writes that land during the scan
        are journaled and folded into the snapshot before it is
        stored, so a stale scan can never mask a newer local write."""
        while True:
            with self._lock:
                if self._entries is not None and self._clock() < self._expires:
                    self.hits += 1
                    return self._entries, self._by_tag
                if (
                    self._entries is not None
                    and self._degraded is not None
                    and self._degraded()
                ):
                    self.stale_serves += 1
                    return self._entries, self._by_tag
                if self._load_event is None:
                    self._load_event = event = threading.Event()
                    self._journal = []
                    self.misses += 1
                    break
                event = self._load_event
                self.waits += 1
            # another worker is already scanning: wait for its result,
            # then re-check (it may have failed — then we lead a retry)
            event.wait()
        try:
            snapshot = list(loader())
        except BaseException:
            with self._lock:
                self._load_event = None
                self._journal = None
                self._tags_refreshing = False
            event.set()
            raise
        with self._lock:
            journal = self._journal or []
            self._load_event = None
            self._journal = None
            discard = False
            entries = {
                accelerator.accelerator_arn: (accelerator, list(tags))
                for accelerator, tags in snapshot
            }
            for op, payload in journal:
                if op == "invalidate":
                    discard = True
                elif op == "upsert":
                    accelerator, tags = payload
                    entries[accelerator.accelerator_arn] = (accelerator, tags)
                else:  # remove
                    entries.pop(payload, None)
            if discard:
                self._entries = None
                self._by_tag = {}
                self._list_cache = None
                self._expires = 0.0
                self._tags_refreshing = False
                result = (entries, self._build_index(entries))
            else:
                self._entries = entries
                self._by_tag = self._build_index(entries)
                self._list_cache = None
                self._expires = self._clock() + self._ttl
                if self._tags_refreshing:
                    # this load was a full tag refresh: restart the
                    # incremental-reuse window from its completion
                    self._tags_loaded_at = self._clock()
                    self._tags_refreshing = False
                result = (entries, self._by_tag)
        event.set()
        return result

    def get(self, loader: Callable[[], Snapshot]) -> Snapshot:
        """The full snapshot as a list of (accelerator, tags) pairs,
        loading when absent or expired (see ``_ensure``).  The list
        view is memoized until the next write, so repeated full walks
        (GC sweeps, drift ticks) share one materialization."""
        entries, _ = self._ensure(loader)
        with self._lock:
            if entries is self._entries:
                if self._list_cache is None:
                    self._list_cache = list(entries.values())
                return self._list_cache
        return list(entries.values())

    def match(
        self, loader: Callable[[], Snapshot], want: dict[str, str]
    ) -> Snapshot:
        """All (accelerator, tags) pairs whose tags contain every
        (key, value) in ``want``, answered from the inverted tag index
        in O(candidates of the rarest key) — for the owner-tag scans
        every reconcile issues, O(1) instead of O(fleet).  Results are
        ordered by arn so iteration order never depends on set/hash
        order (the sim's replay contract)."""
        entries, by_tag = self._ensure(loader)
        with self._lock:
            candidates: Optional[set[str]] = None
            for pair in want.items():
                bucket = by_tag.get(pair)
                if not bucket:
                    return []
                if candidates is None or len(bucket) < len(candidates):
                    candidates = bucket
            if candidates is None:
                return list(entries.values())
            result = []
            for arn in sorted(candidates):
                entry = entries.get(arn)
                if entry is not None and all(
                    (key, value) in by_tag and arn in by_tag[(key, value)]
                    for key, value in want.items()
                ):
                    result.append(entry)
        return result

    def peek(self) -> Optional[Snapshot]:
        """The current snapshot WITHOUT loading, even when expired —
        the settle poller's read (reconcile/pending.py): local writes
        are upserted write-through so the peek is exact for them, and
        the scheduler thread must never dispatch an O(N) scan."""
        with self._lock:
            if self._entries is None:
                return None
            if self._list_cache is None:
                self._list_cache = list(self._entries.values())
            return self._list_cache

    def invalidate(self) -> None:
        """External/unknown change: drop the snapshot, and poison any
        in-flight load so its result is returned but not stored."""
        with self._lock:
            self._entries = None
            self._by_tag = {}
            self._list_cache = None
            self._expires = 0.0
            if self._journal is not None:
                self._journal.append(("invalidate", None))

    def upsert(self, accelerator: Accelerator, tags: list[Tag]) -> None:
        """Fold a local create/update into the snapshot instead of
        discarding it.  During creation storms every item writes; a
        blanket invalidate would force a full O(N) rescan per write,
        making convergence O(N^2) AWS calls.  The writer knows exactly
        the (accelerator, tags) it wrote, so the snapshot can absorb
        it and stay warm.  Expiry is left unchanged: entries from the
        original load still refresh within the TTL, so cross-process
        staleness bounds are unaffected.  During an in-flight load the
        write is also journaled so the loaded snapshot cannot miss it."""
        entry = (accelerator, list(tags))
        with self._lock:
            if self._journal is not None:
                self._journal.append(("upsert", entry))
            if self._entries is not None:
                old = self._entries.get(accelerator.accelerator_arn)
                if old is not None:
                    self._index_discard(accelerator.accelerator_arn, old[1])
                self._entries[accelerator.accelerator_arn] = entry
                self._index_add(accelerator.accelerator_arn, entry[1])
                self._list_cache = None

    def remove(self, accelerator_arn: str) -> None:
        """Drop a locally deleted accelerator from the snapshot (same
        rationale and journal semantics as ``upsert``)."""
        with self._lock:
            if self._journal is not None:
                self._journal.append(("remove", accelerator_arn))
            if self._entries is not None:
                old = self._entries.pop(accelerator_arn, None)
                if old is not None:
                    self._index_discard(accelerator_arn, old[1])
                self._list_cache = None


# ---------------------------------------------------------------------------
# the coalesced verification read plane (ISSUE 2)
# ---------------------------------------------------------------------------


class _TopologyEntry:
    """Per-accelerator chain state.  ``listener``/``endpoint_group``
    are the write-through-maintained data; ``verified_expires`` is the
    tick-scope window within which the chain counts as verified
    against AWS; ``full_expires`` bounds how long the write-through
    listener identity is trusted before a full relist (the detection
    bound for out-of-band listener *mutation*/addition — deletion is
    caught every verify, see ``AcceleratorTopologyCache``)."""

    __slots__ = (
        "listener", "endpoint_group", "verified_expires", "full_expires",
        "load_event", "journal",
    )

    def __init__(self):
        self.listener: Optional[Listener] = None
        self.endpoint_group: Optional[EndpointGroup] = None
        self.verified_expires = 0.0
        self.full_expires = 0.0
        self.load_event: Optional[threading.Event] = None
        self.journal: Optional[list] = None


class AcceleratorTopologyCache:
    """Per-accelerator (listener, endpoint group) chains for the drift
    verify path.

    The uncoalesced verify pays three GA reads per object per tick
    (ListListeners + ListEndpointGroups + ListTagsForResource).  This
    cache gets a converged tick down to ONE read per accelerator:

    - tags come from the shared discovery snapshot (the same data the
      tag-scan ownership match already read — re-listing them live
      bought nothing but quota spend);
    - the listener identity is write-through from the driver's own
      mutate chains (``upsert_listener``), so a cheap verify only has
      to confirm the chain tail: ONE ``ListEndpointGroups(listener)``
      call proves the listener still exists (GA raises
      ListenerNotFound for a deleted parent — and GA cannot delete a
      listener that still has endpoint groups, so a live endpoint
      group implies a live listener) AND returns the endpoint set for
      membership/weight drift checks.

    Freshness contract (tick-scoped):

    - ``verify_ttl`` is the verification dedup window — one cheap
      verify per accelerator per tick; it must sit well under the
      drift period (production periods are >= 300 s, default here
      15 s).  Writes REFRESH DATA but never mark a chain verified:
      verification means an actual AWS read.
    - ``full_ttl`` bounds trust in the write-through listener object:
      past it, the next load is a full relist (ListListeners +
      ListEndpointGroups), which also catches out-of-band listener
      port/protocol edits and extra listeners.
    - any not-found on the verify read falls back to a full load in
      the same flight; mismatch paths in the driver invalidate.

    Loads are single-flight PER KEY with the same write-journal fold
    as ``DiscoveryCache``: a write-through landing mid-load repairs
    the loaded chain, an invalidate/remove poisons the store.
    """

    def __init__(
        self,
        verify_ttl: float = 15.0,
        full_ttl: float = 900.0,
        clock: Optional[Callable[[], float]] = None,
    ):
        self._verify_ttl = verify_ttl
        self._full_ttl = full_ttl
        self._clock = clock or clockseam.monotonic
        self._lock = threading.Lock()
        self._entries: dict[str, _TopologyEntry] = {}
        self.hits = 0       # served from the verified window
        self.verifies = 0   # cheap single-read verifies
        self.misses = 0     # full relists
        self.waits = 0      # callers parked behind another's load

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "verifies": self.verifies,
                "misses": self.misses,
                "waits": self.waits,
                "entries": len(self._entries),
            }

    def chain(
        self,
        arn: str,
        full_loader: Callable[[str], tuple[Listener, Optional[EndpointGroup]]],
        verify_loader: Callable[[Listener], Optional[EndpointGroup]],
    ) -> tuple[Listener, Optional[EndpointGroup]]:
        """The verified (listener, endpoint_group) chain for ``arn``.

        ``full_loader(arn)`` is the 2-read relist (raises
        ListenerNotFound when the accelerator has no listener — the
        caller's create-if-missing path); ``verify_loader(listener)``
        is the 1-read tail check returning the endpoint group (or
        None) and raising ListenerNotFound when the cached listener is
        gone, which falls back to a full load in the same flight."""
        while True:
            with self._lock:
                entry = self._entries.get(arn)
                now = self._clock()
                if entry is not None and entry.load_event is None:
                    if entry.listener is not None and now < entry.verified_expires:
                        self.hits += 1
                        return entry.listener, entry.endpoint_group
                if entry is not None and entry.load_event is not None:
                    event = entry.load_event
                    self.waits += 1
                else:
                    if entry is None:
                        entry = self._entries[arn] = _TopologyEntry()
                    entry.load_event = event = threading.Event()
                    entry.journal = []
                    cheap = entry.listener is not None and now < entry.full_expires
                    cached_listener = entry.listener
                    break
            event.wait()

        full = not cheap
        try:
            if cheap:
                self.verifies += 1
                try:
                    listener = cached_listener
                    endpoint_group = verify_loader(cached_listener)
                except ListenerNotFoundException:
                    # the write-through listener vanished out-of-band:
                    # relist in the same flight (it may have been
                    # recreated with a new arn by another actor)
                    full = True
            if full:
                self.misses += 1
                listener, endpoint_group = full_loader(arn)
        except BaseException as err:
            with self._lock:
                entry.load_event = None
                entry.journal = None
                # no listener at all (accelerator mid-create, chain
                # torn down, or the cached identity confirmed dead):
                # drop the entry so the caller's create path re-seeds
                # it via write-through instead of re-verifying a ghost
                if self._entries.get(arn) is entry and (
                    entry.listener is None
                    or isinstance(err, ListenerNotFoundException)
                ):
                    del self._entries[arn]
            event.set()
            raise

        with self._lock:
            journal = entry.journal or []
            entry.load_event = None
            entry.journal = None
            discard = False
            for op, payload in journal:
                if op in ("invalidate", "remove"):
                    discard = True
                elif op == "listener":
                    listener = payload
                elif op == "endpoint_group":
                    endpoint_group = payload
            if discard:
                if self._entries.get(arn) is entry:
                    del self._entries[arn]
            else:
                now = self._clock()
                entry.listener = listener
                entry.endpoint_group = endpoint_group
                entry.verified_expires = now + self._verify_ttl
                if full:
                    entry.full_expires = now + self._full_ttl
        event.set()
        return listener, endpoint_group

    # -- write-through from the driver's mutate chains ------------------
    def upsert_listener(self, arn: str, listener: Listener) -> None:
        """Fold a local listener create/update in.  A fresh entry is
        seeded with a full-trust window (the writer just created the
        chain, so the topology is known exactly) but NOT marked
        verified — drift verification means an actual AWS read, never
        trusting our own write."""
        with self._lock:
            entry = self._entries.get(arn)
            if entry is None:
                entry = self._entries[arn] = _TopologyEntry()
                entry.full_expires = self._clock() + self._full_ttl
            if entry.journal is not None:
                entry.journal.append(("listener", listener))
            entry.listener = listener

    def upsert_endpoint_group(self, arn: str, endpoint_group: EndpointGroup) -> None:
        with self._lock:
            entry = self._entries.get(arn)
            if entry is None:
                return  # no chain context to attach to
            if entry.journal is not None:
                entry.journal.append(("endpoint_group", endpoint_group))
            entry.endpoint_group = endpoint_group

    def invalidate(self, arn: str) -> None:
        """External/unknown change to this chain: drop it, and poison
        any in-flight load so its result is returned but not stored."""
        with self._lock:
            entry = self._entries.get(arn)
            if entry is None:
                return
            if entry.journal is not None:
                entry.journal.append(("invalidate", None))
            else:
                del self._entries[arn]

    def invalidate_all(self) -> None:
        """Drop every cached chain (sharding reshard: the adopted
        keyspace was written by ANOTHER process, so every local
        snapshot is suspect)."""
        with self._lock:
            for arn, entry in list(self._entries.items()):
                if entry.journal is not None:
                    entry.journal.append(("invalidate", None))
                else:
                    del self._entries[arn]

    def remove(self, arn: str) -> None:
        """The accelerator was deleted locally (same journal semantics
        as ``invalidate``; kept separate for intent at call sites)."""
        self.invalidate(arn)

    def invalidate_endpoint_group(self, endpoint_group_arn: str) -> None:
        """An endpoint-group mutation landed by eg arn (the
        EndpointGroupBinding paths address groups directly): expire
        the verification window of whichever chain holds it so the
        next read re-verifies instead of serving the stale endpoint
        set.  O(entries) scan — in-memory, and eg mutates are orders
        rarer than reads."""
        with self._lock:
            for entry in self._entries.values():
                eg = entry.endpoint_group
                if eg is not None and eg.endpoint_group_arn == endpoint_group_arn:
                    entry.verified_expires = 0.0


def _wire_record_name(name: str) -> str:
    """Route53 returns names dot-terminated with ``*`` escaped as
    ``\\052``; snapshot entries must look like API responses so the
    driver's matching helpers work unchanged.  Idempotent."""
    if not name.endswith("."):
        name += "."
    return name if "\\052" in name else name.replace("*", "\\052", 1)


def _wire_record(record: ResourceRecordSet) -> ResourceRecordSet:
    """A normalized copy of a submitted record set, shaped like the
    service would return it (wire name, dot-terminated alias target)."""
    from .types import AliasTarget, ResourceRecord

    alias = record.alias_target
    if alias is not None:
        dns = alias.dns_name if alias.dns_name.endswith(".") else alias.dns_name + "."
        alias = AliasTarget(
            dns_name=dns,
            evaluate_target_health=alias.evaluate_target_health,
            hosted_zone_id=alias.hosted_zone_id,
        )
    return ResourceRecordSet(
        name=_wire_record_name(record.name),
        type=record.type,
        ttl=record.ttl,
        resource_records=[ResourceRecord(r.value) for r in record.resource_records],
        alias_target=alias,
    )


class RecordSetCache:
    """Per-hosted-zone record-set snapshots for the Route53 verify and
    cleanup paths.

    Hostnames cluster onto a handful of shared zones, so the
    per-object ``ListResourceRecordSets`` drain was the single biggest
    Route53 read family per drift tick (1,100 calls against ~10 zones
    in the bench fleet).  One snapshot per zone per tick window
    collapses that to one list per zone.

    Freshness: tick-scoped TTL (well under the drift period), plus the
    driver folds every change batch it successfully commits back into
    the snapshot (``apply_changes``) so a reconcile never acts on its
    own stale write, and invalidates the zone on InvalidChangeBatch /
    NoSuchHostedZone — the signatures of a snapshot that lied.  A
    stale-positive (record actually deleted after the load) is caught
    on the next tick's reload; a stale-negative CREATE fails loudly at
    AWS, invalidates, and the backoff retry re-reads — the same repair
    shape ``HostedZoneCache`` uses.

    Loads are single-flight per zone with the DiscoveryCache journal
    fold: changes applied while a load is in flight are replayed onto
    the loaded snapshot before it is stored."""

    def __init__(
        self,
        ttl: float = 15.0,
        clock: Optional[Callable[[], float]] = None,
        degraded: Optional[Callable[[], bool]] = None,
    ):
        self._ttl = ttl
        self._clock = clock or clockseam.monotonic
        # health-plane hook (factory wires it to "is the Route53
        # circuit open"): serve expired zone snapshots stale while the
        # service is down instead of dispatching doomed reloads —
        # degraded drift verification with bounded staleness
        self._degraded = degraded
        self._lock = threading.Lock()
        # zone id -> (snapshot, expires) / in-flight (event, journal)
        self._snapshots: dict[str, tuple[list[ResourceRecordSet], float]] = {}
        self._loading: dict[str, tuple[threading.Event, list]] = {}
        self.hits = 0
        self.misses = 0
        self.waits = 0
        self.stale_serves = 0  # expired snapshots served while degraded

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "waits": self.waits,
                "zones": len(self._snapshots),
                "stale_serves": self.stale_serves,
            }

    def get(
        self, zone_id: str, loader: Callable[[], list[ResourceRecordSet]]
    ) -> list[ResourceRecordSet]:
        while True:
            with self._lock:
                cached = self._snapshots.get(zone_id)
                if cached is not None and self._clock() < cached[1]:
                    self.hits += 1
                    return cached[0]
                if (
                    cached is not None
                    and self._degraded is not None
                    and self._degraded()
                ):
                    self.stale_serves += 1
                    return cached[0]
                in_flight = self._loading.get(zone_id)
                if in_flight is None:
                    event = threading.Event()
                    self._loading[zone_id] = (event, [])
                    self.misses += 1
                    break
                event = in_flight[0]
                self.waits += 1
            event.wait()
        try:
            snapshot = list(loader())
        except BaseException:
            with self._lock:
                self._loading.pop(zone_id, None)
            event.set()
            raise
        with self._lock:
            _, journal = self._loading.pop(zone_id, (None, []))
            discard = False
            for op, payload in journal:
                if op == "invalidate":
                    discard = True
                else:  # ("changes", list[Change])
                    snapshot = self._fold_changes(snapshot, payload)
            if not discard:
                self._snapshots[zone_id] = (snapshot, self._clock() + self._ttl)
        event.set()
        return snapshot

    @staticmethod
    def _fold_changes(snapshot: list[ResourceRecordSet], changes: list) -> list:
        """Replay a committed change batch onto a snapshot, returning
        a NEW list (snapshots are shared, never mutated in place)."""
        result = list(snapshot)
        for change in changes:
            record = _wire_record(change.record_set)
            key = (record.name, record.type)
            result = [r for r in result if (r.name, r.type) != key]
            if change.action != CHANGE_ACTION_DELETE:
                result.append(record)
        return result

    def apply_changes(self, zone_id: str, changes: list) -> None:
        """Fold a change batch this process successfully committed into
        the zone snapshot (write-through), and journal it into any
        in-flight load so the loaded snapshot cannot miss it."""
        with self._lock:
            in_flight = self._loading.get(zone_id)
            if in_flight is not None:
                in_flight[1].append(("changes", changes))
            cached = self._snapshots.get(zone_id)
            if cached is not None:
                self._snapshots[zone_id] = (
                    self._fold_changes(cached[0], changes), cached[1]
                )

    def invalidate(self, zone_id: str) -> None:
        with self._lock:
            self._snapshots.pop(zone_id, None)
            in_flight = self._loading.get(zone_id)
            if in_flight is not None:
                in_flight[1].append(("invalidate", None))

    def invalidate_all(self) -> None:
        with self._lock:
            self._snapshots.clear()
            for _, journal in self._loading.values():
                journal.append(("invalidate", None))


class _LBBatch:
    __slots__ = ("names", "event", "results", "error", "closed", "split", "settled")

    def __init__(self):
        self.names: set[str] = set()
        self.event = threading.Event()
        self.results: dict[str, LoadBalancer] = {}
        self.error: Optional[BaseException] = None
        self.closed = False
        # real ELBv2 fails the WHOLE call when any requested name is
        # missing; a split batch degrades members to single fetches
        self.split = False
        # set once the leader recorded an outcome; a wake-up without it
        # (leader died mid-fetch) degrades joiners to single fetches
        self.settled = False


class LoadBalancerCoalescer:
    """Batches concurrent single-name ``DescribeLoadBalancers`` lookups
    into multi-name wire calls behind a short TTL cache.

    Every reconcile of every controller starts with one LB lookup, so
    a drift tick fires ~N concurrent single-name describes.  The wire
    protocol already takes up to 20 names per call
    (``Names.member.N``, real_backend.py) — the first misser of a
    window becomes the batch leader, waits ``batch_window`` for
    co-missers, and issues ONE describe for the gathered names; the
    TTL then shares each result across the controllers that look up
    the same LB in the same tick (GA + EndpointGroupBinding both
    resolve ``benchNNNN``-style names).

    Freshness: the TTL is tick-scoped (LB state/dns drift is re-read
    every round); results are never negatively cached — a name absent
    from a response returns None to the caller (the driver raises its
    usual LoadBalancerNotFound) and the next lookup goes to the wire.
    Real ELBv2 fails an entire multi-name call when ANY name is
    unknown, so a LoadBalancerNotFound on a multi-name batch degrades
    that batch to per-name fetches instead of poisoning 19 healthy
    lookups."""

    # DescribeLoadBalancers accepts at most 20 names per call
    MAX_BATCH = 20

    def __init__(
        self,
        ttl: float = 15.0,
        batch_window: float = 0.01,
        clock: Optional[Callable[[], float]] = None,
        sleep: Optional[Callable[[float], None]] = None,
    ):
        self._ttl = ttl
        self._batch_window = batch_window
        self._clock = clock or clockseam.monotonic
        self._sleep = sleep or clockseam.sleep
        self._lock = threading.Lock()
        self._cache: dict[str, tuple[LoadBalancer, float]] = {}
        self._forming: Optional[_LBBatch] = None
        self.hits = 0
        self.misses = 0
        self.waits = 0          # joiners that parked on a leader's batch
        self.batches = 0        # wire calls issued (incl. split singles)
        self.batch_sizes: dict[int, int] = {}  # size -> count

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "waits": self.waits,
                "batches": self.batches,
                "batch_sizes": dict(sorted(self.batch_sizes.items())),
            }

    def _record_batch(self, size: int) -> None:
        self.batches += 1
        self.batch_sizes[size] = self.batch_sizes.get(size, 0) + 1

    def _store(self, lbs: list[LoadBalancer]) -> None:
        expires = self._clock() + self._ttl
        for lb in lbs:
            self._cache[lb.load_balancer_name] = (lb, expires)

    def get(
        self, name: str, fetch: Callable[[list[str]], list[LoadBalancer]]
    ) -> Optional[LoadBalancer]:
        """The load balancer named ``name``, or None if AWS does not
        know it.  ``fetch(names)`` is the raw multi-name describe."""
        with self._lock:
            cached = self._cache.get(name)
            if cached is not None and self._clock() < cached[1]:
                self.hits += 1
                return cached[0]
            self.misses += 1
            batch = self._forming
            if (
                batch is not None
                and not batch.closed
                and len(batch.names | {name}) <= self.MAX_BATCH
            ):
                batch.names.add(name)
                leader = False
                self.waits += 1
            else:
                batch = _LBBatch()
                batch.names.add(name)
                self._forming = batch
                leader = True

        if leader:
            try:
                if self._batch_window > 0:
                    self._sleep(self._batch_window)  # gather co-missers
                with self._lock:
                    batch.closed = True
                    if self._forming is batch:
                        self._forming = None
                    names = sorted(batch.names)
                try:
                    found = fetch(names)
                except Exception as err:
                    if len(names) > 1 and _is_lb_not_found(err):
                        # real-AWS all-or-nothing semantics: one unknown
                        # name failed the whole call — degrade to singles
                        batch.split = True
                    else:
                        batch.error = err
                else:
                    with self._lock:
                        self._store(found)
                        self._record_batch(len(names))
                    batch.results = {lb.load_balancer_name: lb for lb in found}
                batch.settled = True
            finally:
                # even a BaseException mid-fetch must wake the joiners
                # (an unset event would park them forever); an unsettled
                # wake-up degrades them to their own single fetches
                if not batch.settled:
                    batch.split = True
                batch.event.set()
        else:
            batch.event.wait()

        if batch.error is not None:
            raise batch.error
        if batch.split:
            found = fetch([name])  # may raise not-found: caller's contract
            with self._lock:
                self._store(found)
                self._record_batch(1)
            for lb in found:
                if lb.load_balancer_name == name:
                    return lb
            return None
        return batch.results.get(name)


def _is_lb_not_found(err: BaseException) -> bool:
    code = getattr(err, "code", "")
    return isinstance(code, str) and "LoadBalancerNotFound" in code
